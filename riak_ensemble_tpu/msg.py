"""Quorum fan-out + vote collection (host side).

Mirrors ``src/riak_ensemble_msg.erl``.  Two paths:

- **Non-blocking** (:func:`send_all` / :func:`handle_reply`): used from
  inside the peer FSM (probe/prepare/prelead/commit).  Replies arrive
  as ``('reply', reqid, peer, value)`` events on the peer; once quorum
  is met the peer receives ``('quorum_met', valid_replies)``, on nack
  or timeout ``('quorum_timeout', valid_replies)``
  (msg.erl:85-97,336-366).
- **Blocking** (:func:`blocking_send_all`): spawns a collector actor
  (the analog of the collector process, msg.erl:196-237) and returns a
  :class:`~riak_ensemble_tpu.runtime.Future` resolving to
  ``('quorum_met', valid_replies)`` or ``('timeout', replies)``; K/V
  worker tasks ``yield`` it (= ``wait_for_quorum``, msg.erl:319-332).

``peers`` everywhere is a list of ``(peer_id, addr_or_None)``; a None
address is an offline peer and gets a synthesized nack
(msg.erl:132-142).  Requests carry a ``From = (dst_name, reqid)``;
responders answer with :func:`reply`, so replies route to whichever
actor issued the fan-out (peer or collector) — the reference's
"reply directly to the caller" optimization falls out for free.

The per-call quorum predicate is
:func:`riak_ensemble_tpu.ops.quorum.quorum_met`; the batched engine
runs the same predicate as the jitted kernel.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence, Tuple

from riak_ensemble_tpu.ops.quorum import MET, NACK, UNDECIDED, quorum_met, \
    find_valid
from riak_ensemble_tpu.runtime import Actor, Future, Runtime, Timer

_reqids = itertools.count(1)

#: wire sentinel for negative votes
NACK_REPLY = "nack"


def make_from(owner_name: Any, reqid: int) -> Tuple[Any, int]:
    return (owner_name, reqid)


def reply(actor: Actor, from_: Tuple[Any, int], peer_id: Any,
          value: Any) -> None:
    """Route a reply back to the fan-out owner (msg.erl:180-182)."""
    owner, reqid = from_
    actor.send(owner, ("reply", reqid, peer_id, value))


@dataclass
class MsgState:
    """Non-blocking vote-collection state embedded in the peer FSM
    (``#msgstate{}``, msg.erl:44-49)."""

    id: Any
    awaiting: Optional[int] = None
    timer: Optional[Timer] = None
    required: str = "quorum"
    views: Sequence[Sequence[Any]] = ()
    replies: List[Tuple[Any, Any]] = field(default_factory=list)


def _fan_out(actor: Actor, owner_name: Any, msg: Tuple, reqid: int,
             peers, self_id) -> None:
    from_ = make_from(owner_name, reqid)
    request = msg + (from_,)
    for peer_id, addr in peers:
        if peer_id == self_id:
            continue
        if addr is None:
            # Offline peer: synthesized nack (msg.erl:134-138).
            actor.runtime.post(owner_name, ("reply", reqid, peer_id,
                                            NACK_REPLY))
        else:
            actor.send(addr, request)


def send_all(actor: Actor, msg: Tuple, self_id: Any, peers, views,
             required: str = "quorum") -> MsgState:
    """Fan out msg; replies flow back into the owning peer FSM.

    Returns the MsgState the peer must keep in its state and thread
    through :func:`handle_reply`.
    """
    if [p for p, _ in peers] == [self_id]:
        # Singleton ensemble: trivially met (msg.erl:86-89).
        actor.runtime.post(actor.name, ("quorum_met", []))
        return MsgState(id=self_id)
    reqid = next(_reqids)
    _fan_out(actor, actor.name, msg, reqid, peers, self_id)
    timer = actor.send_after(actor.config.quorum(), ("quorum_timeout_tick",
                                                     reqid))
    return MsgState(id=self_id, awaiting=reqid, timer=timer,
                    required=required, views=views)


def cast_all(actor: Actor, msg: Tuple, self_id: Any, peers) -> None:
    """Fire-and-forget to all peers but self (msg.erl:101-106)."""
    for peer_id, addr in peers:
        if peer_id != self_id and addr is not None:
            actor.send(addr, msg)


def handle_reply(actor: Actor, reqid: int, peer: Any, value: Any,
                 mstate: MsgState) -> MsgState:
    """Accumulate one reply (msg.erl:336-359)."""
    if reqid != mstate.awaiting:
        return mstate
    mstate.replies.append((peer, value))
    met = quorum_met(mstate.replies, mstate.id, mstate.views, mstate.required)
    if met == MET:
        if mstate.timer:
            mstate.timer.cancel()
        valid, _ = find_valid(mstate.replies)
        actor.runtime.post(actor.name, ("quorum_met", valid))
        return MsgState(id=mstate.id)
    if met == NACK:
        if mstate.timer:
            mstate.timer.cancel()
        return quorum_timeout(actor, mstate)
    return mstate


def quorum_timeout(actor: Actor, mstate: MsgState) -> MsgState:
    """Report failure with whatever valid replies arrived
    (msg.erl:361-366)."""
    valid, _ = find_valid(mstate.replies)
    actor.runtime.post(actor.name, ("timeout", valid))
    return MsgState(id=mstate.id)


# ---------------------------------------------------------------------------
# Wire-safe cross-node sync call (the gen_server:call-over-dist
# analog).  Messages carry only names and reqids — no Futures — so the
# same protocol runs on the simulator and the TCP transport.

_xproxy_ids = itertools.count(1)


class _XProxy(Actor):
    def __init__(self, runtime: Runtime, node, fut: Future,
                 ref: int) -> None:
        super().__init__(runtime, ("xproxy", node, next(_xproxy_ids)),
                         node)
        self.fut = fut
        self.ref = ref

    def handle(self, msg: Tuple) -> None:
        if msg[0] == "xreply" and msg[1] == self.ref:
            self.fut.resolve(msg[2])
            self.stop()


_xcall_refs = itertools.count(1)


def xcall(actor: Actor, dst_name: Any, inner: Tuple,
          timeout: float) -> Future:
    """Sync-call `dst_name` (a peer or tree actor on any node) with
    `inner`; resolves to the reply or ``"timeout"``.  The callee
    handles ``("xcall", (proxy_name, ref), inner)`` and replies
    ``("xreply", ref, value)`` to the proxy."""
    fut = Future()
    ref = next(_xcall_refs)
    proxy = _XProxy(actor.runtime, actor.node, fut, ref)
    actor.send(dst_name, ("xcall", (proxy.name, ref), inner))
    out = actor.runtime.with_timeout(fut, timeout)

    def cleanup(_v):
        if actor.runtime.whereis(proxy.name) is not None:
            actor.runtime.stop_actor(proxy.name)

    out.add_waiter(cleanup)
    return out


def handle_xcall(actor: Actor, from_: Tuple, fut: Future) -> Future:
    """Wire a local Future so its resolution answers an xcall."""
    owner, ref = from_
    fut.add_waiter(lambda v: actor.send(owner, ("xreply", ref, v)))
    return fut


# Blocking path: collector actor + future


class _Collector(Actor):
    """Stand-in for the collector process (msg.erl:212-317).

    Resolution rules (matching check_enough/try_collect_all):
      - quorum met, required != all_or_quorum  -> ('quorum_met', valid)
      - quorum met, all_or_quorum              -> keep collecting up to
        notfound_read_delay for *all* replies; resolve ('quorum_met',
        valid-so-far) on all-met, first nack, or delay expiry.
      - nack                                   -> ('timeout', replies)
      - idle for one quorum interval           -> ('timeout', replies)
    """

    def __init__(self, runtime: Runtime, name, node, config, self_id, views,
                 required, extra, future: Future) -> None:
        super().__init__(runtime, name, node)
        self.config = config
        self.self_id = self_id
        self.views = views
        self.required = required
        self.extra = extra
        self.future = future
        self.replies: List[Tuple[Any, Any]] = []
        self.reqid: Optional[int] = None
        self.phase = "collect"  # or "collect_all"
        self.lazy = False
        self.idle_timer: Optional[Timer] = None
        self.all_timer: Optional[Timer] = None

    def _arm_idle(self) -> None:
        if self.idle_timer:
            self.idle_timer.cancel()
        self.idle_timer = self.send_after(self.config.quorum(),
                                          ("idle_timeout",))

    def _finish(self, result: Tuple) -> None:
        self.future.resolve(result)
        self.stop()

    def on_stop(self) -> None:
        for t in (self.idle_timer, self.all_timer):
            if t:
                t.cancel()

    def handle(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "reply":
            _, reqid, peer, value = msg
            if reqid != self.reqid:
                return
            self.replies.append((peer, value))
            if not self.lazy:
                self._check()
        elif kind == "ask":
            # Lazy mode: the caller starts waiting NOW (the reference's
            # {waiting, From, Ref} → check_enough handshake,
            # msg.erl:232-234,258-280).  Everything heard so far counts
            # — this is how ping_quorum/count_quorum see more than a
            # bare majority.
            met = quorum_met(self.replies, self.self_id, self.views,
                             self.required, extra=self.extra)
            if met == MET:
                valid, _ = find_valid(self.replies)
                self._finish(("quorum_met", valid))
            else:
                self._finish(("timeout", list(self.replies)))
        elif kind == "idle_timeout":
            if self.phase == "collect":
                self._finish(("timeout", list(self.replies)))
        elif kind == "all_timeout":
            valid, _ = find_valid(self.replies)
            self._finish(("quorum_met", valid))

    def _check(self) -> None:
        met = quorum_met(self.replies, self.self_id, self.views,
                         self.required, extra=self.extra)
        if self.phase == "collect_all":
            # Waiting for all after quorum (try_collect_all_impl).
            all_met = quorum_met(self.replies, self.self_id, self.views,
                                 "all")
            if all_met != UNDECIDED:
                valid, _ = find_valid(self.replies)
                self._finish(("quorum_met", valid))
            return
        self._arm_idle()
        if met == MET:
            if self.required == "all_or_quorum":
                self.phase = "collect_all"
                self.all_timer = self.send_after(
                    self.config.notfound_read_delay, ("all_timeout",))
            else:
                valid, _ = find_valid(self.replies)
                self._finish(("quorum_met", valid))
        elif met == NACK:
            self._finish(("timeout", list(self.replies)))


_collector_ids = itertools.count(1)


def blocking_send_all(actor: Actor, msg: Tuple, self_id: Any, peers, views,
                      required: str = "quorum",
                      extra: Optional[Callable] = None) -> Future:
    """Fan out and return a Future for a worker task to yield on
    (msg.erl:185-210 + wait_for_quorum:319-332)."""
    future = Future()
    others = [(p, a) for p, a in peers if p != self_id]
    if not others:
        future.resolve(("quorum_met", []))
        return future
    # Node-scoped name so cross-node replies can route back to the
    # collector over a real transport.
    name = ("collector", actor.node, next(_collector_ids))
    collector = _Collector(actor.runtime, name, actor.node, actor.config,
                           self_id, views, required, extra, future)
    reqid = next(_reqids)
    collector.reqid = reqid
    _fan_out(collector, name, msg, reqid, peers, self_id)
    collector._arm_idle()
    return future


def lazy_send_all(actor: Actor, msg: Tuple, self_id: Any, peers, views,
                  required: str = "quorum"):
    """Fan out but keep collecting until asked: the caller later posts
    ``("ask",)`` to the returned collector name and the future resolves
    against everything heard by then (the reference's parent-waiting
    collector protocol, used by ping_quorum).  Returns
    (future, collector_name_or_None)."""
    future = Future()
    others = [(p, a) for p, a in peers if p != self_id]
    if not others:
        future.resolve(("quorum_met", []))
        return future, None
    name = ("collector", actor.node, next(_collector_ids))
    collector = _Collector(actor.runtime, name, actor.node, actor.config,
                           self_id, views, required, None, future)
    collector.lazy = True
    reqid = next(_reqids)
    collector.reqid = reqid
    _fan_out(collector, name, msg, reqid, peers, self_id)
    # A lazy collector waits indefinitely for its owner's ("ask",) —
    # tie its lifetime to the owner (the Erlang collector dies with its
    # parent): if the owning actor stops before asking, stop the
    # collector too instead of leaking it in the registry forever.
    owner = actor.name

    def _owner_down(_name, cname=name):
        c = collector.runtime.whereis(cname)
        if c is not None and c.lazy:
            future.resolve(("timeout", list(collector.replies)))
            collector.runtime.stop_actor(cname)

    actor.runtime.monitor(owner, _owner_down)
    # The collector always stops eventually (ask path or owner-down
    # path); drop the owner monitor with it so a long-lived owner
    # doesn't accumulate one dead closure per lazy_send_all call.
    actor.runtime.monitor(
        name, lambda _n: actor.runtime.demonitor(owner, _owner_down))
    return future, name
