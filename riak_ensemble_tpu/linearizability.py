"""Linearizability checking harness — the sc.erl analog.

The reference's real consistency test (``test/sc.erl``, 1071 LoC EQC
statem) drives random concurrent kget/kover/kput_once/kupdate/kdelete
from N workers against a live cluster, injects partitions/heals, and
checks postconditions: every acked write is observed, reads return a
plausible value, and acked data is never lost ("Data loss!" check,
sc.erl:835-880; read postcondition sc.erl:112-148).

This module re-creates that as a deterministic virtual-time harness:

- :class:`KeyModel` — per-key set of *plausible current values*.  An
  acked write fixes the state to its value (plus any still-in-flight
  concurrent writes, which may legally serialize after it).  A
  timed-out write MAY have applied (now or once its queued put reaches
  quorum), so its value joins the plausible set.  A CAS-failed write
  did not apply.  A successful read both validates against and
  re-pins the plausible set — that is the linearizable-read property.
- :class:`Workload` — N sequential workers (runtime tasks) issuing a
  random op mix through the real router/client path, a nemesis
  schedule (peer suspensions; node partitions healed before checking),
  and the final quiesced read-back verifying no acked write was lost.

Any violation raises :class:`Violation` with the offending history
tail for debugging.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from riak_ensemble_tpu import router as routerlib
from riak_ensemble_tpu.peer import do_kput_once, do_kupdate
from riak_ensemble_tpu.runtime import Runtime
from riak_ensemble_tpu.types import NOTFOUND, Obj

_op_ids = itertools.count(1)


class Violation(AssertionError):
    pass


def _val(v: Any) -> Any:
    """Hashable value token (NOTFOUND is a singleton already)."""
    return v


@dataclass
class _Inflight:
    op_id: int
    value: Any


@dataclass
class KeyModel:
    """Plausible-current-value tracking for one key (the sc.erl
    possible-values postcondition model).

    Three tiers of uncertainty:
    - ``possible`` — values the key may hold given every *completed*
      op (acked writes pin it, plus still-in-flight concurrent writes
      that may serialize after the pin);
    - ``inflight`` — invoked-but-unresponded writes;
    - ``maybe`` — writes that TIMED OUT.  An op with no response has
      no linearization upper bound: it may take effect at ANY later
      point (e.g. a delete queued behind a suspended leader applies
      after that peer wins a later election — observed under the
      freeze/partition nemesis), so these stay plausible indefinitely.
      True data loss is still caught whenever the key has no
      unresolved timeout that could explain the observation.
    """

    key: Any
    possible: Set[Any] = field(default_factory=lambda: {NOTFOUND})
    inflight: Dict[int, _Inflight] = field(default_factory=dict)
    maybe: Set[Any] = field(default_factory=set)
    history: List[Tuple] = field(default_factory=list)

    def _inflight_values(self, exclude: Optional[int] = None) -> Set[Any]:
        return {w.value for w in self.inflight.values()
                if w.op_id != exclude}

    def invoke_write(self, value: Any) -> int:
        op_id = next(_op_ids)
        self.inflight[op_id] = _Inflight(op_id, _val(value))
        self.history.append(("invoke", op_id, value))
        return op_id

    def ack_write(self, op_id: int) -> None:
        w = self.inflight.pop(op_id)
        # Linearization point inside the op window: state is now w's
        # value; in-flight concurrent writes may serialize after it.
        # (An earlier-completed read's pinned value X stays plausible
        # only if something could make X current after this write —
        # an in-flight or timed-out write of X, which `inflight`/
        # `maybe` already cover.  MODEL ASSUMPTION: read results are
        # fed to ack_read in a serialization-consistent order — a read
        # must not be reported after an overlapping write's ack if it
        # linearized before that write.  Both harness drivers satisfy
        # this: the batched service resolves in device round order and
        # the actor stack serializes same-key ops through one worker.)
        self.possible = {w.value} | self._inflight_values()
        self.history.append(("ack", op_id, w.value))

    def fail_write(self, op_id: int) -> None:
        """CAS precondition failure — op did NOT apply (sc.erl treats
        {error,failed} CAS results as no-ops)."""
        self.inflight.pop(op_id, None)
        self.history.append(("failed", op_id))

    def timeout_write(self, op_id: int) -> None:
        """Outcome unknown: may have applied, or may apply at any
        later point — stays plausible until observed."""
        w = self.inflight.pop(op_id)
        self.maybe.add(w.value)
        self.history.append(("timeout", op_id, w.value))

    def ack_read(self, value: Any) -> None:
        value = _val(value)
        valid = self.possible | self._inflight_values() | self.maybe
        if value not in valid:
            what = ("DATA LOSS (notfound read, but a write must be "
                    "visible)" if value is NOTFOUND else "stale/phantom "
                    "read")
            raise Violation(
                f"{what} of {self.key!r}: returned {value!r}; plausible "
                f"was {valid!r}\nhistory tail: {self.history[-12:]}")
        # A linearizable read pins the state (timed-out writes may
        # still land later, so `maybe` persists).
        self.possible = {value} | self._inflight_values()
        self.history.append(("read", value))


@dataclass
class CounterModel:
    """Convergence model for a key driven ONLY by commutative device
    RMWs (rmw:add / rmw:sub) — the §18 commutative-lane sweeps.

    Per-op sequencing is invisible in the final value, so the whole
    history collapses to one obligation: the final counter must equal
    the int32-wraparound sum of every ACKED operand plus SOME subset
    of the AMBIGUOUS operands (timeouts / ambiguous disconnects — each
    may have applied at most once), starting from the initial value.
    This catches both failure modes the lane could introduce: a
    double-apply (a merged or retried op counted twice — the final
    overshoots every reachable sum) and early-ack loss (an acked op
    missing after a crash/handoff — the final undershoots).

    The reachable-sum set is 2^len(ambiguous); keep ambiguity rare
    (the sweeps inject a handful of kills, not thousands)."""

    key: Any
    initial: int = 0
    acked_sum: int = 0
    n_acked: int = 0
    ambiguous: List[int] = field(default_factory=list)

    def ack(self, operand: int) -> None:
        """An acked rmw:add of ``operand`` (sub acks negated)."""
        from riak_ensemble_tpu import funref
        self.acked_sum = funref.i32(self.acked_sum + int(operand))
        self.n_acked += 1

    def unknown(self, operand: int) -> None:
        """An op whose outcome is ambiguous (timeout, dropped
        mid-ack): it applied once or not at all — never twice."""
        self.ambiguous.append(int(operand))

    def check_final(self, final: int) -> None:
        from riak_ensemble_tpu import funref
        reach = {funref.i32(self.initial + self.acked_sum)}
        for op in self.ambiguous:
            reach |= {funref.i32(v + op) for v in reach}
        if funref.i32(int(final)) not in reach:
            raise Violation(
                f"counter {self.key!r} diverged: final {final!r} not "
                f"reachable from initial {self.initial} + "
                f"{self.n_acked} acked ops (sum {self.acked_sum}) + "
                f"any subset of ambiguous {self.ambiguous!r}")


class Workload:
    """Concurrent random workload + nemesis against one ensemble."""

    OPS = ("kget", "kover", "kput_once", "kupdate", "kdelete")

    def __init__(self, mc, ensemble: Any, n_workers: int = 3,
                 n_keys: int = 4, ops_per_worker: int = 60,
                 op_timeout: float = 8.0, seed: int = 0,
                 nemesis_hold: Tuple[float, float] = (0.3, 1.5),
                 member_churn: bool = False,
                 oneway_partitions: bool = False) -> None:
        import random

        self.mc = mc
        self.runtime: Runtime = mc.runtime
        self.ensemble = ensemble
        self.rng = random.Random(seed)
        self.keys = [f"k{i}" for i in range(n_keys)]
        self.models: Dict[Any, KeyModel] = {k: KeyModel(k)
                                            for k in self.keys}
        self.n_workers = n_workers
        self.ops_per_worker = ops_per_worker
        self.op_timeout = op_timeout
        self.done = 0
        self.nemesis_hold = nemesis_hold
        self.member_churn = member_churn
        #: opt-in ONE-DIRECTIONAL partitions (the sc.erl fault mode
        #: never ported before the fault plane: A→B delivers, B→A
        #: drops — the classic failover killer).  Opt-in so the
        #: pre-existing seeded sweeps keep their exact schedules.
        self.oneway_partitions = oneway_partitions
        self.op_counts: Dict[str, int] = {}
        self.violations: List[Violation] = []

    # -- op plumbing -------------------------------------------------------

    def _sync(self, node, event):
        return routerlib.sync_send_event_fut(
            self.runtime, node, self.ensemble, event, self.op_timeout)

    def _worker(self, widx: int):
        nodes = list(self.mc.managers)
        last_read: Dict[Any, Obj] = {}
        for _ in range(self.ops_per_worker):
            key = self.rng.choice(self.keys)
            node = self.rng.choice(nodes)
            op = self.rng.choice(self.OPS)
            model = self.models[key]
            self.op_counts[op] = self.op_counts.get(op, 0) + 1
            try:
                if op == "kget":
                    result = yield self._sync(node, ("get", key, ()))
                    if isinstance(result, tuple) and result[0] == "ok":
                        last_read[key] = result[1]
                        model.ack_read(result[1].value)
                elif op == "kupdate":
                    cur = last_read.get(key)
                    if cur is None:
                        continue
                    value = f"w{widx}-{self.rng.randrange(10**6)}".encode()
                    op_id = model.invoke_write(value)
                    result = yield self._sync(
                        node, ("put", key, do_kupdate, [cur, value]))
                    self._settle_write(model, op_id, result)
                elif op == "kput_once":
                    value = f"p{widx}-{self.rng.randrange(10**6)}".encode()
                    op_id = model.invoke_write(value)
                    result = yield self._sync(
                        node, ("put", key, do_kput_once, [value]))
                    self._settle_write(model, op_id, result)
                elif op == "kdelete":
                    op_id = model.invoke_write(NOTFOUND)
                    result = yield self._sync(
                        node, ("overwrite", key, NOTFOUND))
                    self._settle_write(model, op_id, result)
                else:  # kover
                    value = f"o{widx}-{self.rng.randrange(10**6)}".encode()
                    op_id = model.invoke_write(value)
                    result = yield self._sync(
                        node, ("overwrite", key, value))
                    self._settle_write(model, op_id, result)
            except Violation as v:
                self.violations.append(v)
                break
            # Think time stretches the workload across many nemesis
            # windows (ops are ~ms in virtual time; without this the
            # whole run fits between two nemesis actions).
            yield self.runtime.sleep(self.rng.uniform(0.05, 0.3))
        self.done += 1

    @staticmethod
    def _is_ok(result) -> bool:
        return isinstance(result, tuple) and result[0] == "ok"

    def _settle_write(self, model: KeyModel, op_id: int, result) -> None:
        if self._is_ok(result):
            model.ack_write(op_id)
        elif result == "failed":
            model.fail_write(op_id)
        else:  # timeout / unavailable: outcome unknown
            model.timeout_write(op_id)

    # -- nemesis -----------------------------------------------------------

    def _member_churn(self, spare):
        """One add→remove membership cycle through the real
        update_members path, concurrent with the workload (the
        replace_members-under-load scenario)."""
        from riak_ensemble_tpu import router as routerlib

        for changes in ((("add", spare),), (("del", spare),)):
            r = yield routerlib.sync_send_event_fut(
                self.runtime, spare.node, self.ensemble,
                ("update_members", changes), 10.0)
            # a failed/raced change is fine — the next cycle retries;
            # what must hold is the workload's consistency
            yield self.runtime.sleep(self.rng.uniform(0.5, 1.5))

    def _nemesis(self, duration: float, partitions: bool):
        members = list(self.mc.mgr(self.mc.node0).get_members(
            self.ensemble)) or []
        nodes = sorted({m.node for m in members})
        spare_n = 0
        end = self.runtime.now + duration
        while self.runtime.now < end and self.done < self.n_workers:
            action = self.rng.random()
            lo, hi = self.nemesis_hold
            if self.member_churn and action < 0.25 and nodes:
                from riak_ensemble_tpu.types import PeerId

                spare = PeerId(1000 + spare_n, self.rng.choice(nodes))
                spare_n += 1
                yield from self._member_churn(spare)
            elif action < 0.5 and members:
                # freeze a random peer (suspend_process analog)
                victim = self.rng.choice(members)
                self.mc.suspend_peer(self.ensemble, victim)
                yield self.runtime.sleep(self.rng.uniform(lo, hi))
                self.mc.resume_peer(self.ensemble, victim)
            elif self.oneway_partitions and partitions \
                    and len(nodes) >= 3 and action < 0.75:
                # ONE-DIRECTIONAL cut (fault plane): the victim
                # either goes deaf (inbound dropped: it keeps
                # sending — acks, votes, heartbeats — but hears
                # nothing) or mute (outbound dropped: it receives
                # the cluster's traffic but every reply vanishes).
                # Either asymmetry must serialize exactly like the
                # symmetric cut: a leader that can reach clients but
                # not its quorum must stop acking.
                victim = self.rng.choice(nodes)
                rest = [n for n in nodes if n != victim]
                if self.rng.random() < 0.5:
                    self.runtime.net.partition_oneway([victim], rest)
                else:
                    self.runtime.net.partition_oneway(rest, [victim])
                yield self.runtime.sleep(self.rng.uniform(lo, 2 * hi))
                self.runtime.net.heal()
            elif partitions and len(nodes) >= 3:
                # cut off a minority node (sc.erl partition_nodes)
                victim = self.rng.choice(nodes)
                rest = [n for n in nodes if n != victim]
                self.runtime.net.partition([victim], rest)
                yield self.runtime.sleep(self.rng.uniform(lo, 2 * hi))
                self.runtime.net.heal()
            yield self.runtime.sleep(self.rng.uniform(0.1, 0.5))
        self.runtime.net.heal()

    # -- run ---------------------------------------------------------------

    def run(self, max_virtual: float = 600.0,
            partitions: bool = True) -> None:
        for w in range(self.n_workers):
            self.runtime.spawn_task(self._worker(w), name=f"sc-worker{w}")
        self.runtime.spawn_task(self._nemesis(max_virtual, partitions),
                                name="sc-nemesis")
        ok = self.runtime.run_until(
            lambda: self.done >= self.n_workers or self.violations,
            max_time=max_virtual, poll=0.5)
        if self.violations:
            raise self.violations[0]
        if not ok:
            raise Violation("workload did not finish in virtual budget")
        self._final_check()

    def _final_check(self) -> None:
        """Quiesced read-back: heal everything, then every key must
        read back a plausible value (no acked write lost)."""
        self.runtime.net.heal()
        self.mc.wait_stable(self.ensemble, max_time=120.0)
        client = self.mc.client(self.mc.node0)
        for key in self.keys:
            model = self.models[key]

            def read_ok(key=key, model=model):
                r = client.kget(self.ensemble, key, timeout=5.0)
                if not self._is_ok(r):
                    return False
                model.ack_read(r[1].value)
                return True
            if not self.runtime.run_until(read_ok, 60.0, poll=0.5):
                raise Violation(f"final read of {key!r} never succeeded")


class ServiceReadWorkload:
    """Read-heavy workload + nemesis for the BATCHED SERVICE's
    lease-protected read fast path (batched_host §9): random
    concurrent kput/kdelete/kget against a
    :class:`~riak_ensemble_tpu.parallel.batched_host.BatchedEnsembleService`
    on the virtual clock, checked against :class:`KeyModel`.

    The nemesis schedule targets exactly the hazards the fast path
    introduces:

    - **lease expiry mid-workload** — virtual-time jumps past the
      lease horizon, so reads race renewal and must fall back to the
      device round rather than serve a lapsed mirror;
    - **leader step-down / re-election** — the current leader's up
      flag drops right before the carrying flush (the election folds
      into the same launch); a later heal re-elects.  Fast reads must
      refuse leaderless/electing rows, and the post-election epoch
      bump must invalidate the vsn mirror rather than hand out stale
      CAS tokens;
    - **skewed-margin clock** — sub-lease jumps that land the clock
      INSIDE the safety margin ``[lease - margin, lease)``, the
      region where a skew-prone implementation would still serve; the
      margin must refuse there.

    Fast reads resolve synchronously at submit — their linearization
    point — so their model events apply immediately; queued ops apply
    in resolution (device-round) order after the drain, preserving
    the KeyModel's serialization-consistency assumption (a fast read
    of a key with any pending write is impossible by construction:
    the per-slot pending-write gate routes it to the round).

    Raises :class:`Violation` on any stale or lost read; the caller
    asserts coverage via the service's ``read_fastpath_hits`` /
    ``read_fastpath_misses`` counters.
    """

    def __init__(self, svc, runtime, n_keys: int = 3,
                 rounds: int = 40, seed: int = 0,
                 read_frac: float = 0.7) -> None:
        import random

        self.svc = svc
        self.runtime = runtime
        self.rng = random.Random(seed)
        self.rounds = rounds
        self.read_frac = read_frac
        self.keys = [f"k{i}" for i in range(n_keys)]
        self.models: Dict[Any, KeyModel] = {
            (e, k): KeyModel(f"{e}/{k}")
            for e in range(svc.n_ens) for k in self.keys}
        self.down: Dict[int, int] = {}
        self._vals = itertools.count(1)

    # -- nemesis arms --------------------------------------------------------

    def _nemesis(self) -> None:
        svc, rng = self.svc, self.rng
        r = rng.random()
        cfg = svc.config
        if r < 0.2 and self.down:
            # heal a downed leader: the next flush re-elects (the
            # step-down → re-election cycle completes)
            e = rng.choice(list(self.down))
            svc.set_peer_up(e, self.down.pop(e), True)
        elif r < 0.45:
            # step-down: kill the CURRENT leader right before the
            # flush that carries this round's ops
            e = rng.randrange(svc.n_ens)
            if e not in self.down and svc.leader_np[e] >= 0:
                p = int(svc.leader_np[e])
                svc.set_peer_up(e, p, False)
                self.down[e] = p
        elif r < 0.65:
            # lease expiry mid-workload: jump the clock past every
            # lease so the next reads race renewal
            self.runtime.run_for(cfg.lease() * 2.5)
        elif r < 0.85:
            # skewed-margin clock: land INSIDE [lease - margin,
            # lease) of the freshest grant — a correct margin check
            # refuses to serve there even though the lease itself has
            # not lapsed
            horizon = float(svc.lease_until.max()) - self.runtime.now
            if horizon > 0:
                skew = cfg.read_margin() * rng.uniform(0.0, 1.0)
                self.runtime.run_for(max(0.0, horizon - skew))

    # -- one round -----------------------------------------------------------

    def _submit(self):
        svc, rng = self.svc, self.rng
        pending = []
        for _ in range(rng.randrange(3, 9)):
            e = rng.randrange(svc.n_ens)
            key = rng.choice(self.keys)
            m = self.models[(e, key)]
            r = rng.random()
            if r < self.read_frac:
                fut = svc.kget(e, key)
                if fut.done:
                    # fast path (or immediate NOTFOUND): linearizes
                    # NOW — apply the model event at the serve point
                    if isinstance(fut.value, tuple) \
                            and fut.value[0] == "ok":
                        m.ack_read(fut.value[1])
                else:
                    pending.append(("get", m, None, fut))
            elif r < self.read_frac + 0.25 * (1 - self.read_frac):
                op_id = m.invoke_write(NOTFOUND)
                fut = svc.kdelete(e, key)
                if fut.done:  # no slot: immediate ack of NOTFOUND
                    m.ack_write(op_id)
                else:
                    pending.append(("del", m, op_id, fut))
            else:
                val = f"v{next(self._vals)}".encode()
                op_id = m.invoke_write(val)
                fut = svc.kput(e, key, val)
                if fut.done and fut.value == "failed":
                    m.fail_write(op_id)
                else:
                    pending.append(("put", m, op_id, fut))
        return pending

    def _drain(self, pending, max_flushes: int = 30) -> None:
        for _ in range(max_flushes):
            if all(f.done for *_x, f in pending):
                return
            self.svc.flush()
            self.runtime.run_for(0.001)
        raise Violation("service ops never resolved")

    @staticmethod
    def _apply(pending) -> None:
        for kind, m, op_id, fut in pending:
            r = fut.value
            ok = isinstance(r, tuple) and r[0] == "ok"
            if kind == "get":
                if ok:
                    m.ack_read(r[1])
            elif ok:
                m.ack_write(op_id)
            else:
                # the engine gates every replica write on the round's
                # quorum commit: 'failed' is a definitive no-op
                m.fail_write(op_id)

    def run(self) -> None:
        for _ in range(self.rounds):
            self._nemesis()
            pending = self._submit()
            self._drain(pending)
            self._apply(pending)
        # quiesce: heal, fold elections in, read every key back
        for e, p in list(self.down.items()):
            self.svc.set_peer_up(e, p, True)
        self.down.clear()
        self.svc.flush()
        pending = []
        for (e, key), m in self.models.items():
            fut = self.svc.kget(e, key)
            if fut.done:
                if isinstance(fut.value, tuple) \
                        and fut.value[0] == "ok":
                    m.ack_read(fut.value[1])
            else:
                pending.append(("get", m, None, fut))
        self._drain(pending)
        self._apply(pending)
