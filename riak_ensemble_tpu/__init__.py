"""riak_ensemble_tpu — a TPU-native multi-ensemble consensus framework.

A ground-up re-design of the capabilities of riak_ensemble (an Erlang
library of N independent Multi-Paxos consensus groups with per-key
linearizable K/V operations, Merkle-tree integrity, leader leases, and
gossip-based cluster management) for TPU hardware:

- The *protocol math* — ballot transitions, quorum vote reduction, Merkle
  hashing — runs as batched JAX kernels over an ``[ensembles, peers]``
  array program (see :mod:`riak_ensemble_tpu.ops` and
  :mod:`riak_ensemble_tpu.parallel`), sharded over a
  ``jax.sharding.Mesh`` with vote collection as an ICI ``psum``.
- The *orchestration* — timers, membership, supervision, disk — runs in a
  deterministic Python host runtime (:mod:`riak_ensemble_tpu.runtime`)
  with native C++ components for the monotonic clock and synctree
  persistence (:mod:`riak_ensemble_tpu.utils.clock`, ``native/``).

Layer map (mirrors SURVEY.md §1; reference files cited in each module;
see PARITY.md for the component-by-component map):

====  =======================  ============================================
L0    platform/runtime         config, runtime, netruntime, app, utils
L1    persistence              storage, save, synctree.backends,
                               synctree.native_store (+ native/ C++)
L2    integrity                synctree.tree, synctree.peer_tree,
                               synctree.exchange, ops.hash
L3    communication/quorum     msg, router, ops.quorum, ops.pallas_quorum
L4    consensus core           peer, worker, lease, backend
L5    cluster management       manager, root, state
L6    client API               client, netnode (async), svcnode
                               (scale-path TCP front-end + client)
--    batched TPU engine       ops.engine, parallel.mesh,
                               parallel.batched_host (the scale-path
                               service), parallel.distributed,
                               parallel.repgroup (replica quorum
                               across machine failure domains +
                               GroupClient), service_manager
                               (consensus-managed tenant placement),
                               synctree.remote_sync (streamed wire
                               Merkle exchange)
--    wire safety              wire (restricted codec + native/
                               wirecodec.cc C++ extension), funref
--    testing/verification     testing, linearizability, utils.trace
====  =======================  ============================================
"""

__version__ = "0.1.0"

from riak_ensemble_tpu.types import (  # noqa: F401
    Obj,
    Fact,
    PeerId,
    EnsembleInfo,
    NOTFOUND,
)
