"""Directory: the manager-facing interface peers depend on.

This is exactly the slice of ``riak_ensemble_manager`` the peer FSM
calls on its hot paths (all ETS reads in the reference —
manager.erl:188-245): peer addressing, current/pending views, cluster
membership — plus the async update/gossip entry points
(``update_ensemble``, ``gossip_pending``, root gossip).

Two implementations:

- :class:`StaticDirectory` — fixed membership for unit/integration
  tests of single ensembles (the ens_test.erl pattern of one host
  hosting all peers, test/ens_test.erl:31-45).
- :class:`riak_ensemble_tpu.manager.Manager` — the full gossiping
  cluster manager (one per node).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from riak_ensemble_tpu.types import PeerId, Views, Vsn


class Directory:
    """Interface; see module docstring."""

    def get_peer_addr(self, ensemble, peer_id) -> Optional[Any]:
        raise NotImplementedError

    def get_views(self, ensemble) -> Optional[Tuple[Vsn, Views]]:
        raise NotImplementedError

    def get_pending(self, ensemble) -> Optional[Tuple[Vsn, Views]]:
        raise NotImplementedError

    def get_leader(self, ensemble) -> Optional[PeerId]:
        raise NotImplementedError

    def cluster(self) -> List[str]:
        raise NotImplementedError

    def update_ensemble(self, ensemble, peer_id, views, vsn) -> None:
        """Leader pushes committed views (manager.erl:150-155)."""

    def gossip_pending(self, ensemble, vsn, views) -> None:
        """Leader pushes pending views (manager.erl:168-173)."""

    def root_gossip(self, peer, vsn, peer_id, views) -> None:
        """Root leader pushes root views (riak_ensemble_root:gossip)."""

    def stop_peer(self, ensemble, peer_id) -> None:
        """Ask the supervisor to stop a peer (transition shutdown)."""


class StaticDirectory(Directory):
    def __init__(self, runtime) -> None:
        self.runtime = runtime
        self.peers: Dict[Tuple[Any, PeerId], Any] = {}
        self.views: Dict[Any, Tuple[Vsn, Views]] = {}
        self.pending: Dict[Any, Tuple[Vsn, Views]] = {}
        self.leaders: Dict[Any, Optional[PeerId]] = {}
        self.nodes: List[str] = []

    def register_peer(self, ensemble, peer_id, actor_name) -> None:
        self.peers[(ensemble, peer_id)] = actor_name
        if peer_id.node not in self.nodes:
            self.nodes.append(peer_id.node)

    def get_peer_addr(self, ensemble, peer_id):
        name = self.peers.get((ensemble, peer_id))
        if name is None or self.runtime.whereis(name) is None:
            return None
        return name

    def get_views(self, ensemble):
        return self.views.get(ensemble)

    def get_pending(self, ensemble):
        return self.pending.get(ensemble)

    def get_leader(self, ensemble):
        return self.leaders.get(ensemble)

    def cluster(self):
        return list(self.nodes)

    def update_ensemble(self, ensemble, peer_id, views, vsn) -> None:
        cur = self.views.get(ensemble)
        if cur is None or vsn > cur[0]:
            self.views[ensemble] = (vsn, views)
            self.leaders[ensemble] = peer_id

    def gossip_pending(self, ensemble, vsn, views) -> None:
        cur = self.pending.get(ensemble)
        if cur is None or vsn > cur[0]:
            self.pending[ensemble] = (vsn, views)

    def stop_peer(self, ensemble, peer_id) -> None:
        name = self.peers.pop((ensemble, peer_id), None)
        if name is not None and self.runtime.whereis(name) is not None:
            self.runtime.stop_actor(name)
