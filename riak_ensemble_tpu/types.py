"""Core data model: objects, facts, peer identity, ensemble info.

Semantics match the reference's type layer
(``include/riak_ensemble_types.hrl:1-27``) and the peer's ``#fact{}``
record (``src/riak_ensemble_peer.erl:84-101``), re-expressed as
immutable Python dataclasses.  Versions are ``(epoch, seq)`` pairs
ordered lexicographically — the single most load-bearing comparison in
the whole protocol (``latest_obj``: ``src/riak_ensemble_backend.erl
:132-143``; ``latest_fact``: ``src/riak_ensemble_peer.erl:2031-2040``).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, NamedTuple, Optional, Tuple


class _NotFound:
    """Singleton sentinel for missing keys / tombstones.

    The reference uses the atom ``notfound`` both as a read miss and as
    the value written by ``kdelete`` (a tombstone object whose value is
    ``notfound``).
    """

    _instance: Optional["_NotFound"] = None

    def __new__(cls) -> "_NotFound":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "NOTFOUND"

    def __bool__(self) -> bool:
        return False


NOTFOUND = _NotFound()


class PeerId(NamedTuple):
    """Peer identity ``{Id, Node}`` (riak_ensemble_types.hrl:2)."""

    name: Any
    node: str

    def __repr__(self) -> str:  # compact for traces
        return f"{self.name}@{self.node}"


#: An ensemble id is an arbitrary hashable term; the distinguished root
#: ensemble is the atom-like string "root" (riak_ensemble_root.erl).
EnsembleId = Any

#: A view is an ordered list of peer ids; ``views`` is a list of views
#: (joint consensus holds quorums in every view simultaneously).
View = Tuple[PeerId, ...]
Views = Tuple[View, ...]

#: A version is a (epoch, seq) pair, ordered lexicographically.
Vsn = Tuple[int, int]

#: Sort key treating None vsns as minimal (reference orders `undefined`
#: before any tuple; Python can't compare None < tuple natively).
VSN_MIN: Vsn = (-1, -1)


def vsn_key(vsn: Optional[Vsn]) -> Vsn:
    return VSN_MIN if vsn is None else vsn


@dataclass(frozen=True)
class Obj:
    """A versioned K/V object (``#obj{}``,
    ``src/riak_ensemble_basic_backend.erl:42-45``).

    ``epoch``/``seq`` form the object version: epoch is the leader era
    that last wrote it, seq the per-epoch object sequence number
    (``obj_sequence``, ``src/riak_ensemble_peer.erl:1776-1791``).
    """

    epoch: int
    seq: int
    key: Any
    value: Any

    @property
    def vsn(self) -> Vsn:
        return (self.epoch, self.seq)

    def with_value(self, value: Any) -> "Obj":
        return replace(self, value=value)


def latest_obj(a: Obj, b: Obj) -> Obj:
    """Pick the newer of two object versions
    (``riak_ensemble_backend:latest_obj/3``, backend.erl:132-143)."""
    return a if a.vsn >= b.vsn else b


@dataclass(frozen=True)
class Fact:
    """Per-ensemble replicated consensus fact (``#fact{}``,
    ``src/riak_ensemble_peer.erl:84-101``).

    - ``epoch``/``seq``: current ballot number; seq resets to 0 on new
      epoch and increments per committed fact change.
    - ``leader``: peer id of the epoch's elected leader.
    - ``views``: current list of member views (joint consensus: more
      than one view while a membership change is in flight).
    - ``view_vsn``: vsn at which the current views took effect.
    - ``pend_vsn``: vsn that committed the current *pending* view.
    - ``commit_vsn``: pend_vsn of the last pending view that has since
      been transitioned to (no longer pending).
    - ``pending``: ``(vsn, views)`` — proposed next views published to
      the manager for gossip before transition; ``None`` when no
      pending change has ever been proposed (the reference's
      ``undefined``, distinguished from ``(vsn, ())`` by
      ``stable_views``, peer.erl:705-713).
    """

    epoch: int
    seq: int
    leader: Optional[PeerId]
    views: Views
    view_vsn: Optional[Vsn] = None
    pend_vsn: Optional[Vsn] = None
    commit_vsn: Optional[Vsn] = None
    pending: Optional[Tuple[Vsn, Views]] = None

    @property
    def vsn(self) -> Vsn:
        return (self.epoch, self.seq)


def latest_fact(a: Fact, b: Fact) -> Fact:
    """Newer-of-two facts by (epoch, seq)
    (``riak_ensemble_peer:latest_fact/2``, peer.erl:2031-2040)."""
    return a if (a.epoch, a.seq) >= (b.epoch, b.seq) else b


def initial_fact(views: Views) -> Fact:
    """A fresh fact for a newly-created ensemble (``reload_fact``
    not-found branch, ``riak_ensemble_peer.erl:2190-2194``: epoch=0,
    seq=0, view_vsn={0,0}, leader=undefined)."""
    return Fact(epoch=0, seq=0, leader=None, view_vsn=(0, 0),
                views=tuple(tuple(v) for v in views))


@dataclass(frozen=True)
class EnsembleInfo:
    """Manager-side record of one ensemble (``#ensemble_info{}``,
    ``include/riak_ensemble_types.hrl:20-26``)."""

    vsn: Vsn
    leader: Optional[PeerId]
    views: Views
    seq: Optional[Vsn]
    mod: str = "basic"
    args: Tuple[Any, ...] = ()


def term_order(v: Any) -> Tuple:
    """Total order over mixed-type terms (the Erlang term-order analog:
    numbers < strings < everything else).  Peer ids mix integer and
    string names (``{root, Node}`` vs ``{2, Node}``) and the usort of
    members must be deterministic and identical on every peer."""
    if isinstance(v, bool):
        return (1, 0, repr(v))
    if isinstance(v, (int, float)):
        return (0, v, "")
    if isinstance(v, str):
        return (1, 0, v)
    if isinstance(v, tuple):
        return (2, 0, tuple(term_order(x) for x in v))
    return (3, 0, repr(v))


def peer_order(p: PeerId) -> Tuple:
    return (term_order(p.name), term_order(p.node))


def members_of(views: Views) -> Tuple[PeerId, ...]:
    """Canonical sorted union of all views (``compute_members`` =
    ``lists:usort(lists:append(Views))``,
    ``src/riak_ensemble_peer.erl:2077-2081``)."""
    seen = set()
    for view in views:
        seen.update(view)
    return tuple(sorted(seen, key=peer_order))


# ---------------------------------------------------------------------------
# Client-visible results (std_reply(), riak_ensemble_types.hrl:8)

class Timeout(Exception):
    pass


class Failed(Exception):
    pass


class Unavailable(Exception):
    pass
