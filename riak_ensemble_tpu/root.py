"""Root-ensemble operations: consensus-managed cluster metadata.

Re-implementation of ``src/riak_ensemble_root.erl``: every cluster
mutation (join/remove/set_ensemble) is a ``kmodify`` on the key
``cluster_state`` of the distinguished root ensemble, with the mutator
function executed *inside the put FSM on the root leader*
(root.erl:74-114).  The default value for a first write is the calling
node's manager state (root_init, root.erl:118-119).

The mutators delegate to the vsn-guarded
:mod:`riak_ensemble_tpu.state` functions and return ``"failed"`` on a
vsn conflict, which aborts the kmodify (root_call/root_cast,
root.erl:123-165).

``gossip`` is the root leader pushing its own committed views into the
consistent state via a kmodify *cast to itself*; on completion the
(possibly unchanged) state is handed to the local manager for epidemic
spread — through a backpressured singleton in the reference
(maybe_async_gossip, root.erl:167-185), here a plain post to the
manager actor, which serializes naturally.
"""

from __future__ import annotations

from typing import Any, Optional

from riak_ensemble_tpu import funref
from riak_ensemble_tpu import router as routerlib
from riak_ensemble_tpu import state as statelib
from riak_ensemble_tpu.runtime import Future
from riak_ensemble_tpu.state import ClusterState
from riak_ensemble_tpu.types import EnsembleInfo, PeerId, Views, Vsn

ROOT = "root"
KEY = "cluster_state"


def _call(mgr, target_node: str, fun, timeout: float) -> Future:
    """root.erl:74-90: kmodify on `target_node`'s root ensemble; the
    returned future resolves to "ok" | "failed" | "timeout"."""
    default = mgr.get_cluster_state()
    event = ("put", KEY, funref.ref("peer:kmodify"), [fun, default])
    fut = routerlib.sync_send_event_fut(mgr.runtime, target_node, ROOT,
                                        event, timeout)
    out = Future()

    def translate(result: Any) -> None:
        if isinstance(result, tuple) and result[0] == "ok":
            out.resolve("ok")
        else:
            out.resolve(result)

    fut.add_waiter(translate)
    return out


def _cast(mgr, target_node: str, fun, timeout: float = 5.0) -> None:
    """root.erl:92-108: fire-and-forget kmodify."""
    default = mgr.get_cluster_state()
    event = ("put", KEY, funref.ref("peer:kmodify"), [fun, default])
    routerlib.sync_send_event_fut(mgr.runtime, target_node, ROOT, event,
                                  timeout)


# The mutators are registered funrefs so root operations cross nodes as
# plain data (name + bound args), never as live functions — the analog
# of the reference shipping {Module, Function, Cmd} MFAs
# (root.erl:82,104); the executing root leader resolves them locally.


@funref.register("root:join")
def _join_fun(joining_node: str, vsn: Vsn, cs: ClusterState):
    out = statelib.add_member(vsn, joining_node, cs)
    return out if out is not None else "failed"


@funref.register("root:remove")
def _remove_fun(target_node: str, vsn: Vsn, cs: ClusterState):
    out = statelib.del_member(vsn, target_node, cs)
    return out if out is not None else "failed"


@funref.register("root:set_ensemble")
def _set_ensemble_fun(ensemble: Any, info: EnsembleInfo, _vsn: Vsn,
                      cs: ClusterState):
    out = statelib.set_ensemble(ensemble, info, cs)
    return out if out is not None else "failed"


@funref.register("root:update_ensemble")
def _update_ensemble_fun(ensemble: Any, leader: Optional[PeerId],
                         views: Views, vsn: Vsn, _vsn: Vsn,
                         cs: ClusterState):
    out = statelib.update_ensemble(vsn, ensemble, leader, views, cs)
    return out if out is not None else "failed"


def join(mgr, target_node: str, joining_node: str,
         timeout: float = 60.0) -> Future:
    """Add `joining_node` to the cluster via `target_node`'s root
    ensemble (root.erl:47-55, root_call {join,..}:123-130)."""
    return _call(mgr, target_node,
                 funref.ref("root:join", joining_node), timeout)


def remove(mgr, target_node: str, timeout: float = 60.0) -> Future:
    """Remove `target_node`, via the local root (root.erl:57-65)."""
    return _call(mgr, mgr.node,
                 funref.ref("root:remove", target_node), timeout)


def set_ensemble(mgr, ensemble: Any, info: EnsembleInfo,
                 timeout: float = 10.0) -> Future:
    """Create/overwrite an ensemble record (root.erl:38-45,139-145)."""
    return _call(mgr, mgr.node,
                 funref.ref("root:set_ensemble", ensemble, info), timeout)


def update_ensemble(mgr, ensemble: Any, leader: Optional[PeerId],
                    views: Views, vsn: Vsn) -> None:
    """root.erl:34-36,159-165 (cast)."""
    _cast(mgr, mgr.node,
          funref.ref("root:update_ensemble", ensemble, leader, views,
                     vsn))


def gossip(mgr, peer, vsn: Vsn, leader: PeerId, views: Views) -> None:
    """Root leader pushes its committed views into the root state and
    relays the result to the local manager (root.erl:68-70,149-158)."""
    info = EnsembleInfo(vsn=vsn, leader=leader,
                        views=tuple(tuple(v) for v in views), seq=None)

    def fun(_vsn: Vsn, cs: ClusterState):
        out = statelib.set_ensemble(ROOT, info, cs)
        # maybe_async_gossip on both branches (root.erl:149-158)
        mgr.runtime.post(mgr.name, ("gossip", out if out is not None
                                    else cs))
        return out if out is not None else "failed"

    # Cast directly to the issuing peer itself (root.erl:68-70 sends to
    # the root leader's own pid).  This never leaves the node, so the
    # mutator stays a live closure (resolve passes it through).
    fut = Future()
    mgr.runtime.post(peer.name, ("peer_sync", fut,
                                 ("put", KEY, funref.ref("peer:kmodify"),
                                  [fun, mgr.get_cluster_state()])))
