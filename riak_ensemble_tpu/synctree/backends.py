"""Synctree storage backends.

Reference: ``synctree_ets.erl`` / ``synctree_orddict.erl`` /
``synctree_leveldb.erl`` — all implement ``new/fetch/exists/store``.
Here: an in-memory dict backend (= ets/orddict), and a file-backed
backend that journals batches to an append-only CRC log (the role the
eleveldb C++ dependency plays for the reference; a C++ engine can slot
in behind the same interface — see ``native/``).
"""

from __future__ import annotations

import os
import pickle
import zlib
from typing import Any, Dict, Iterable, Tuple

from riak_ensemble_tpu import faults
from riak_ensemble_tpu.save import fsync_dir


class DictBackend:
    """synctree_ets/synctree_orddict equivalent."""

    def __init__(self) -> None:
        self.data: Dict[Any, Any] = {}

    def fetch(self, key, default=None):
        return self.data.get(key, default)

    def exists(self, key) -> bool:
        return key in self.data

    def store(self, key, value) -> None:
        self.data[key] = value

    def delete(self, key) -> None:
        self.data.pop(key, None)

    def keys(self) -> Iterable:
        return list(self.data.keys())


class FileBackend(DictBackend):
    """Persistent backend: in-memory dict + append-only CRC32-framed
    journal, compacted on open.  Plays eleveldb's role for synctree
    persistence (synctree_leveldb.erl:104-161; batched writes
    ``store/2:141-152``).  Key layout note (tree_id scoping for shared
    trees) is handled by the caller via key prefixing.
    """

    #: storage fault-plane path class (docs/ARCHITECTURE.md §15)
    fault_class = "tree"

    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        #: CRC-detected replay stops (corrupt/torn frames dropped,
        #: never served) — the detection evidence counter
        self.truncations = 0
        self.truncated_bytes = 0
        #: CRC-failed frames healed by a one-shot re-read (transient
        #: read corruption, not on-disk damage)
        self.read_retries = 0
        os.makedirs(os.path.dirname(path), exist_ok=True)
        existed = os.path.exists(path)
        self._replay()
        self._fh = open(self.path, "ab")
        if not existed:
            fsync_dir(os.path.dirname(path))

    def _replay(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        pos = 0
        while pos + 8 <= len(raw):
            size = int.from_bytes(raw[pos:pos + 4], "big")
            crc = int.from_bytes(raw[pos + 4:pos + 8], "big")
            frame = faults.read_filter(self.fault_class,
                                       raw[pos + 8:pos + 8 + size])
            if len(frame) == size \
                    and (zlib.crc32(frame) & 0xFFFFFFFF) != crc:
                # one-shot re-read FROM DISK before trusting the
                # mismatch: a transient bad read heals on a real
                # retry; dropping the tail on it would discard
                # healthy on-disk frames behind it (review r15)
                self.read_retries += 1
                with open(self.path, "rb") as rf:
                    rf.seek(pos + 8)
                    frame = faults.read_filter(self.fault_class,
                                               rf.read(size))
            if len(frame) < size or (zlib.crc32(frame) & 0xFFFFFFFF) != crc:
                # torn tail write or corrupt frame: DETECTED by the
                # CRC gate — stop replay here (count the evidence;
                # what was dropped heals from a live replica via the
                # exchange path, it is never served)
                self.truncations += 1
                self.truncated_bytes += len(raw) - pos
                break
            op, key, value = pickle.loads(frame)
            if op == "put":
                self.data[key] = value
            else:
                self.data.pop(key, None)
            pos += 8 + size
        # Compact: rewrite only the live image.
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for key, value in self.data.items():
                f.write(self._frame(("put", key, value)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        fsync_dir(os.path.dirname(self.path))

    @staticmethod
    def _frame(record: Tuple) -> bytes:
        blob = pickle.dumps(record)
        crc = zlib.crc32(blob) & 0xFFFFFFFF
        return len(blob).to_bytes(4, "big") + crc.to_bytes(4, "big") + blob

    def store(self, key, value) -> None:
        faults.storage_raise(self.fault_class, "write")
        super().store(key, value)
        self._fh.write(self._frame(("put", key, value)))

    def delete(self, key) -> None:
        faults.storage_raise(self.fault_class, "write")
        super().delete(key)
        self._fh.write(self._frame(("del", key, None)))

    def sync(self) -> None:
        self._fh.flush()
        faults.crashpoint("tree_save")
        faults.storage_raise(self.fault_class, "fsync")
        os.fsync(self._fh.fileno())

    def close(self) -> None:
        self._fh.close()
