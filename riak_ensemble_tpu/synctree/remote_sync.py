"""Streamed Merkle exchange between OS processes — the
``synctree_remote.erl`` role for the DEVICE tree.

The reference proves its exchange streams level-by-level across a
process boundary and counts the messages/bytes it costs
(``test/synctree_remote.erl:24-38``); the descent fetches only the
children of differing buckets (``synctree.erl:372-417``), so traffic
is O(width · height · diffs), never O(keys).

This module is that protocol for :mod:`riak_ensemble_tpu.ops.hash`
device trees (1M-segment scale): a :class:`TreeSyncServer` exposes one
host's levels over the restricted wire codec, and :func:`sync_diff`
descends from another process — ONE level-batched request per level
(the ``start_exchange_level`` streaming shape), gathering only the
differing parents' children on BOTH sides (a device gather + transfer
of just those nodes, never a full-level d2h).  The caller gets the
divergent segment ids (what key repair must fetch) plus a traffic
ledger the tests assert the O-bound against.
"""

from __future__ import annotations

import socket
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from riak_ensemble_tpu import wire
from riak_ensemble_tpu.ops import hash as hashk
from riak_ensemble_tpu.parallel.repgroup import (
    _ThreadedAcceptor, recv_frame, send_frame)


class TreeSyncServer:
    """Serve one device tree's levels to remote exchange clients.

    Protocol (one response per request):
      ("meta",)             -> ("meta", n_levels, n_segments, lanes)
      ("nodes", level, ids) -> ("nodes", raw_uint32_bytes)  # [n, LANES]
    """

    def __init__(self, levels: hashk.Levels, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.levels = levels
        self._srv = _ThreadedAcceptor(host, port, self._serve)
        self.port = self._srv.port

    def _serve(self, sock: socket.socket) -> None:
        import jax.numpy as jnp

        while True:
            try:
                frame = recv_frame(sock)
            except (ConnectionError, OSError, wire.WireError):
                return
            try:
                if frame[0] == "meta":
                    resp = ("meta", len(self.levels),
                            int(self.levels[-1].shape[0]), hashk.LANES)
                elif frame[0] == "nodes":
                    _, level, ids = frame
                    lvl = self.levels[int(level)]
                    idx = jnp.asarray(
                        np.asarray(ids, np.int32).clip(
                            0, lvl.shape[0] - 1))
                    # device gather first: only the requested nodes
                    # ever cross the device link or the wire
                    chunk = np.asarray(lvl[idx], np.uint32)
                    resp = ("nodes", chunk.tobytes())
                else:
                    resp = ("error", "unknown-op")
            except Exception:
                resp = ("error", "bad-request")
            try:
                send_frame(sock, resp)
            except (ConnectionError, OSError):
                return

    def close(self) -> None:
        self._srv.close()


def sync_diff(levels: hashk.Levels, host: str, port: int,
              width: int = 16, timeout: float = 120.0
              ) -> Tuple[np.ndarray, Dict[str, Any]]:
    """Find every segment where the remote tree differs from
    ``levels``, exchanging O(width · height · diffs) traffic.

    Returns ``(segment_ids, stats)`` where stats carries the message
    count, bytes in each direction and per-level visited-node counts —
    the ledger ``test/synctree_remote.erl`` keeps across its process
    boundary, and what the tests bound against ``hash.exchange_cost``.
    """
    import jax.numpy as jnp

    stats: Dict[str, Any] = {"messages": 0, "bytes_tx": 0,
                             "bytes_rx": 0, "visited": []}

    def call(sock, frame):
        payload = wire.encode(frame)
        stats["messages"] += 1
        stats["bytes_tx"] += len(payload) + 4
        send_frame(sock, frame)
        resp = recv_frame(sock)
        stats["bytes_rx"] += len(wire.encode(resp)) + 4
        return resp

    with socket.create_connection((host, port), timeout=timeout) as s:
        s.settimeout(timeout)
        meta = call(s, ("meta",))
        assert meta[0] == "meta", meta
        n_levels, segs, lanes = int(meta[1]), int(meta[2]), int(meta[3])
        if n_levels != len(levels) or lanes != hashk.LANES or \
                segs != int(levels[-1].shape[0]):
            raise ValueError(
                f"tree shape mismatch: remote {n_levels} levels/"
                f"{segs} segs, local {len(levels)}/"
                f"{int(levels[-1].shape[0])}")

        # root compare
        r = call(s, ("nodes", 0, [0]))
        remote = np.frombuffer(r[1], np.uint32).reshape(-1, lanes)
        local = np.asarray(levels[0], np.uint32)
        diff = [0] if (remote[0] != local[0]).any() else []
        stats["visited"].append(1)

        for level in range(1, n_levels):
            if not diff:
                stats["visited"].append(0)
                continue
            child_ids: List[int] = []
            for p in diff:
                base = p * width
                child_ids.extend(range(base, base + width))
            r = call(s, ("nodes", level, child_ids))
            remote = np.frombuffer(r[1], np.uint32).reshape(-1, lanes)
            idx = jnp.asarray(np.asarray(child_ids, np.int32))
            local = np.asarray(levels[level][idx], np.uint32)
            neq = (remote != local).any(axis=1)
            diff = [child_ids[i] for i in np.nonzero(neq)[0]]
            stats["visited"].append(len(child_ids))

    return np.asarray(diff, np.int64), stats
