"""Integrity subsystem: self-validating Merkle hash trie.

Python/host implementation of the reference's ``synctree.erl`` family
(always-up-to-date, verify-on-every-access trie; see
``src/synctree.erl:44-73`` for the design rationale) plus the
tree-server actor and the peer-to-peer exchange driver.  The batched
device-side Merkle kernel lives in :mod:`riak_ensemble_tpu.ops.hash`.
"""

from riak_ensemble_tpu.synctree.tree import SyncTree, Corrupted, NONE  # noqa: F401
from riak_ensemble_tpu.synctree.peer_tree import PeerTree  # noqa: F401
