"""Tree exchange driver: sync a peer's Merkle tree with a trusted
majority of its ensemble.

Mirrors ``src/riak_ensemble_exchange.erl``: spawned per exchange
(``start_exchange:23-31``); finds a trusted majority via an
``('exchange',)`` quorum round (required='quorum' if the local tree is
trusted, else 'other' — a majority *excluding* self,
``trust_majority:109-126``), falling back to an ``('all_exchange',)``
round with required='all' (``all_trust_majority:128-145``).  Then
verifies the local upper tree and pairwise-compares against each remote
tree, adopting remote hashes that are missing locally or strictly newer
(``valid_obj_hash(B, A)``, exchange.erl:85-98).  Reports
``exchange_complete`` / ``exchange_failed`` / tree_corrupted back to
the peer FSM.

Runs as a runtime Task; remote tree reads are wire-safe level-batched
xcalls (``tree_exchange_get_many`` — one round trip per level, the
start_exchange_level streaming of synctree_remote.erl) to the remote
peer's tree actor.  The remote tree name is fetched first via a
``tree_pid`` sync call (exchange.erl:71-72) so the M:N shared-tree
mapping keeps working.
"""

from __future__ import annotations

from typing import Any, List

from riak_ensemble_tpu import msg as msglib
from riak_ensemble_tpu.runtime import Future
from riak_ensemble_tpu.synctree.tree import (
    NONE, Corrupted, compare_gen_streamed,
)


def start_exchange(peer, tree_name, peers, views, trusted: bool) -> None:
    """Spawn the exchange task (exchange.erl:23-31)."""
    peer.runtime.spawn_task(
        _perform_exchange(peer, tree_name, peers, views, trusted),
        name=f"exchange:{peer.name}")


def _perform_exchange(peer, tree_name, peers, views, trusted: bool):
    try:
        required = "quorum" if trusted else "other"
        fut = msglib.blocking_send_all(peer, ("exchange",), peer.id, peers,
                                       views, required)
        result = yield fut
        if result[0] == "quorum_met":
            remote_peers = [p for p, _ in result[1]]
        else:
            fut = msglib.blocking_send_all(peer, ("all_exchange",), peer.id,
                                           peers, views, "all")
            result = yield fut
            if result[0] == "quorum_met":
                remote_peers = [p for p, _ in result[1]]
            else:
                peer.runtime.post(peer.name, ("exchange_failed",))
                return
        yield from _perform_exchange2(peer, tree_name, remote_peers)
    except Corrupted:
        yield from _sync_tree_corrupted(peer)
    except Exception:
        peer.runtime.post(peer.name, ("exchange_failed",))


def _sync_tree_corrupted(peer):
    fut = Future()
    peer.runtime.post(peer.name, ("peer_sync", fut, ("tree_corrupted",)))
    yield fut


def _tree_call(peer, tree_name, request) -> Future:
    fut = Future()
    peer.send(tree_name, request + (fut,))
    return fut


def _perform_exchange2(peer, tree_name, remote_peers: List[Any]):
    # Remote reads are wire-safe xcalls (no Futures on the wire), so
    # the same protocol runs on the simulator and the TCP transport; a
    # dead/unreachable remote mid-exchange times out and fails the
    # exchange (the reference's monitor-crash path, exchange.erl:40-52).
    call_timeout = max(peer.config.quorum() * 10, 1.0)
    ok = yield _tree_call(peer, tree_name, ("tree_verify_upper",))
    if not ok:
        yield from _sync_tree_corrupted(peer)
        return
    height = yield _tree_call(peer, tree_name, ("tree_height",))
    for remote in remote_peers:
        remote_addr = peer.peer_addr(remote)
        if remote_addr is None:
            continue
        # Fetch the remote peer's tree name (tree_pid sync event).
        remote_tree = yield msglib.xcall(peer, remote_addr,
                                         ("tree_pid",), call_timeout)
        if remote_tree == "timeout" or remote_tree == "nack":
            peer.runtime.post(peer.name, ("exchange_failed",))
            return

        flags = {"local": False, "remote": False, "timeout": False}

        # Level-batched (streamed) fetches: one local call and ONE
        # remote round trip per level (compare_gen_streamed).
        def local_many(pairs):
            return _tree_call(peer, tree_name,
                              ("tree_exchange_get_many", tuple(pairs)))

        def remote_many(pairs):
            return msglib.xcall(peer, remote_tree,
                                ("tree_exchange_get_many", tuple(pairs)),
                                call_timeout)

        gen = compare_gen_streamed(
            height, _wrap_many(local_many, flags, "local"),
            _wrap_many(remote_many, flags, "remote"))
        diffs = yield from _drive(gen)
        if flags["timeout"]:
            peer.runtime.post(peer.name, ("exchange_failed",))
            return
        if flags["local"]:
            yield from _sync_tree_corrupted(peer)
            return
        if flags["remote"]:
            # Remote tree corrupt: tell it, then move on
            # (exchange.erl:102-108 throws; peer retries later).
            msglib.xcall(peer, remote_addr, ("tree_corrupted",),
                         call_timeout)
            peer.runtime.post(peer.name, ("exchange_failed",))
            return
        for key, (a, b) in diffs:
            if b is NONE:
                continue
            if a is NONE or _valid_obj_hash(b, a):
                yield _tree_call(peer, tree_name, ("tree_insert", key, b))
    peer.runtime.post(peer.name, ("exchange_complete",))


def _wrap_many(fetch_many, flags, side):
    """Translate per-entry 'corrupted' and whole-call 'timeout'
    replies of a batched fetch into Corrupted aborts."""
    def inner(pairs):
        raw = fetch_many(pairs)
        out = Future()

        def on(v):
            if v == "timeout":
                flags["timeout"] = True
                out.resolve([Corrupted(0, 0)] * len(pairs))
                return
            entries = []
            for item in v:
                if item == "corrupted":
                    flags[side] = True
                    entries.append(Corrupted(0, 0))
                else:
                    entries.append(item)
            out.resolve(entries)

        raw.add_waiter(on)
        return out

    return inner


def _drive(gen):
    """Run a compare_gen to completion, re-yielding its futures."""
    try:
        fut = next(gen)
        while True:
            value = yield fut
            fut = gen.send(value)
    except StopIteration as stop:
        return stop.value or []
    except Corrupted:
        return []


def _valid_obj_hash(b: bytes, a: bytes) -> bool:
    """Adopt remote hash only if tagged and >= local
    (riak_ensemble_peer:valid_obj_hash, peer.erl:1726-1729)."""
    return isinstance(b, bytes) and isinstance(a, bytes) and \
        b[:1] == b"\x00" and a[:1] == b"\x00" and b >= a
