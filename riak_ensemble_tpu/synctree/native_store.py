"""Synctree backend over the C++ treestore engine.

The role ``synctree_leveldb.erl`` + eleveldb play for the reference:
durable Merkle-bucket storage with a shared-engine registry (many
trees, one store — synctree_leveldb.erl:52-83) and batched sequential
writes.  Keys/values are pickled terms; the engine stores raw bytes
(``native/treestore.cc``: CRC-framed WAL + ordered in-memory index +
snapshot compaction).

Use :func:`available` to gate tests/deployments; construction raises
RuntimeError when the native library cannot be built.
"""

from __future__ import annotations

import ctypes
import pickle
from typing import Any, Iterable, List

from riak_ensemble_tpu import faults
from riak_ensemble_tpu.utils import native


def available() -> bool:
    return native.load() is not None


_corrupt_warned = False


def _storage_faults(fault_class: str, op: str) -> None:
    """The write-path seam of the §15 storage fault plane for the C
    engine: injected EIO/ENOSPC raise exactly like the Python
    stores'; a torn-write rule degrades to an ERROR-ONLY injection
    (the engine owns its file handles, so Python cannot leave a
    physically torn frame — the write still fails and the rule is
    consumed, keeping an armed nemesis from injecting nothing)."""
    faults.storage_raise(fault_class, op)
    if op == "write":
        cut = faults.torn_limit(fault_class)
        if cut is not None:
            import errno as _errno
            raise OSError(
                _errno.EIO,
                f"injected torn write (native {fault_class} store: "
                f"error-only, no partial frame)")


def _enc(term: Any) -> bytes:
    return pickle.dumps(term, protocol=4)


def _dec(blob: bytes) -> Any:
    return pickle.loads(blob)


class NativeBackend:
    """Implements the synctree storage interface
    (fetch/exists/store/delete/keys) over the C++ engine."""

    #: storage fault-plane path class (docs/ARCHITECTURE.md §15);
    #: the WAL's ``_open_store`` rebinds it to ``"wal"`` — the engine
    #: serves both roles and the injection must track the role
    fault_class = "tree"

    def __init__(self, path: str) -> None:
        lib = native.load()
        if lib is None:
            raise RuntimeError("native treestore unavailable "
                               "(g++/make missing?)")
        self._lib = lib
        self._handle = lib.retpu_store_open(path.encode())
        if not self._handle:
            raise RuntimeError(f"cannot open treestore at {path}")
        self.path = path
        # the §15 read-corruption knob cannot reach the C engine's
        # replay reads (its CRC gate runs in C; Python filtering the
        # DECODED value would corrupt without detection — worse).
        # An armed-but-inert rule must at least be loud: on-disk
        # byte flips are the native corruption harness instead.
        global _corrupt_warned
        p = faults.active_plan()
        if (not _corrupt_warned and p is not None
                and p.describe().get("corrupt")):
            _corrupt_warned = True
            import sys
            print("riak_ensemble_tpu.native_store: "
                  "RETPU_FAULT_CORRUPT does not reach the C "
                  "engine's replay reads (corrupt native store "
                  "files on disk instead; the C CRC gate covers "
                  "that path)", file=sys.stderr, flush=True)

    # -- backend interface ------------------------------------------------

    def fetch(self, key, default=None):
        k = _enc(key)
        n = self._lib.retpu_store_get(self._handle, k, len(k), None, 0)
        if n < 0:
            return default
        buf = ctypes.create_string_buffer(n)
        n2 = self._lib.retpu_store_get(self._handle, k, len(k), buf, n)
        if n2 != n:  # pragma: no cover - single-threaded host
            return default
        return _dec(buf.raw)

    def exists(self, key) -> bool:
        k = _enc(key)
        return self._lib.retpu_store_get(self._handle, k, len(k),
                                         None, 0) >= 0

    def store(self, key, value) -> None:
        _storage_faults(self.fault_class, "write")
        k, v = _enc(key), _enc(value)
        self._lib.retpu_store_put(self._handle, k, len(k), v, len(v))

    def store_raw(self, k: bytes, v: bytes) -> None:
        """Pre-pickled record append (the resolve kernel's arena
        path): skips the Python-side encode, identical framing."""
        _storage_faults(self.fault_class, "write")
        self._lib.retpu_store_put(self._handle, k, len(k), v, len(v))

    def put_many_raw(self, arena, index) -> None:
        """One C call appends a whole arena of pre-pickled records
        ((key_off, key_len, val_off, val_len) rows; key_len <= 0 rows
        are skipped) — the per-flush WAL append of the native resolve
        path.  Falls back to per-record puts on a stale .so without
        the batch symbol."""
        import numpy as np
        _storage_faults(self.fault_class, "write")
        if not hasattr(self._lib, "retpu_store_put_many"):
            a = np.ascontiguousarray(arena, np.uint8)
            for koff, klen, voff, vlen in np.asarray(index).tolist():
                if klen > 0:
                    self.store_raw(a[koff:koff + klen].tobytes(),
                                   a[voff:voff + vlen].tobytes())
            return
        a = np.ascontiguousarray(arena, np.uint8)
        idx = np.ascontiguousarray(index, np.int64)
        self._lib.retpu_store_put_many(
            self._handle, a.ctypes.data_as(ctypes.c_void_p),
            idx.ctypes.data_as(ctypes.c_void_p), len(idx))

    def delete(self, key) -> None:
        _storage_faults(self.fault_class, "write")
        k = _enc(key)
        self._lib.retpu_store_delete(self._handle, k, len(k))

    def keys(self) -> Iterable:
        out: List[Any] = []
        i = 0
        while True:
            n = self._lib.retpu_store_key_at(self._handle, i, None, 0)
            if n < 0:
                break
            buf = ctypes.create_string_buffer(n)
            if self._lib.retpu_store_key_at(self._handle, i, buf,
                                            n) != n:  # pragma: no cover
                break
            out.append(_dec(buf.raw))
            i += 1
        return out

    # -- engine management --------------------------------------------------

    def sync(self) -> None:
        if self.fault_class == "tree":
            # the WAL role has its own barriers (wal_fsync_pre/post
            # around the ServiceWAL sync call)
            faults.crashpoint("tree_save")
        faults.storage_raise(self.fault_class, "fsync")
        self._lib.retpu_store_sync(self._handle)

    def flush(self) -> None:
        """Flush-only (no fsync): the process-crash durability floor."""
        self._lib.retpu_store_flush(self._handle)

    def compact(self) -> None:
        self._lib.retpu_store_compact(self._handle)

    def count(self) -> int:
        return self._lib.retpu_store_count(self._handle)

    def close(self) -> None:
        if self._handle:
            self._lib.retpu_store_close(self._handle)
            self._handle = None
