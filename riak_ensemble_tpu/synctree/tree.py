"""Self-validating fixed-shape Merkle hash trie.

Mirrors ``src/synctree.erl``:

- Keys map to one of ``segments`` leaf buckets via md5
  (``get_segment``, synctree.erl:251-253); inner levels have
  ``width``-way fan-out, ``height = log_width(segments)``
  (synctree.erl:88-89, 270-284).
- Every traversal verifies hashes root→leaf (``get_path``,
  synctree.erl:302-320) and reports ``Corrupted(level, bucket)``.
- ``insert`` recomputes the path hashes up to a new top hash
  (synctree.erl:189-209); bucket hash = md5 over the bucket's hash
  values in key order, tagged ``\\x00`` (synctree.erl:255-259).
- Exchange: level-by-level bucket diff (``compare``/``exchange_level``,
  synctree.erl:380-417) over pluggable accessor functions, cost
  O(width·height·diffs) not O(keys).
- Repair: bottom-up ``rehash`` (equivalent to the reference's DFS
  recompute, synctree.erl:489-535, but driven by the set of existing
  buckets so it is O(live buckets)); BFS ``verify``
  (synctree.erl:549-571).
- ``corrupt`` deliberately loses a leaf entry without fixing hashes —
  the corruption-test hook (synctree.erl:241-247).

Storage backends provide dict-like ``fetch/store/exists/store_batch``
(:mod:`riak_ensemble_tpu.synctree.backends`).
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, Generator, List, Optional, Tuple

from riak_ensemble_tpu.synctree.backends import DictBackend

#: marker for "missing on this side" in exchange deltas
#: (riak_ensemble_util:orddict_delta, util.erl:115-141)
NONE = "$none"

DEFAULT_WIDTH = 16
DEFAULT_SEGMENTS = 1024 * 1024


class Corrupted(Exception):
    """Raised internally; surfaced as a return value like the
    reference's {corrupted, Level, Bucket}."""

    def __init__(self, level: int, bucket: int) -> None:
        super().__init__(f"corrupted at {level}/{bucket}")
        self.level = level
        self.bucket = bucket


def term_key(key: Any):
    """Total order over heterogeneous keys (erlang term order spirit:
    numbers < strings < bytes < tuples)."""
    if isinstance(key, bool):
        return (1, str(key))
    if isinstance(key, (int, float)):
        return (0, key)
    if isinstance(key, str):
        return (1, key)
    if isinstance(key, bytes):
        return (2, key)
    if isinstance(key, tuple):
        return (3, tuple(term_key(k) for k in key))
    return (4, repr(key))


def ensure_binary(key: Any) -> bytes:
    """synctree.erl:261-268."""
    if isinstance(key, int) and not isinstance(key, bool):
        return key.to_bytes(8, "big", signed=True)
    if isinstance(key, str):
        return key.encode("utf-8")
    if isinstance(key, bytes):
        return key
    return repr(key).encode("utf-8")


def hash_bucket(bucket: Dict[Any, bytes]) -> bytes:
    """md5 over the bucket's hash values in key order, tagged with a
    hash-method byte (synctree.erl:255-259)."""
    h = hashlib.md5()
    for k in sorted(bucket, key=term_key):
        h.update(bucket[k])
    return b"\x00" + h.digest()


class SyncTree:
    def __init__(self, tree_id: Any = None, width: int = DEFAULT_WIDTH,
                 segments: int = DEFAULT_SEGMENTS, backend=None) -> None:
        self.id = tree_id
        self.width = width
        self.segments = segments
        # By design segments is a power of width and width a power of 2
        # (synctree.erl:270-284).
        height = round(__import__("math").log(segments, width))
        assert width ** height == segments, "segments must be width^height"
        shift = round(__import__("math").log2(width))
        assert 2 ** shift == width, "width must be a power of 2"
        self.height = height
        self.shift = shift
        self.shift_max = shift * height
        self.backend = backend if backend is not None else DictBackend()
        self._buffer: List[Tuple] = []
        # Reload top hash from storage (synctree.erl:174-177).
        self.top_hash: Optional[bytes] = self._bfetch((0, 0), None)

    def _loc(self, loc):
        """Namespace backend keys by tree id so many trees can share
        one storage engine (the synctree_leveldb key layout
        ``<<0, TreeId, Level, Bucket>>``, synctree_leveldb.erl:104-109;
        exercised by the shared synctree_path mapping)."""
        return loc if self.id is None else (self.id,) + loc

    def _bfetch(self, loc, default=None):
        return self.backend.fetch(self._loc(loc), default)

    def _bstore(self, loc, value) -> None:
        self.backend.store(self._loc(loc), value)

    def _bexists(self, loc) -> bool:
        return self.backend.exists(self._loc(loc))

    def _bdelete(self, loc) -> None:
        self.backend.delete(self._loc(loc))

    def _bkeys(self):
        """This tree's (level, bucket) keys, prefix-stripped."""
        for k in self.backend.keys():
            if self.id is None:
                yield k
            elif isinstance(k, tuple) and len(k) == 3 and k[0] == self.id:
                yield k[1:]

    # -- basic ops ---------------------------------------------------------

    def get_segment(self, key: Any) -> int:
        digest = hashlib.md5(ensure_binary(key)).digest()
        return int.from_bytes(digest, "big") % self.segments

    def _fetch(self, level: int, bucket: int) -> Dict[Any, bytes]:
        return dict(self._bfetch((level, bucket), {}))

    def get_path(self, segment: int):
        """Verified root→leaf path; returns list of ((level, bucket),
        bucket_dict) leaf-first, or raises Corrupted
        (synctree.erl:302-340)."""
        n = self.shift_max
        level = 1
        up_hashes = {0: self.top_hash}
        acc = []
        while True:
            bucket = segment >> n
            expected = up_hashes.get(bucket)
            hashes = self._fetch(level, bucket)
            acc.insert(0, ((level, bucket), hashes))
            if not self._verify_hash(expected, hashes):
                raise Corrupted(level, bucket)
            if n == 0:
                return acc
            up_hashes = hashes
            n -= self.shift
            level += 1

    @staticmethod
    def _verify_hash(expected: Optional[bytes],
                     hashes: Dict[Any, bytes]) -> bool:
        """synctree.erl:322-340: a missing expectation admits only an
        empty bucket."""
        if expected is None:
            return not hashes
        return hash_bucket(hashes) == expected

    def insert(self, key: Any, value: bytes):
        """Insert and rehash the path; returns None or Corrupted
        (synctree.erl:189-209)."""
        assert isinstance(value, bytes)
        segment = self.get_segment(key)
        try:
            path = self.get_path(segment)
        except Corrupted as c:
            return c
        child_key: Any = key
        child_hash = value
        updates = []
        for (level, bucket), hashes in path:
            hashes[child_key] = child_hash
            updates.append(((level, bucket), hashes))
            child_key = bucket
            child_hash = hash_bucket(hashes)
        updates.append(((0, 0), child_hash))
        for loc, val in updates[:-1]:
            self._bstore(loc, val)
        self._bstore((0, 0), child_hash)
        self.top_hash = child_hash
        return None

    def get(self, key: Any):
        """Verified read: bytes | None (notfound) | Corrupted
        (synctree.erl:215-231)."""
        if self.top_hash is None:
            return None
        segment = self.get_segment(key)
        try:
            path = self.get_path(segment)
        except Corrupted as c:
            return c
        (_loc, leaf) = path[0]
        return leaf.get(key)

    def exchange_get(self, level: int, bucket: int):
        """Verified hashes of one bucket for the exchange protocol;
        level 0 returns [(0, top_hash)] (synctree.erl:233-237,
        verified_hashes:288-298)."""
        if level == 0 and bucket == 0:
            return {0: self.top_hash}
        # Walk down the ancestor chain of `bucket`: at depth d (1-based)
        # the ancestor is bucket >> shift*(level-d), verifying each
        # bucket against its parent's entry.
        up_hashes = {0: self.top_hash}
        hashes: Dict[Any, bytes] = {}
        for d in range(1, level + 1):
            b = bucket >> (self.shift * (level - d))
            expected = up_hashes.get(b)
            hashes = self._fetch(d, b)
            if not self._verify_hash(expected, hashes):
                return Corrupted(d, b)
            up_hashes = hashes
        return hashes

    def corrupt(self, key: Any) -> None:
        """Silently lose a leaf entry (test hook, synctree.erl:241-247)."""
        segment = self.get_segment(key)
        loc = (self.height + 1, segment)
        hashes = self._fetch(*loc)
        hashes.pop(key, None)
        self._bstore(loc, hashes)

    def corrupt_upper(self, key: Any, level: int = 1) -> None:
        """Corrupt an inner node on key's path (test hook for the
        corrupt_upper intercept, test/synctree_intercepts.erl:30-41)."""
        segment = self.get_segment(key)
        bucket = segment >> (self.shift_max - self.shift * (level - 1))
        loc = (level, bucket)
        hashes = self._fetch(*loc)
        if hashes:
            k = sorted(hashes, key=term_key)[0]
            hashes[k] = b"\x00" + b"\xde\xad" * 8
            self._bstore(loc, hashes)

    # -- repair ------------------------------------------------------------

    def rehash_upper(self) -> None:
        self._rehash(self.height)

    def rehash(self) -> None:
        self._rehash(self.height + 1)

    def _rehash(self, max_depth: int) -> None:
        """Recompute hashes bottom-up from live buckets.  Equivalent to
        the reference's full DFS (synctree.erl:489-535) because a
        missing bucket hashes to nothing and contributes no entry, but
        O(live buckets) instead of O(width^height)."""
        # Live buckets at max_depth level.
        level_buckets = sorted(
            {b for (lvl, b) in self._bkeys() if lvl == max_depth})
        child_hashes: Dict[int, bytes] = {}
        for b in level_buckets:
            content = self._fetch(max_depth, b)
            if content:
                child_hashes[b] = hash_bucket(content)
        for level in range(max_depth - 1, 0, -1):
            existing = {b for (lvl, b) in self._bkeys() if lvl == level}
            parents: Dict[int, Dict[int, bytes]] = {}
            for child, h in child_hashes.items():
                parents.setdefault(child >> self.shift, {})[child] = h
            child_hashes = {}
            for b in sorted(set(parents) | existing):
                content = parents.get(b, {})
                if content:
                    self._bstore((level, b), content)
                    child_hashes[b] = hash_bucket(content)
                elif self._bexists((level, b)):
                    self._bdelete((level, b))
        if child_hashes:
            assert set(child_hashes) == {0}
            self.top_hash = child_hashes[0]
            self._bstore((0, 0), self.top_hash)
        else:
            if self._bexists((0, 0)):
                self._bdelete((0, 0))
            self.top_hash = None

    # -- verification ------------------------------------------------------

    def verify_upper(self) -> bool:
        return self._verify(self.height)

    def verify(self) -> bool:
        return self._verify(self.height + 1)

    def _verify(self, max_depth: int) -> bool:
        """Top-down BFS hash check (synctree.erl:549-571)."""
        def check(level: int, bucket: int, up_hash) -> bool:
            hashes = self._fetch(level, bucket)
            if not self._verify_hash(up_hash, hashes):
                return False
            if level == max_depth:
                return True
            return all(check(level + 1, child, h)
                       for child, h in hashes.items())

        return check(1, 0, self.top_hash)


# ---------------------------------------------------------------------------
# Exchange protocol (synctree.erl:352-417)


def orddict_delta(a: Dict, b: Dict) -> List[Tuple[Any, Tuple[Any, Any]]]:
    """Symmetric diff: [(key, (a_val|NONE, b_val|NONE))], key-ordered
    (riak_ensemble_util:orddict_delta)."""
    out = []
    for k in sorted(set(a) | set(b), key=term_key):
        va = a.get(k, NONE)
        vb = b.get(k, NONE)
        if va != vb:
            out.append((k, (va, vb)))
    return out


def compare_gen(height: int, local: Callable, remote: Callable
                ) -> Generator:
    """Level-by-level diff as a generator (so remote accessors may be
    asynchronous).  ``local(level, bucket)`` / ``remote(level, bucket)``
    return a Future resolving to a bucket dict or Corrupted.

    Yields the futures it needs; returns the list of final-level key
    deltas ``[(key, (local_hash|NONE, remote_hash|NONE))]``.  Raises
    Corrupted if either side reports corruption.

    Mirrors ``synctree:compare/exchange/exchange_level/exchange_final``
    (synctree.erl:372-417).
    """
    final = height + 1
    level = 0
    diff: List[int] = [0]
    acc: List = []
    while diff:
        next_diff: List = []
        for bucket in diff:
            a = yield local(level, bucket)
            if isinstance(a, Corrupted):
                raise a
            b = yield remote(level, bucket)
            if isinstance(b, Corrupted):
                raise b
            delta = orddict_delta(a, b)
            if level == final:
                acc.extend(delta)
            else:
                next_diff.extend(bk for bk, _ in delta)
        if level == final:
            break
        diff = next_diff
        level += 1
    return acc


def compare_gen_streamed(height: int, local_many: Callable,
                         remote_many: Callable) -> Generator:
    """Level-batched diff: ONE fetch per side per level, covering every
    bucket the descent needs — the reference's streaming exchange
    (``start_exchange_level`` prefetch hook, exercised by
    synctree_remote.erl:24-38).  Cuts a WAN exchange from
    O(width·height·diffs) round trips to O(height).

    ``local_many/remote_many(pairs)`` take a list of (level, bucket)
    and return a Future resolving to a list of bucket dicts (or
    Corrupted entries).  Raises Corrupted on either side's corruption.
    """
    final = height + 1
    level = 1
    diff: List[int] = [0]
    acc: List = []
    # level 0: the root hashes
    a0 = yield local_many([(0, 0)])
    b0 = yield remote_many([(0, 0)])
    for v in (a0[0], b0[0]):
        if isinstance(v, Corrupted):
            raise v
    if not orddict_delta(a0[0], b0[0]):
        return acc
    while diff and level <= final:
        pairs = [(level, b) for b in diff]
        a_buckets = yield local_many(pairs)
        b_buckets = yield remote_many(pairs)
        next_diff: List[int] = []
        for a, b in zip(a_buckets, b_buckets):
            if isinstance(a, Corrupted):
                raise a
            if isinstance(b, Corrupted):
                raise b
            delta = orddict_delta(a, b)
            if level == final:
                acc.extend(delta)
            else:
                next_diff.extend(bk for bk, _ in delta)
        diff = next_diff
        level += 1
    return acc


def local_compare(t1: SyncTree, t2: SyncTree) -> List:
    """Synchronous compare of two in-process trees
    (synctree.erl:361-369)."""
    from riak_ensemble_tpu.runtime import Future

    def acc_of(tree):
        def fetch(level, bucket):
            fut = Future()
            fut.resolve(tree.exchange_get(level, bucket))
            return fut
        return fetch

    gen = compare_gen(t1.height, acc_of(t1), acc_of(t2))
    result = None
    try:
        fut = next(gen)
        while True:
            fut = gen.send(fut.value)
    except StopIteration as stop:
        result = stop.value
    return result
