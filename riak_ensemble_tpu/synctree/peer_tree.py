"""Tree-server actor: one synctree per peer, serialized access.

Mirrors ``src/riak_ensemble_peer_tree.erl``: a gen_server owning the
tree, tracking the last ``corrupted`` location, with sync ops (get /
insert / exchange_get / top_hash / height / verify) and async ops that
reply by event to the owning peer FSM (``async_repair`` →
``repair_complete``, peer_tree.erl:127-129, 211-212, 264-277).

Message protocol (all sync ops carry a reply Future):
  ('tree_get', key, fut) -> hash | None | 'corrupted'
  ('tree_insert', key, objhash, fut) -> 'ok' | 'corrupted'
  ('tree_exchange_get', level, bucket, fut) -> bucket dict|'corrupted'
  ('tree_top_hash', fut) / ('tree_height', fut)
  ('tree_verify_upper', fut) / ('tree_verify', fut)
  ('tree_rehash', fut) / ('tree_rehash_upper', fut)
  ('tree_async_repair', owner_name)  -> sends ('repair_complete',)
"""

from __future__ import annotations

from typing import Optional, Tuple

from riak_ensemble_tpu.runtime import Actor, Future, Runtime
from riak_ensemble_tpu.synctree.tree import Corrupted, SyncTree


class PeerTree(Actor):
    def __init__(self, runtime: Runtime, name, node, tree: SyncTree) -> None:
        super().__init__(runtime, name, node)
        self.tree = tree
        self.corrupted: Optional[Tuple[int, int]] = None

    def handle(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "xcall":
            # Wire-safe remote call: run the inner op with a local
            # future whose resolution replies over the transport
            # (remote exchange reads, synctree_remote.erl role).
            from riak_ensemble_tpu import msg as msglib

            _, from_, inner = msg
            fut = Future()
            msglib.handle_xcall(self, from_, fut)
            self.handle(tuple(inner) + (fut,))
            return
        if kind == "tree_get":
            _, key, fut = msg
            result = self.tree.get(key)
            if isinstance(result, Corrupted):
                self.corrupted = (result.level, result.bucket)
                fut.resolve("corrupted")
            else:
                fut.resolve(result)
        elif kind == "tree_insert":
            _, key, objhash, fut = msg
            result = self.tree.insert(key, objhash)
            if isinstance(result, Corrupted):
                self.corrupted = (result.level, result.bucket)
                fut.resolve("corrupted")
            else:
                fut.resolve("ok")
        elif kind == "tree_exchange_get_many":
            # Level-batched exchange fetch (the start_exchange_level
            # streaming hook): one message covers a whole level's
            # buckets.
            _, pairs, fut = msg
            out = []
            for level, bucket in pairs:
                result = self.tree.exchange_get(level, bucket)
                if isinstance(result, Corrupted):
                    self.corrupted = (result.level, result.bucket)
                    out.append("corrupted")
                else:
                    out.append(result)
            fut.resolve(out)
        elif kind == "tree_exchange_get":
            _, level, bucket, fut = msg
            result = self.tree.exchange_get(level, bucket)
            if isinstance(result, Corrupted):
                self.corrupted = (result.level, result.bucket)
                fut.resolve("corrupted")
            else:
                fut.resolve(result)
        elif kind == "tree_top_hash":
            msg[1].resolve(self.tree.top_hash)
        elif kind == "tree_height":
            msg[1].resolve(self.tree.height)
        elif kind == "tree_verify_upper":
            msg[1].resolve(self.tree.verify_upper())
        elif kind == "tree_verify":
            msg[1].resolve(self.tree.verify())
        elif kind == "tree_rehash":
            self.tree.rehash()
            msg[1].resolve("ok")
        elif kind == "tree_rehash_upper":
            self.tree.rehash_upper()
            msg[1].resolve("ok")
        elif kind == "tree_async_repair":
            owner = msg[1]
            self._do_repair()
            self.send_local(owner, ("repair_complete",))

    def _do_repair(self) -> None:
        """Repair after detected corruption.

        Segment-level corruption: delete the corrupted segment, then
        full rehash (peer_tree.erl:264-277); the lost keys are healed
        by the subsequent exchange + read-path rewrite.

        Inner-node corruption: the reference merely clears the
        corrupted flag (peer_tree.erl:275-276) which can ping-pong with
        a failing verify_upper; we instead rehash from the leaf data
        (leaves are the truth, so this genuinely repairs inner nodes) —
        a strictly stronger recovery than the reference.
        """
        if self.corrupted is None:
            return
        level, bucket = self.corrupted
        if level == self.tree.height + 1:
            self.tree._bdelete((level, bucket))
        self.tree.rehash()
        self.corrupted = None
