"""Host↔engine bridge: many ensembles served through the batched
device engine.

The scalar actor stack (:mod:`riak_ensemble_tpu.peer`) is the protocol
oracle and the fully-general path (dynamic membership, synctree,
gossip).  This module is the scale path the north star describes: a
host *service* that multiplexes thousands of engine-backed ensembles —

- client ops (kget/kput/kdelete) queue per ensemble and flush as one
  ``full_step`` launch per tick: ``[K, E]`` op matrices, one device
  dispatch for every queued op of every ensemble (the batched analog
  of E leader processes × worker pools);
- the host side keeps what consensus doesn't need on-device: the
  key→slot assignment per ensemble, the payload store (device arrays
  carry int32 handles; real bytes live host-side keyed by handle —
  engine.py's object-store contract), per-ensemble leases (monotonic
  clock), and the failure detector (an ``up`` mask per ensemble);
- leaderless or leader-down ensembles get an election folded into the
  SAME launch (``full_step``'s elect inputs) — the thundering-herd
  re-election after failures is one kernel call, not E timers.

Results come back to client futures after each flush (one d2h per
flush, amortized over every op in the batch).

Launches are TWO-PHASE (pipelined async service execution): an
enqueue half dispatches the fused step + packed-result transfer
without any host read, and a resolve half — up to ``pipeline_depth``
launches later — unpacks, applies the host mirrors, WAL-logs and
fans out the futures, so a round's d2h transfer and host bookkeeping
overlap the next round's device step.  Ordering, the WAL-before-ack
barrier, and corruption→exchange semantics are preserved; see
docs/ARCHITECTURE.md §7 "Two-phase launch pipeline".

Launches are ACTIVE-COLUMN COMPACTED: one hot ensemble forces the
`[K, E]` grid to its queue depth, but the flush gathers down to the
columns that actually hold ops (`[K, A]`, A pow2-bucketed like the K
ladder).  On single-shard engines at low occupancy the fused step
itself runs on the gathered grid (``engine.full_step_sliced`` —
compute, h2d and the packed d2h all scale with the live working
set); mesh engines and mid-occupancy launches keep the full-grid
step and gather only the packed result.  The host unpack scatters
everything back to full width — pure re-indexing, results
bit-identical to the full-width pack (``RETPU_COMPACT=0`` opts
out); in pack-gather mode the corrupt mask stays full width so
inactive columns' integrity flags still reach the scrub path.  See
docs/ARCHITECTURE.md §7 "Active-column compaction and the (K, A)
bucket grid".

Read-modify-writes have a DEVICE FAST PATH: a ``kmodify`` whose
mod-fun resolves against the funref device table (rmw:add & co) runs
as one fused ``OP_RMW`` engine round — read, fun and commit under the
round's seq discipline, conflict-free by construction — instead of
the host's read→fn→CAS retry cycle; such keys hold device-native
int32 values (``_inline_slots``).  Arbitrary mod-funs keep the host
path, with the CAS half chained into the flush that resolved its read
and jittered backoff between conflicted retries.  See
docs/ARCHITECTURE.md §3 "Device-side RMW and the mod-fun table".

Reads have a LEASE-PROTECTED FAST PATH (``RETPU_FAST_READS=0`` or
``Config.trust_lease=False`` opt out): a ``kget``/``kget_vsn``/
``kget_many`` of a keyed slot is answered directly from the leader's
host-resident committed mirror — no ``OP_GET`` row, no flush —
whenever the ensemble's lease is valid on the monotonic clock with a
safety margin (``Config.read_margin``, with lease + margin strictly
inside the follower timeout), the slot has no queued or in-flight
write (the per-slot ``_pending_writes`` index; otherwise the read
falls back to the device round), the row has a live leader and is not
corruption-flagged (flagged rows always take the device round so the
synctree integrity gate still vets the read).  The resolve half
updates every mirror BEFORE completing write futures, so a read
issued after a write's ack always observes it — across pipeline
depth, RMW inline slots and tenant install.  See
docs/ARCHITECTURE.md §9 "Lease-protected reads".
"""

from __future__ import annotations

import errno
import functools
import operator
import os
import random
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from riak_ensemble_tpu import faults, obs
from riak_ensemble_tpu.config import Config
from riak_ensemble_tpu.ops import engine as eng
from riak_ensemble_tpu.parallel import enqueue_native, resolve_native
from riak_ensemble_tpu.runtime import Future, Runtime, Timer
from riak_ensemble_tpu.types import NOTFOUND

#: latency-record fields excluded from every ``total`` sum so the
#: breakdown stays additive: the metadata fields plus the canonical
#: derived-mark list (obs.flightrec.DERIVED_MARKS — 'enqueue' and the
#: resolve_native/resolve_fallback arm attribution, which subdivide
#: the resolve half by which arm ran without double-counting it).
#: Single-sourced from flightrec so the flight recorder's
#: dominant-mark argmax and these sums can never drift apart.
DERIVED_MARKS = ("k", "total") + obs.flightrec.DERIVED_MARKS

#: per-entry field extractor for the per-op SLO fold (C-level
#: attrgetter: one call per taken entry beats a Python loop body)
_OP_SLO_FIELDS = operator.attrgetter("kind", "n", "t_sub", "t_enq")



def _pack_results_body(won, res: eng.KvResult, want_vsn: bool,
                       active_idx=None):
    """Flatten a launch's results into ONE uint8 vector on device.

    The host needs ~7 result arrays per launch; fetching them
    separately costs a device round trip each — ruinous over a
    tunneled/remote device link.  And the link's bandwidth is the
    service's throughput ceiling (measured ~10 MB/s through the
    tunnel), so the six boolean planes travel BIT-PACKED (32x smaller
    than int32) and only the genuinely integer planes ride at full
    width, bitcast into the same buffer: one fused pack, one
    transfer, ~3.6x less data than the all-int32 layout.

    ACTIVE-COLUMN COMPACTION: ``active_idx [A]`` (A pow2-bucketed,
    padding repeats index 0) gathers the per-round client planes down
    to the columns the flush actually scheduled ops into
    (:func:`engine.gather_result_columns`), so the payload scales
    ``O(K·A)`` instead of ``O(K·E)`` — decoupled from the launch
    grid.  The election/lease/corruption planes stay full width: the
    host's lease renewal and scrub path see every column, active or
    not.  ``None`` keeps the historical full-width layout.

    Layout: packbits([won E | quorum_ok E | corrupt E*M |
    committed K*A | get_ok K*A | found K*A]) ++ bitcast_u8(
    [value K*A | (vsn_epoch K*A | vsn_seq K*A)])  (A = E when
    uncompacted).
    """
    if active_idx is not None:
        res = eng.gather_result_columns(res, active_idx)
    flags = jnp.concatenate([
        won.ravel(),
        res.quorum_ok.any(0).ravel(),
        res.tree_corrupt.any(0).ravel(),
        res.committed.ravel(),
        res.get_ok.ravel(),
        res.found.ravel(),
    ]).astype(bool)
    ints = [res.value.ravel()]
    if want_vsn:
        ints += [res.obj_vsn[..., 0].ravel(), res.obj_vsn[..., 1].ravel()]
    ints_u8 = jax.lax.bitcast_convert_type(
        jnp.concatenate(ints), jnp.uint8).ravel()
    return jnp.concatenate([jnp.packbits(flags), ints_u8])


_pack_results = jax.jit(_pack_results_body,
                        static_argnames=("want_vsn",))


@functools.partial(jax.jit, static_argnames=("want_vsn", "sharding"))
def _pack_results_gathered(won, res: eng.KvResult, want_vsn: bool,
                           sharding, active_idx=None):
    """Mesh-aware pack: a sharded step's result planes leave the
    kernel with MIXED shardings ('ens'-sharded [K, E] planes with E
    minor, peer-sharded corrupt masks, replicated scalars).  Raveling
    and concatenating those directly leaves GSPMD no expressible
    output sharding, so it falls back to involuntary full
    rematerialization (replicate-then-repartition) per operand — the
    exact ``spmd_partitioner`` warnings MULTICHIP_r04 recorded, and a
    real ICI/HBM tax on the per-flush d2h critical path at scale.  The
    packed vector is fetched to the host anyway, so gather explicitly:
    one ``with_sharding_constraint`` to fully-replicated per input
    turns the implicit remats into ordinary all-gathers riding ICI,
    and the pack itself runs replicated (no further resharding).
    ``sharding`` is the mesh's fully-replicated NamedSharding
    (static: hashable and compile-time constant).  The active-column
    index vector is constrained replicated too — the column gather
    then runs on the already-replicated planes instead of forcing a
    resharding of its own.
    """
    def con(x):
        return jax.lax.with_sharding_constraint(x, sharding)

    return _pack_results_body(con(won), jax.tree.map(con, res),
                              want_vsn,
                              None if active_idx is None
                              else con(active_idx))


def _backend_mem_bytes() -> float:
    """Live device-memory gauge read (export time only): bytes in
    use on the default jax device; NaN when the backend keeps no
    allocator stats (CPU) — the registry maps NaN to None/``NaN``
    rather than forging a 0."""
    import jax
    stats = jax.local_devices()[0].memory_stats()
    if not stats:
        return float("nan")
    return float(stats.get("bytes_in_use", float("nan")))


def _backend_mem_bytes_per_device() -> Dict[str, float]:
    """Per-device form of :func:`_backend_mem_bytes` for mesh-sharded
    engines: bytes in use on EVERY local device, keyed by device id.
    An 'ens'-shard imbalance (one shard's slabs growing past its
    siblings) is invisible in the default-device gauge."""
    import jax
    out: Dict[str, float] = {}
    for d in jax.local_devices():
        stats = d.memory_stats()
        out[str(d.id)] = (float(stats.get("bytes_in_use",
                                          float("nan")))
                          if stats else float("nan"))
    return out


def mesh_ens_shards(engine) -> int:
    """Number of 'ens'-axis shards the SHARD-WISE pack path applies
    to: >1 only for a mesh engine whose 'peer' axis is unsharded
    (each device then holds complete [e_loc, M, ...] rows, so the
    per-shard pack needs no cross-device traffic at all).  A sharded
    peer axis keeps the gathered pack (`_pack_results_gathered`) —
    the corrupt plane spans peer shards there.  0 = not shard-wise
    (single-device engines included)."""
    mesh = getattr(engine, "mesh", None)
    if mesh is None or int(mesh.shape.get("peer", 1)) != 1:
        return 0
    n = int(mesh.shape["ens"])
    return n if n > 1 else 0


def _make_shardwise_packer(mesh):
    """Compaction-aware SHARD-WISE pack for an 'ens'-sharded mesh
    (peer axis unsharded): ``_pack_results_body`` runs PER ENS-SHARD
    under shard_map — each device bit-packs its own [K, e_loc] result
    block with its own LOCAL active-column gather, and the packed d2h
    payload leaves each device without any all-gather (the gathered
    pack's replication step is exactly the cross-device tax this
    removes).  The active-column index operand is ``[n_sh, A_loc]``
    LOCAL indices (one row per shard, pad 0 — ignored by the host
    unpack), sharded P('ens', None) so row s lands on shard s.  The
    output is the per-shard flat vectors concatenated in shard order
    — :func:`unpack_results_sharded` inverts it.

    Returns a wrapper with the ``(won, res, want_vsn, active_idx)``
    packer signature, ``_cache_size`` summed over the member programs
    (CompileWatch), and ``_shardwise`` = the shard count (the service
    keys its launch records off it).
    """
    from jax.sharding import PartitionSpec as P

    from riak_ensemble_tpu.parallel.mesh import _shard_map

    res_specs = eng.scan_result_specs()
    programs: Dict[Tuple[bool, bool], Any] = {}

    def program(want_vsn: bool, has_active: bool):
        prog = programs.get((want_vsn, has_active))
        if prog is None:
            if has_active:
                def body(won, res, aidx):
                    return _pack_results_body(won, res, want_vsn,
                                              aidx.ravel())
                in_specs = (P("ens"), res_specs, P("ens", None))
            else:
                def body(won, res):
                    return _pack_results_body(won, res, want_vsn)
                in_specs = (P("ens"), res_specs)
            prog = jax.jit(_shard_map(
                body, mesh=mesh, in_specs=in_specs,
                out_specs=P("ens"), check_vma=False))
            programs[(want_vsn, has_active)] = prog
        return prog

    def pack(won, res, want_vsn, active_idx=None):
        if active_idx is None:
            return program(bool(want_vsn), False)(won, res)
        return program(bool(want_vsn), True)(won, res, active_idx)

    pack._cache_size = lambda: sum(p._cache_size()
                                   for p in programs.values())
    pack._shardwise = int(mesh.shape["ens"])
    return pack


def _select_packer(engine):
    """The pack program matching the engine's placement: plain jit for
    single-device engines, the shard-wise form for 'ens'-sharded
    meshes with an unsharded peer axis, the gathered form for the
    rest."""
    mesh = getattr(engine, "mesh", None)
    if mesh is None:
        return _pack_results
    if mesh_ens_shards(engine):
        return _make_shardwise_packer(mesh)
    from jax.sharding import NamedSharding, PartitionSpec
    rep = NamedSharding(mesh, PartitionSpec())
    return functools.partial(_pack_results_gathered, sharding=rep)


def _wide_to_packed_layout(res: eng.KvResult, g: int, w: int,
                           e: int) -> eng.KvResult:
    """Reshape a wide [G, E, W] result into the packed [G*W, E] layout
    (lane-major per group) so :func:`_pack_results`/
    :func:`unpack_results` serve both step flavors unchanged; the
    launch path then routes rows back to op order via the plan's
    (map_g, map_w)."""
    def t(x):
        return x.transpose(0, 2, 1).reshape(g * w, e)
    return res._replace(
        committed=t(res.committed), get_ok=t(res.get_ok),
        found=t(res.found), value=t(res.value),
        obj_vsn=res.obj_vsn.transpose(0, 2, 1, 3).reshape(g * w, e, 2),
        quorum_ok=t(res.quorum_ok))


#: smallest active-column bucket the pack compiles: below 8 columns
#: the payload is mostly headers anyway, and every extra (K, A)
#: bucket is one more XLA program — the floor keeps the warm grid
#: (and test suites full of tiny services) from compiling compaction
#: variants that can't pay for themselves.
A_BUCKET_MIN = 8

#: smallest grid width the SLICED launch engages at: the slice adds
#: fixed per-launch cost (host column slicing, the index upload, the
#: gather/scatter dispatches) that only amortizes when the full-grid
#: step it replaces is itself substantial — measured at the skewed
#: CPU rung: ~3.9x ops/sec at E=512, but a net LOSS at E=64 where
#: the full step is already sub-millisecond.  Below this, compaction
#: still runs in pack-gather mode (the d2h payload cut is ~free).
SLICE_MIN_E = 256


def packed_nbytes(e: int, m: int, k: int, want_vsn: bool,
                  a_width: Optional[int] = None) -> int:
    """Size in bytes of one :func:`_pack_results` payload — the
    per-flush d2h transfer.  ``a_width`` is the compacted column
    count (None = full width E); used for the ``payload_bytes``
    accounting and the bench's full-width-vs-compacted A/B."""
    aw = e if a_width is None else a_width
    nbits = 2 * e + e * m + 3 * k * aw
    return (nbits + 7) // 8 + 4 * k * aw * (3 if want_vsn else 1)


def unpack_results(flat: np.ndarray, e: int, m: int, k: int,
                   want_vsn: bool, active: Optional[np.ndarray] = None,
                   a_width: int = 0, sliced: bool = False):
    """Invert :func:`_pack_results`: one packed uint8 vector →
    ``(won, quorum_ok, corrupt, committed, get_ok, found, value,
    vsn)`` host arrays (the k == 0 planes are None).  Module-level so
    the replica side of the replication group
    (:mod:`riak_ensemble_tpu.parallel.repgroup`) unpacks the SAME
    layout its leader packs.

    With ``active`` (the launch's active column index list, packed at
    ``a_width`` pow2-padded columns), the per-round planes arrive
    compacted ``[K, A]`` and are scattered back through the index
    list into full-width ``[K, E]`` arrays — inactive columns get the
    all-false/zero NOOP results a full-width pack would have carried
    for them, so every downstream consumer (resolve loops, wide
    routing, WAL, replica CRC) is layout-blind.  ``sliced`` marks a
    launch whose step itself ran on the gathered grid: then the
    won/quorum_ok/corrupt planes are A-width too and scatter the
    same way (inactive columns won nothing, renewed nothing and
    flagged nothing — exactly what the full grid reports for
    columns no round touched)."""
    aw = e if active is None else a_width
    hw = aw if sliced else e  # election/quorum/corrupt plane width
    nbits = 2 * hw + hw * m + 3 * k * aw
    bits = np.unpackbits(flat[:(nbits + 7) // 8],
                         count=nbits).astype(bool)
    ints = flat[(nbits + 7) // 8:].copy().view(np.int32)
    boff = ioff = 0

    def take_bits(n, shape=None):
        nonlocal boff
        out = bits[boff:boff + n]
        boff += n
        return out.reshape(shape) if shape is not None else out

    def take_ints(n, shape=None):
        nonlocal ioff
        out = ints[ioff:ioff + n]
        ioff += n
        return out.reshape(shape) if shape is not None else out

    won = take_bits(hw)
    quorum_ok = take_bits(hw)
    corrupt = take_bits(hw * m, (hw, m))
    if sliced and active is not None:
        a = len(active)

        def scat_cols(c, shape):
            out = np.zeros(shape, bool)
            out[active] = c[:a]
            return out
        won = scat_cols(won, (e,))
        quorum_ok = scat_cols(quorum_ok, (e,))
        corrupt = scat_cols(corrupt, (e, m))
    if k:
        committed = take_bits(k * aw, (k, aw))
        get_ok = take_bits(k * aw, (k, aw))
        found = take_bits(k * aw, (k, aw))
        value = take_ints(k * aw, (k, aw))
        vsn = None
        if want_vsn:
            vsn = np.stack([take_ints(k * aw, (k, aw)),
                            take_ints(k * aw, (k, aw))], axis=-1)
        if active is not None:
            a = len(active)

            def scatter(c, dtype):
                out = np.zeros((k, e) + c.shape[2:], dtype)
                out[:, active] = c[:, :a]
                return out
            committed = scatter(committed, bool)
            get_ok = scatter(get_ok, bool)
            found = scatter(found, bool)
            value = scatter(value, np.int32)
            if vsn is not None:
                vsn = scatter(vsn, np.int32)
    else:
        committed = get_ok = found = value = vsn = None
    return won, quorum_ok, corrupt, committed, get_ok, found, value, vsn


def unpack_results_sharded(flat: np.ndarray, e: int, m: int, k: int,
                           want_vsn: bool, n_shards: int,
                           shard_active: Optional[List[np.ndarray]]
                           = None, a_width: int = 0):
    """Invert the shard-wise packer (:func:`_make_shardwise_packer`):
    the payload is ``n_shards`` :func:`_pack_results` blocks in shard
    order, each covering a contiguous ``e_loc = E/n_shards`` column
    slice, compacted per shard through its LOCAL active index list
    (``shard_active[s]``, ≤ ``a_width`` entries; None = every shard
    at full width).  Each block unpacks through the single-shard
    oracle and the full-width planes concatenate back along E — so
    every downstream consumer (mirror scatter, WAL, wide routing,
    replica CRC) stays layout-blind, exactly as with the gathered
    pack."""
    e_loc = e // n_shards
    nb = packed_nbytes(e_loc, m, k, want_vsn,
                       a_width if shard_active is not None else None)
    parts = []
    for s in range(n_shards):
        act = None if shard_active is None else shard_active[s]
        parts.append(unpack_results(
            flat[s * nb:(s + 1) * nb], e_loc, m, k, want_vsn,
            active=act,
            a_width=0 if shard_active is None else a_width))

    def cat(i, axis):
        if parts[0][i] is None:
            return None
        return np.concatenate([p[i] for p in parts], axis=axis)

    return (cat(0, 0), cat(1, 0), cat(2, 0), cat(3, 1), cat(4, 1),
            cat(5, 1), cat(6, 1), cat(7, 1))


def _lane_indices(ent_col: np.ndarray, ent_row0: np.ndarray,
                  ent_len: np.ndarray):
    """Flat (rows, cols) plane indices expanded from the pending
    slab's run descriptors — the numpy fallback's form of the walk
    the C pack/gather passes do natively (one np.repeat-based
    expansion, no Python loop)."""
    ent_of = np.repeat(np.arange(len(ent_len)), ent_len)
    ends = np.cumsum(ent_len)
    starts = ends - ent_len
    within = np.arange(int(ends[-1]) if len(ends) else 0) \
        - starts[ent_of]
    return ent_row0[ent_of] + within, ent_col[ent_of]


def _u8view(x: np.ndarray) -> np.ndarray:
    """Zero-copy uint8 view of a contiguous bool plane (the native
    gather's input form); copies only on a layout surprise."""
    if x.dtype == np.bool_ and x.flags.c_contiguous:
        return x.view(np.uint8)
    return np.ascontiguousarray(x, np.uint8)


def warmup_kernels(svc: "BatchedEnsembleService") -> None:
    """Back-compat wrapper for
    :meth:`BatchedEnsembleService.warmup` (the (K, A)-grid
    pre-compile bench.py and svcnode share)."""
    svc.warmup()


class _LocalEngine:
    """Default engine adapter: the module kernels, single-process jit
    (data-parallel over whatever devices XLA picks).  A
    :class:`~riak_ensemble_tpu.parallel.mesh.ShardedEngine` instance
    slots in here to run the same service over a ('ens', 'peer') mesh.
    """

    init_state = staticmethod(eng.init_state)
    full_step = staticmethod(eng.full_step)
    full_step_donate = staticmethod(eng.full_step_donate)
    full_step_wide = staticmethod(eng.full_step_wide)
    full_step_wide_donate = staticmethod(eng.full_step_wide_donate)
    full_step_sliced = staticmethod(eng.full_step_sliced)
    full_step_sliced_donate = staticmethod(eng.full_step_sliced_donate)
    full_step_wide_sliced = staticmethod(eng.full_step_wide_sliced)
    full_step_wide_sliced_donate = staticmethod(
        eng.full_step_wide_sliced_donate)
    rebuild_trees = staticmethod(eng.rebuild_trees)
    exchange_step = staticmethod(eng.exchange_step)
    reconfig_step = staticmethod(eng.reconfig_step)
    reset_rows = staticmethod(eng.reset_rows)
    verify_trees = staticmethod(eng.verify_trees)


class WallRuntime:
    """Minimal real-time runtime for driving the service outside the
    simulator (bench / production): ``now`` is the monotonic clock.
    It has no event loop, so it only supports caller-driven services —
    construct the service with ``tick=None`` and call ``flush()``."""

    @property
    def now(self) -> float:
        return time.monotonic()

    def schedule(self, delay: float, fn) -> Timer:
        raise RuntimeError(
            "WallRuntime has no event loop; use tick=None and drive "
            "flush() from the caller")


@dataclass(slots=True)
class _PendingOp:
    kind: int
    slot: int
    handle: int
    fut: Future
    key: Any = None
    #: slot write generation at enqueue (puts only) — lets the failed
    #: path tell whether it was the slot's last queued write
    gen: int = 0
    #: CAS expected version (OP_CAS); for OP_RMW, (fun code, 0) — the
    #: exp_epoch plane carries the mod-fun table code and ``handle``
    #: the int32 operand
    exp: Tuple[int, int] = (0, 0)
    #: resolve gets as ("ok", value, vsn) instead of ("ok", value)
    want_vsn: bool = False
    #: enqueue timestamp (perf_counter) — queue-wait latency component
    t_enq: float = 0.0
    #: rounds this entry occupies in the [K, E] op matrix
    n: int = 1
    #: per-op SLO ring (obs.opslo): API-entry submit timestamp
    #: (0 = use t_enq; the settle-time record_flush reads both)
    t_sub: float = 0.0


@dataclass(slots=True)
class _PendingBatch:
    """A struct-of-arrays batch of keyed ops for ONE ensemble sharing
    one Future — the vectorized keyed path (kput_many/kget_many).

    Arrays are COMPACT: keys with no slot never queue a device round —
    their results are pre-filled into the accumulator at submit time —
    and ``pos`` maps each compact row back to its position in the
    caller's key order.  Packing into the flush's [K, E] planes is an
    array slice (no per-op Python), and resolution is positional
    assembly into the shared accumulator.  ``kind`` is uniform per
    batch (all-put, all-get, all-CAS or all-RMW; an RMW batch's
    ``handle`` column carries int32 operands and ``exp_e`` the fun
    code).
    """

    kind: int
    slot: Any          # List[int] [n] compact (plain lists end to
    #                    end: numpy slice assignment packs them into
    #                    the flush planes, and list zips resolve them
    #                    — no per-entry asarray/tolist round trips)
    handle: Any        # List[int] [n] (puts; zeros for gets)
    fut: Future
    pos: Any = None    # List[int] [n] position in the caller's order
    keys: Any = None   # list of key objects (puts: for WAL/recycle)
    gen: Any = None    # List[int] [n] slot generations (puts)
    #: CAS expected versions (OP_CAS batches; None otherwise)
    exp_e: Any = None  # List[int] [n]
    exp_s: Any = None  # List[int] [n]
    accum: Any = None  # shared _BatchAccum across splits
    want_vsn: bool = False
    t_enq: float = 0.0
    n: int = 0
    #: per-op SLO ring (obs.opslo): API-entry submit timestamp
    #: (0 = use t_enq; the settle-time record_flush reads both)
    t_sub: float = 0.0

    def split(self, head_n: int) -> Tuple["_PendingBatch", "_PendingBatch"]:
        """Split into (head, tail) when a flush's K cap lands inside
        the batch; both halves share the Future and accumulator — it
        resolves once the whole batch's results accumulated.  Both
        halves keep the submit/enqueue stamps: each settles as its
        own per-op SLO entry under its OWN flush's id, weights
        conserved."""
        def cut(x, a, b):
            return None if x is None else x[a:b]
        h = _PendingBatch(self.kind, self.slot[:head_n],
                          self.handle[:head_n], self.fut,
                          self.pos[:head_n], cut(self.keys, 0, head_n),
                          cut(self.gen, 0, head_n),
                          cut(self.exp_e, 0, head_n),
                          cut(self.exp_s, 0, head_n), self.accum,
                          self.want_vsn, self.t_enq, head_n,
                          self.t_sub)
        t = _PendingBatch(self.kind, self.slot[head_n:],
                          self.handle[head_n:], self.fut,
                          self.pos[head_n:], cut(self.keys, head_n, None),
                          cut(self.gen, head_n, None),
                          cut(self.exp_e, head_n, None),
                          cut(self.exp_s, head_n, None), self.accum,
                          self.want_vsn, self.t_enq, self.n - head_n,
                          self.t_sub)
        return h, t


class _BatchAccum:
    """Positional result assembly for a (possibly split) batch: each
    chunk fills its rows by original position; the shared Future
    resolves once every position is filled."""

    __slots__ = ("remaining", "results")

    def __init__(self, total: int) -> None:
        self.remaining = total
        self.results: List[Any] = [None] * total

    def fill(self, fut: Future, positions: List[int],
             chunk: List[Any], resolver) -> None:
        res = self.results
        for i, r in zip(positions, chunk):
            res[i] = r
        self.remaining -= len(chunk)
        if self.remaining <= 0 and not fut.done:
            resolver(fut, res)


@dataclass(slots=True)
class _InFlightLaunch:
    """One dispatched-but-unresolved device launch in the service's
    bounded launch pipeline: the enqueue half's outputs (the packed
    result array whose d2h transfer is already running, the latency
    marks so far, the rollback snapshots) plus whatever the resolve
    half needs to finish the round (election vector for the leader
    mirror, wide-plan routing, the flush's taken queue entries or an
    ``execute_async`` future)."""

    flat: Any               # device uint8 packed result (in flight)
    rec: Dict[str, float]   # latency marks (enqueue half)
    k: int                  # caller's round count
    k_eff: int              # rounds in the packed layout (wide: G*W)
    want_vsn: bool
    plan: Any               # WidePlan (wide launches) or None
    w_b: int                # wide plane width (plan only)
    kind_np: Any            # host kind plane (wide routing masks +
    #                         the native mirror scatter); None for
    #                         device-resident execute() planes
    elect: Any              # [E] bool — this launch's election vector
    cand: Any               # [E] int32 — its candidates
    now: float              # runtime.now at enqueue (lease renewal)
    state_snapshot: Any     # pre-launch EngineState (rollback)
    leader_snapshot: Any
    lease_snapshot: Any
    donated: bool           # state buffers donated (no rollback)
    #: active-column compaction: the launch's active ensemble index
    #: list (None = full-width pack) and the pow2-bucketed packed
    #: column count — the resolve half scatters the compact [K, A]
    #: planes back through these.  ``sliced`` marks a launch whose
    #: STEP ran on the gathered [K, A] grid (then the won/quorum/
    #: corrupt planes are A-width too, not just the client planes).
    active: Any = None
    a_width: int = 0
    sliced: bool = False
    #: shard-wise mesh pack (mesh_ens_shards > 0): the packed payload
    #: is n_shards per-shard blocks; ``shard_active`` holds each
    #: shard's LOCAL active index list when the flush compacted
    #: (None = per-shard full width).  Set on EVERY launch of a
    #: shard-wise service — election-only and device-resident
    #: (execute) launches included.
    n_shards: int = 0
    shard_active: Any = None
    #: host slot plane in op order (the native mirror scatter's
    #: companion to ``kind_np``); None for device-resident planes
    op_slot_np: Any = None
    #: flush path: the (ensemble, taken ops) pairs this launch serves
    taken: Any = None
    #: slab enqueue path: the flush's pending-slab record
    #: (ent_col, ent_row0, ent_len run descriptors, taken round
    #: count, per-entry offsets, SLO stamp columns) — the
    #: completion-slab resolve gathers every result plane through
    #: the runs in one pass (ARCHITECTURE §12b)
    lanes: Any = None
    #: execute_async path: the client future + WAL planes + op count
    exec_fut: Any = None
    exec_wal: Any = None
    exec_ops: int = 0
    t_enq: float = 0.0
    #: replication-group extension (repgroup.ReplicatedService): the
    #: launch's group seq, its captured ship inputs (op planes +
    #: elect/lease vectors), its put-lane metadata, and the
    #: corruption-counter snapshot that gates delta eligibility.
    #: ``grp_ship`` is the discriminator: None = not a replicated
    #: leader launch.
    grp_seq: int = 0
    grp_ship: Any = None
    grp_meta: Any = None
    grp_corr0: int = 0
    #: the round's quorum confirmations [E], stashed by the resolve
    #: half for subclass hooks (delta frames ship them)
    quorum_np: Any = None
    #: observability plane: the launch's process-monotonic flush id
    #: (stamped at enqueue, joins leader and replica spans — rides
    #: the replication wire as each entry's trailing field) and the
    #: packed d2h byte count the resolve half measured
    flush_id: int = 0
    payload_nbytes: int = 0
    #: per-op SLO plane: when this launch's enqueue half started —
    #: the flush-JOIN stamp shared by every taken entry (queue_wait
    #: ends here; the 'flush' stage runs from here to settle, so a
    #: serve-time compile inside the dispatch lands in 'flush')
    t_join: float = 0.0


class BatchedEnsembleService:
    """N engine-backed ensembles behind a put/get API.

    ``n_slots`` bounds live keys per ensemble (slots are recycled when
    keys are deleted).  ``tick`` is the flush cadence: lower = lower
    latency, higher = bigger batches; ``tick=None`` disables the timer
    entirely — the caller drives ``flush()`` (bench / WallRuntime mode).
    """

    def __init__(self, runtime: Runtime, n_ens: int, n_peers: int,
                 n_slots: int = 128, tick: Optional[float] = 0.005,
                 max_ops_per_tick: int = 64,
                 config: Optional[Config] = None,
                 engine: Optional[Any] = None,
                 data_dir: Optional[str] = None,
                 wal_sync: str = "fsync",
                 wal_compact_records: int = 1 << 18,
                 dynamic: bool = False,
                 scrub_every_flushes: Optional[int] = None,
                 pipeline_depth: int = 1) -> None:
        import jax.numpy as jnp

        self.runtime = runtime
        self.config = config if config is not None else Config()
        self.n_ens, self.n_peers, self.n_slots = n_ens, n_peers, n_slots
        self.tick = tick
        self.max_k = max_ops_per_tick
        self.engine = engine if engine is not None else _LocalEngine()
        #: result packer matched to the engine's placement (mesh
        #: engines pack per ens-shard or gather explicitly — see
        #: _make_shardwise_packer / _pack_results_gathered)
        self._pack = _select_packer(self.engine)
        #: >0 = the shard-wise mesh pack path: packed payloads are
        #: per-ens-shard blocks and active-column compaction computes
        #: its |A| bucket PER SHARD (compaction-aware sharding)
        self._mesh_shards = mesh_ens_shards(self.engine)
        self.state = self.engine.init_state(n_ens, n_peers, n_slots)
        #: host failure detector input (set_peer_up)
        self.up = np.ones((n_ens, n_peers), dtype=bool)
        self._up_dev = None  # cached device copy (see _up_device)
        #: host mirrors of device ballot state (leader changes only via
        #: elections THIS host requested, membership only via reconfigs
        #: it issued) — election planning costs zero device round trips
        self.leader_np = np.full((n_ens,), -1, dtype=np.int32)
        self.member_np = np.ones((n_ens, n_peers), dtype=bool)
        #: membership-change pipeline, host side: a requested change is
        #: QUEUED while an earlier change is still joint on device,
        #: DESIRED until its joint view installs, PENDING until the
        #: joint view collapses, then live in member_np.  Every
        #: update_members call advances whatever is in flight.
        self._queued_view_np = np.ones((n_ens, n_peers), dtype=bool)
        self._queued_mask = np.zeros((n_ens,), dtype=bool)
        self._desired_view_np = np.ones((n_ens, n_peers), dtype=bool)
        self._desired_mask = np.zeros((n_ens,), dtype=bool)
        self._pending_view_np = np.ones((n_ens, n_peers), dtype=bool)
        self._pending_mask = np.zeros((n_ens,), dtype=bool)
        #: per-ensemble key→slot and free slots
        self.key_slot: List[Dict[Any, int]] = [dict() for _ in range(n_ens)]
        self.free_slots: List[List[int]] = [
            list(range(n_slots)) for _ in range(n_ens)]
        #: per-ensemble slot write generation: bumped on every queued
        #: put, so a delete's deferred recycle can tell whether a later
        #: write re-used the slot (then recycling would orphan it)
        self.slot_gen: List[Dict[int, int]] = [dict() for _ in range(n_ens)]
        #: per-ensemble slot -> handle of the last COMMITTED payload;
        #: lets a committed overwrite/delete release the superseded
        #: handle from ``values`` (otherwise the store grows forever)
        self.slot_handle: List[Dict[int, int]] = [
            dict() for _ in range(n_ens)]
        #: deferred slot recycles: (key, slot, gen) waiting until no
        #: queued op still references the slot (an overflowed get must
        #: never read a recycled slot another key re-used)
        self._recycle_pending: List[List[Tuple[Any, int, int]]] = [
            [] for _ in range(n_ens)]
        #: per-ensemble slots holding DEVICE-NATIVE int32 values (the
        #: kmodify device fast path — OP_RMW commits) rather than
        #: payload-store handles: reads of these slots return the raw
        #: int32, and a committed RMW records the sentinel handle -1
        #: in ``slot_handle`` (blocks recycling like a live handle;
        #: released as a no-op).  A committed put/CAS flips the slot
        #: back to handle storage.
        self._inline_slots: List[set] = [set() for _ in range(n_ens)]
        #: slots with QUEUED (not yet resolved) host-payload writes:
        #: flat per-slot count rows ([E][S], plain Python ints).  The
        #: RMW fast-path eligibility must see these — slot_handle
        #: only reflects COMMITTED writes, and a device RMW racing a
        #: same-flush kput would do int32 arithmetic on the put's
        #: payload HANDLE (silent corruption).  SLAB-ROW layout (not
        #: per-row dicts) so the enqueue/resolve halves note and
        #: un-note whole batches by position; Python lists, not a
        #: numpy plane, on purpose — the accesses are per-slot scalar
        #: bumps, where a list indexes ~3x faster than a numpy cell
        #: (measured; docs/ARCHITECTURE.md §12).  Advisory queue
        #: state (reset with the queues, never persisted); drift only
        #: parks a slot on the safe host path.
        self._queued_handle_writes: List[List[int]] = [
            [0] * n_slots for _ in range(n_ens)]
        #: payload store: handle -> value (device carries handles).
        #: Handles are int32 on device and 0 is the tombstone sentinel,
        #: so released handles are recycled — a monotonically growing
        #: counter would wrap into live (or tombstone) handles after
        #: 2^31 puts.
        self.values: Dict[int, Any] = {}
        self._free_handles: List[int] = []
        self._next_handle = 1
        self.queues: List[List[Any]] = [[] for _ in range(n_ens)]
        #: queued device ROUNDS per ensemble (a batch entry occupies
        #: entry.n rounds) — drives flush depth and the burst trigger
        self._queue_rounds: List[int] = [0] * n_ens
        #: ensembles with queued ops / pending recycles: flush and the
        #: recycle drain iterate THESE, not range(n_ens) — at 10k
        #: ensembles with sparse traffic the O(E) Python sweep per
        #: flush would dwarf the work itself
        self._active: set = set()
        self._recycle_dirty: set = set()
        #: leader leases, host-side: ensemble -> expiry (runtime.now)
        self.lease_until = np.zeros((n_ens,), dtype=float)
        #: lease-protected read fast path (RETPU_FAST_READS=0 or
        #: config.trust_lease=False opt out): reads of keyed slots
        #: serve from the host committed mirror while the lease holds
        self._fast_reads = (os.environ.get("RETPU_FAST_READS", "1")
                            != "0") and self.config.trust_lease
        self._read_margin = self.config.read_margin()
        # the lease-read safety inequality (see Config.validate):
        # every read the leader may still serve expires strictly
        # before any follower's election patience runs out.  Only
        # enforced while the fast path is ON — a config that opted
        # out (trust_lease=False / RETPU_FAST_READS=0) never serves
        # around the round and must keep constructing as before.
        if self._fast_reads:
            self._assert_read_margin()
        #: committed (epoch, seq) per slot — the version a fast
        #: kget_vsn serves.  Invalidated per-row on won elections
        #: (the epoch bump re-versions objects lazily on next device
        #: access); repopulated by every committed write's resolve and
        #: refreshed by device reads.  SLAB layout ([E, S, 2] int32 +
        #: an [E, S] validity plane) rather than per-row dicts: the
        #: native resolve kernel scatters a whole flush's committed
        #: versions into it in one C pass, and the Python fallback
        #: writes the same cells per-op (byte-identical slabs either
        #: way — the tests' equivalence contract).
        self._slot_vsn_np = np.zeros((n_ens, n_slots, 2), np.int32)
        self._slot_vsn_ok = np.zeros((n_ens, n_slots), bool)
        #: committed device-native int32 per inline (RMW) slot — the
        #: value a fast read of a device-native key serves (the engine
        #: arrays hold it; slot_handle only carries the -1 sentinel).
        #: Invalid entries (fresh restore) miss to the device round,
        #: which refreshes the mirror.  Same slab layout as the vsn
        #: mirror, same native/fallback write discipline.
        self._inline_value_np = np.zeros((n_ens, n_slots), np.int32)
        self._inline_value_ok = np.zeros((n_ens, n_slots), bool)
        #: [E, S] storage-class slab kept in lockstep with
        #: ``_inline_slots`` (every add/discard writes both): the
        #: native kernel reads it to route leased-GET refreshes to the
        #: right mirror without touching Python sets mid-pass.
        self._inline_np = np.zeros((n_ens, n_slots), bool)
        #: per-slot count of QUEUED + IN-FLIGHT writes (put/CAS/RMW/
        #: tombstone): a fast read of a slot with any pending write
        #: falls back to the device round — the round orders it after
        #: the writes, and the mirror-before-ack discipline alone only
        #: covers writes whose resolve already ran.  Flat [E][S]
        #: count rows like ``_queued_handle_writes`` (same measured
        #: list-vs-numpy-cell reasoning): the enqueue half notes a
        #: whole batch's slots by position at ``_push`` time and the
        #: completion-slab resolve un-notes every write lane at
        #: settle — the PR 4 fast-read gate sees slab-enqueued
        #: writes the moment they queue.
        self._pending_writes: List[List[int]] = [
            [0] * n_slots for _ in range(n_ens)]
        #: rows whose last resolve flagged synctree corruption: fast
        #: reads bypass to the device round (its integrity gate vets
        #: the read) until the exchange/scrub reports the row synced
        self._corrupt_rows = np.zeros((n_ens,), dtype=bool)
        #: per-row won-election count — the ensemble-health verb's
        #: election-churn signal (a row re-electing every few flushes
        #: is losing its leader; host-mirror-sourced, zero device
        #: rounds).  Reset with the row on lifecycle recycle.
        self.elections_np = np.zeros((n_ens,), dtype=np.int64)
        #: read fast-path observability
        self.read_fastpath_hits = 0
        self.read_fastpath_misses = 0
        #: network front-end backpressure events, incremented by
        #: whatever server fronts this service (svcnode.ServiceServer,
        #: the proxy tier): a client stalled at the per-connection
        #: inflight cap, or dropped because its reply buffer passed
        #: the write cap — the evidence row behind
        #: retpu_svc_backpressure_total
        self.svc_backpressure: Dict[str, int] = {
            "inflight_stalls": 0, "write_buf_drops": 0}
        self.read_fastpath_miss_reasons: Dict[str, int] = {}
        self.flushes = 0
        self.ops_served = 0
        #: integrity-gate detections (replica flagged corrupt in a round)
        self.corruptions = 0
        #: replicas the post-detection exchange actually healed (in-round
        #: read repair usually heals the accessed slot first, so this
        #: counts only residual divergence the sweep fixed)
        self.repairs = 0
        #: dynamic ensemble lifecycle (create_ensemble,
        #: manager.erl:157-166, over fixed device arrays): a logical
        #: ensemble maps to a physical row; ``dynamic=True`` starts
        #: with every row FREE (no members, no elections) and
        #: create/destroy manage the rows.  ``dynamic=False`` keeps
        #: the historical all-rows-live service.
        self.dynamic = dynamic
        self._live = np.full((n_ens,), not dynamic, dtype=bool)
        self._free_rows: List[int] = (
            list(range(n_ens - 1, -1, -1)) if dynamic else [])
        self._ens_names: Dict[Any, int] = {}
        self._row_name: Dict[int, Any] = {}
        if dynamic:
            self.member_np[:] = False
            self.state = self.engine.reset_rows(
                self.state, jnp.ones((n_ens,), bool),
                jnp.zeros((n_ens, n_peers), bool))
        #: leader-status watchers per ensemble (watch_leader)
        self._leader_watchers: Dict[int, List[Any]] = {}
        #: periodic anti-entropy cadence: run :meth:`scrub` every N
        #: flushes (None = on demand only) — the AAE-timer analog.
        #: Watermark, not modulo: a pipelined drain can settle two
        #: launches in one flush (flushes += 2), which would jump a
        #: modulo test past its multiple and silently skip the sweep.
        self.scrub_every_flushes = scrub_every_flushes
        self._scrubbed_at_flush = 0
        self._timer: Optional[Timer] = None
        self._kick_pending = False  # burst flush queued (see _maybe_kick)
        self._jnp = jnp
        #: opt-in wide rounds (RETPU_WIDE=1): a flush whose host op
        #: planes schedule into <= 2 conflict-free wide rounds
        #: launches through ``full_step_wide`` (ops/schedule.py);
        #: deeper duplicate chains and device-resident planes keep the
        #: scalar scan.  Replication note: the schedule is a pure
        #: function of the shipped [K, E] planes, so replica hosts
        #: recompute it bit-identically — but the flag itself must
        #: match across a replication group (a mismatch diverges seq
        #: assignment; the ack CRC detects it and forces re-sync).
        self._wide = os.environ.get("RETPU_WIDE", "") == "1"
        #: launches that actually took the wide path (tests assert the
        #: A/B coverage is real; stats() reports it)
        self.wide_launches = 0
        #: active-column compaction (RETPU_COMPACT=0 opts out): a
        #: flush's packed d2h payload gathers down to the columns that
        #: actually hold ops — O(K·A) instead of O(K·E) — with |A|
        #: pow2-bucketed for compile reuse, mirroring the K ladder.
        #: Pure re-indexing (results bit-identical to the full-width
        #: pack); the corrupt mask stays full width so inactive
        #: columns' integrity flags still reach the scrub path.
        self._compact = os.environ.get("RETPU_COMPACT", "1") != "0"
        #: payload observability: actual packed d2h bytes fetched, the
        #: bytes the full-width [K, E] layout would have moved, and
        #: the mean packed-grid occupancy (a_width / E; 1.0 for
        #: full-width launches) — the compaction win, measurable
        self.payload_bytes = 0
        self.payload_bytes_full_width = 0
        self._occ_sum = 0.0
        self._occ_launches = 0
        #: RMW observability: host-path kmodify CAS attempts that
        #: failed and were retried (write races, plus transient
        #: quorum failures — indistinguishable client-side), and ops
        #: the device mod-fun table served in one round
        self.rmw_conflicts = 0
        self.rmw_device_fastpath = 0
        #: commutative replication lane (docs/ARCHITECTURE.md §18):
        #: gates the leader's merge-section build, kmodify_many's
        #: enqueue-side coalescing and the replicas' early acks as
        #: one knob — =0 is the bit-identical ordered oracle arm
        self._comm_repl = os.environ.get(
            "RETPU_COMM_REPL", "1") != "0"
        #: §18 enqueue-side coalescing: duplicate-key commutative ops
        #: within one kmodify_many absorbed into an already-queued
        #: device row (ops saved, not rows pushed)
        self.rmw_enqueue_coalesced = 0
        #: svc_kmodify_error rate limit (a hot mod-fun bug at flush
        #: rate would otherwise emit a traceback per op per retry)
        self._kmodify_err_at = -1e9
        self._kmodify_err_dropped = 0
        #: backed-off kmodify retries: (due_flush_call, ensemble,
        #: client future, thunk), run at the top of the flush whose
        #: ordinal reaches them — backoff is measured in FLUSH CALLS
        #: (the service's round clock; a wall-clock sleep would stall
        #: caller-driven flush loops).  Tagged with ensemble + future
        #: so destroy_ensemble can fail them: a thunk surviving a
        #: row recycle would commit the dead tenant's kmodify value
        #: into the new tenant.
        self._retry_at: List[Tuple[int, int, Future, Any]] = []
        self._flush_calls = 0
        self._rng = random.Random(0x524D57)
        #: same-flush chaining: set when a resolve enqueues follow-up
        #: ops (a kmodify read's CAS half), consumed by flush() to run
        #: one bounded extra launch cycle inside the same flush call
        self._chain_kick = False
        self._chain_depth = 0
        #: per-flush latency breakdown records (bounded); see
        #: :meth:`latency_breakdown`.  Collection is always on — the
        #: clock reads are nanoseconds against millisecond launches.
        from collections import deque
        self.lat_records = deque(maxlen=1024)
        #: bounded launch pipeline (the two-phase async service
        #: execution): up to ``pipeline_depth`` launches may be
        #: dispatched-but-unresolved, so batch N's packed d2h transfer
        #: and host resolve overlap batch N+1's device step.  Depth 1
        #: keeps the historical fully-synchronous flush.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._inflight_launches: "deque[_InFlightLaunch]" = deque()
        #: jit buffer donation for the fused step's state argument:
        #: back-to-back launches then reuse the E×M(×S) plane buffers
        #: instead of copying them each launch.  RETPU_DONATE=1/0
        #: forces it; default ON off-CPU (CPU keeps the copy so the
        #: launch-failure rollback snapshots stay valid — a donated
        #: launch that fails poisons the state, see _rollback_launch).
        _don = os.environ.get("RETPU_DONATE", "")
        self._donate = (_don == "1" if _don
                        else jax.default_backend() != "cpu")
        #: continuous durability (task: never ack a write that isn't on
        #: disk — basic_backend.erl:120-125): when ``data_dir`` is set,
        #: committed client writes append to a WAL generation paired
        #: with the checkpoint generation, forced down per ``wal_sync``
        #: BEFORE their futures resolve, and the WAL auto-compacts into
        #: a full checkpoint after ``wal_compact_records`` records.
        self.data_dir = data_dir
        self.wal_sync = wal_sync
        self.wal_compact_records = wal_compact_records
        #: WAL-compaction observability: save() is a full checkpoint
        #: and used to run SYNCHRONOUSLY inside flush() the moment the
        #: record bound tripped — a multi-hundred-ms pause billed to
        #: whatever client op was in flight (the mixed p99 spike).
        #: Compaction now waits for an idle flush (queues empty,
        #: pipeline drained) and only runs in-line past a hard 2x
        #: record bound; every run emits an ``svc_compaction`` latency
        #: mark + trace event so the pause is attributable.
        self.wal_compactions = 0
        self.wal_compaction_ms_last = 0.0
        self.wal_compaction_ms_total = 0.0
        self._wal = None
        self._in_save = False
        #: one-time flag: a WAL-enabled service served device-resident
        #: execute() calls (which skip the WAL — see execute())
        self._dev_exec_unlogged = False
        #: graceful storage degradation (docs/ARCHITECTURE.md §15):
        #: an EIO/ENOSPC surfacing from the WAL's durability barrier
        #: flips the service READ-ONLY (writes fail fast at enqueue,
        #: reads keep serving) instead of crashing the serving loop —
        #: the decision record lands here, in health()["storage"],
        #: in a trace event and in the retpu_recovery_* gauges.  A
        #: replicated leader additionally steps down through the
        #: group's existing depose machinery (repgroup override).
        self._storage_degraded: Optional[Dict[str, Any]] = None
        #: WAL OSErrors observed on the ack path (monotonic evidence)
        self.wal_storage_errors = 0
        if data_dir is not None:
            from riak_ensemble_tpu import save as savelib
            from riak_ensemble_tpu.parallel.wal import ServiceWAL

            os.makedirs(data_dir, exist_ok=True)
            meta = os.path.join(data_dir, "META")
            if savelib.read(meta) is None:
                import pickle
                from riak_ensemble_tpu.ops import hash as hashk
                savelib.write(meta, pickle.dumps(
                    {"shape": (n_ens, n_peers, n_slots),
                     "dynamic": dynamic,
                     # informational: META-only restores replay the
                     # WAL through the CURRENT fold, so no rebuild is
                     # needed — trees are born in the current format
                     "hash_format": hashk.HASH_FORMAT}, protocol=4))
            self._wal = ServiceWAL.open_gen(
                data_dir, self._current_ckpt(data_dir), wal_sync)
        #: unified observability plane (riak_ensemble_tpu.obs): a
        #: per-service metrics registry + flight recorder, plus
        #: per-tenant accounting vectorized over ensemble rows.
        #: ``RETPU_OBS=0`` short-circuits every hot-path record; the
        #: answer is cached here so the gate is one attribute test.
        self._obs = obs.enabled()
        #: native single-pass resolve kernel (RETPU_NATIVE_RESOLVE=0
        #: or a missing toolchain pins the pure-Python fallback — the
        #: oracle arm; docs/ARCHITECTURE.md §12).  Resolved at
        #: construction like RETPU_OBS so the bench A/B can hold one
        #: arm per live service.
        self._native_resolve = resolve_native.get()
        self.native_resolve_flushes = 0
        self.fallback_resolve_flushes = 0
        #: slab-resident ENQUEUE half (RETPU_NATIVE_ENQUEUE, default
        #: on; docs/ARCHITECTURE.md §12): pending ops pack into the
        #: [K, E] op planes from flat int32 lanes (one C++ traversal
        #: when the kernel loads, one numpy fancy-index pack
        #: otherwise) and each flush resolves through a per-flush
        #: COMPLETION SLAB — one gathered record per taken round, one
        #: wake per flush — instead of the per-op future fan-out.
        #: ``=0`` pins the historical per-entry pack + per-op resolve
        #: (the oracle arm).  Resolved at construction like the
        #: resolve knob so a bench A/B holds one arm per live service.
        self._enq_slab = enqueue_native.enabled()
        self._native_enqueue = enqueue_native.get()
        self.native_enqueue_flushes = 0
        self.fallback_enqueue_flushes = 0
        #: completion-slab observability: wakes (exactly one per
        #: settled op-carrying flush on the slab path) and the rounds
        #: those wakes fanned in — the "one wake per flush" claim,
        #: measurable (stats()["completion_slab"])
        self.completion_wakes = 0
        self.completion_rows = 0
        #: sharded resolve/enqueue workers (RETPU_RESOLVE_SHARDS,
        #: default 1 = the single-threaded path, the bit-identical
        #: oracle arm; docs/ARCHITECTURE.md §16).  >1 partitions the
        #: per-flush host bookkeeping — pending-slab build,
        #: completion-slab gather, mirror scatter — by contiguous
        #: run-descriptor/column range across a small thread pool.
        #: Every chunk writes/reads disjoint plane cells or mirror
        #: rows, so the sharded result is state-identical to the
        #: serial walk; the native kernels release the GIL, which is
        #: where the parallelism comes from.  The pool is lazy
        #: (created on the first sharded flush) and torn down in
        #: stop().
        self._resolve_shards = max(1, int(
            os.environ.get("RETPU_RESOLVE_SHARDS", "1") or "1"))
        self._resolve_pool: Optional[ThreadPoolExecutor] = None
        self.sharded_flushes = 0
        self.obs_registry = obs.MetricsRegistry()
        self.flight = obs.FlightRecorder(name="svc")
        self._h_flush = self.obs_registry.histogram(
            "retpu_flush_total_ms",
            "settled launch wall time (all marks summed)")
        #: per-op SLO plane (obs.opslo, docs/ARCHITECTURE.md §11):
        #: bounded stamp ring + the client-perceived latency
        #: histogram it feeds (labeled by op kind) — the surface that
        #: answers "what p99 does a kput caller actually see, and was
        #: that tail queue wait, a compile, or the device".
        #: RETPU_SLO_RING=0 disables the ring ALONE (per-op + tenant
        #: latency histograms freeze; counters and the rest of the
        #: obs plane stay live) — the op-trace A/B's off arm.
        self._slo = (obs.OpSloRing()
                     if self._obs and obs.opslo.ring_capacity()
                     else None)
        self._h_op = self.obs_registry.histogram(
            "retpu_op_latency_ms",
            "client-perceived op latency (submit to ack; "
            "mirror-served leased reads included)",
            label_name="kind")
        #: compile/device telemetry: every jitted step/pack variant
        #: the launch path dispatches is wrapped in a CompileWatch
        #: (executable-cache-size deltas) — a first-use compile at an
        #: un-warmed (K, A) bucket increments the serve-phase counter
        #: and logs the bucket shape instead of hiding in dispatch p99
        self._compile_watch: Dict[str, Any] = {}
        self._compile_log: "deque[Dict[str, Any]]" = deque(maxlen=64)
        self._in_warmup = False
        self._c_compile = self.obs_registry.counter(
            "retpu_compile_events_total",
            "XLA executable-cache misses in watched launch programs",
            label_name="phase")
        self._c_compile_ms = self.obs_registry.counter(
            "retpu_compile_ms_total",
            "wall ms spent inside watched calls that compiled",
            label_name="phase")
        #: per-(K)-bucket step cost analysis captured at warmup
        #: (engine.lowered_cost_analysis) — flops/bytes gauges
        self._step_costs: Dict[str, Dict[str, float]] = {}
        if self._obs:
            self._pack = self._watched("pack", self._pack)
        #: per-tenant attribution planes [E] (a tenant is an ensemble
        #: row; named tenants via _row_name / set_tenant_label):
        #: keyed+fast-read ops, committed rounds, put payload bytes,
        #: device launches the row was active in, and a fixed-bucket
        #: latency histogram [E, B] (enqueue → resolve, ms).  All
        #: updates are numpy fancy-index adds over the flush's active
        #: rows — never per-op Python dicts.
        self.tenant_ops = np.zeros((n_ens,), np.int64)
        self.tenant_commits = np.zeros((n_ens,), np.int64)
        self.tenant_bytes = np.zeros((n_ens,), np.int64)
        self.tenant_rounds = np.zeros((n_ens,), np.int64)
        self._tenant_lat = np.zeros(
            (n_ens, len(obs.MS_BUCKETS) + 1), np.int64)
        self._lat_edges = np.asarray(obs.MS_BUCKETS)
        self._tenant_labels: Dict[int, Any] = {}
        self._launches_total = 0
        #: tenant-guard flush admission (docs/ARCHITECTURE.md §14):
        #: None = no caps installed (the bit-identical default path —
        #: flush() takes one falsy test); else {row: rounds-per-flush
        #: cap} with a per-row token bucket (refill = cap per flush,
        #: burst 2x) so a capped tenant keeps steady throughput while
        #: its queue stops forcing every flush to its own batch depth
        self._admission_caps: Optional[Dict[int, int]] = None
        self._admission_tokens: Dict[int, float] = {}
        #: the obs-actuated runtime controller (obs/controller.py):
        #: ALWAYS constructed so its retpu_autotune_* gauge family
        #: registers (zeros while off); it only acts when
        #: RETPU_AUTOTUNE=1 — cached here so the off arm pays one
        #: attribute test per settled flush
        self.controller = obs.RuntimeController(self)
        self._autotune = self.controller.enabled
        self._register_obs_metrics()
        self._schedule()

    # -- dynamic ensemble lifecycle ----------------------------------------

    def create_ensemble(self, name: Any,
                        view: Optional[np.ndarray] = None
                        ) -> Optional[int]:
        """Create a named ensemble at runtime
        (``riak_ensemble_manager:create_ensemble``, manager.erl:157-166,
        over fixed device arrays): allocate a free physical row, reset
        it on device (objects/trees/leader cleared; the row's ballot
        epoch stays monotone so straggler ops of a destroyed tenant
        can never outrank the new one), install the initial view, and
        register the name.  Returns the ensemble id, or None when the
        name is taken or no row is free (capacity backpressure — the
        caller retries after a destroy, the analog of the reference's
        peer-sup limits).  ``view`` defaults to all peers.
        """
        assert self.dynamic, "construct with dynamic=True"
        # lifecycle mutates device rows + host mirrors: settle any
        # in-flight launches first (no-op in steady state)
        self._drain_launches()
        if name in self._ens_names or not self._free_rows:
            return None
        row = self._free_rows.pop()
        view = (np.ones((self.n_peers,), bool) if view is None
                else np.asarray(view, bool))
        assert view.any(), "an ensemble needs at least one member"
        mask = np.zeros((self.n_ens,), bool)
        mask[row] = True
        view_e = np.zeros((self.n_ens, self.n_peers), bool)
        view_e[row] = view
        jnp = self._jnp
        self.state = self.engine.reset_rows(
            self.state, jnp.asarray(mask), jnp.asarray(view_e))
        self.member_np[row] = view
        self._live[row] = True
        self.leader_np[row] = -1
        self.lease_until[row] = 0.0
        self._ens_names[name] = row
        self._row_name[row] = name
        self._reset_row_host(row)
        if self._wal is not None:
            self._wal.log([(("mem", row), (name, view.tolist()))])
        self._emit("svc_create_ensemble", {"name": name, "row": row})
        return row

    def destroy_ensemble(self, name: Any) -> bool:
        """Tear down a named ensemble and recycle its row: queued ops
        fail (request_failed semantics), payload handles release, the
        device row is wiped eagerly, and the row returns to the free
        pool.  Returns False for unknown names."""
        assert self.dynamic, "construct with dynamic=True"
        # settle first: an in-flight launch's WAL/settle reads this
        # row's payload handles, which the reset below releases
        self._drain_launches()
        row = self._ens_names.pop(name, None)
        if row is None:
            return False
        # the name label dies with the tenant (the row-reset below
        # only sees the post-delete fallback label) — unless a
        # sibling row still serves under it
        self._drop_tenant_series(row)
        del self._row_name[row]
        for op in self.queues[row]:
            self._fail_entry(row, op)
        self.queues[row] = []
        self._queue_rounds[row] = 0
        self._active.discard(row)
        self._purge_retries(row)
        mask = np.zeros((self.n_ens,), bool)
        mask[row] = True
        jnp = self._jnp
        self.state = self.engine.reset_rows(
            self.state, jnp.asarray(mask),
            jnp.zeros((self.n_ens, self.n_peers), bool))
        self.member_np[row] = False
        self._live[row] = False
        self.leader_np[row] = -1
        self.lease_until[row] = 0.0
        self._reset_row_host(row)
        self._free_rows.append(row)
        if self._wal is not None:
            # The destroyed tenant's kv records must not replay into
            # the recycled row; its membership row is now empty.
            self._wal.log([(("mem", row), (None, [False] * self.n_peers))])
            self._wal.delete([("kv", row, s)
                              for s in range(self.n_slots)])
        self._emit("svc_destroy_ensemble", {"name": name, "row": row})
        return True

    def resolve_ensemble(self, name: Any) -> Optional[int]:
        """Name → ensemble id (the manager's directory read)."""
        return self._ens_names.get(name)

    def _reset_row_host(self, row: int) -> None:
        """Clear a row's keyed-store host mirrors, releasing payloads,
        and wipe per-row control state a recycled tenant must not
        inherit: the membership-change pipeline (a dead tenant's
        desired/queued view would otherwise re-propose over the new
        tenant) and the failure-detector marks (an old peer-down flag
        would block the new tenant's elections)."""
        for h in self.slot_handle[row].values():
            self._release_handle(h)
        self.key_slot[row] = {}
        self.free_slots[row] = list(range(self.n_slots))
        self.slot_gen[row] = {}
        self.slot_handle[row] = {}
        self._inline_slots[row] = set()
        self._inline_np[row] = False
        self._queued_handle_writes[row] = [0] * self.n_slots
        self._recycle_pending[row] = []
        self._slot_vsn_ok[row] = False
        self._inline_value_ok[row] = False
        self._pending_writes[row] = [0] * self.n_slots
        self._corrupt_rows[row] = False
        self.elections_np[row] = 0
        # a recycled row starts with no watchers (the reference cleans
        # up watchers with their watched peer)
        self._leader_watchers.pop(row, None)
        self._desired_mask[row] = False
        self._queued_mask[row] = False
        self._pending_mask[row] = False
        self.up[row] = True
        self._up_dev = None
        # per-tenant attribution must not leak across row recycles —
        # the new tenant starts with a clean ledger, and any LABELED
        # registry series recorded under the old tenant's label go
        # with it (labeled children otherwise persist forever — a
        # successor tenant reusing the label would inherit a dead
        # tenant's samples; registry.remove_labeled is the hook).
        # Read the label BEFORE the ledger row is zeroed; a label a
        # sibling row still serves under survives the recycle.
        self._drop_tenant_series(row)
        self.tenant_ops[row] = 0
        self.tenant_commits[row] = 0
        self.tenant_bytes[row] = 0
        self.tenant_rounds[row] = 0
        self._tenant_lat[row] = 0
        self._tenant_labels.pop(row, None)

    # -- client API --------------------------------------------------------

    def _dead(self, ens: int) -> bool:
        """Ops addressed to a free/destroyed row fail fast (the
        unknown-ensemble rejection of the reference client)."""
        return self.dynamic and not self._live[ens]

    def kput(self, ens: int, key: Any, value: Any) -> Future:
        """Quorum-replicated write; resolves ('ok', handle_vsn) or
        'failed' (no slot / no quorum this flush)."""
        fut = Future()
        if self._dead(ens):
            fut.resolve("failed")
            return fut
        slot = self._slot_for(ens, key, allocate=True)
        if slot is None:
            fut.resolve("failed")
            return fut
        handle = self._alloc_handle()
        self.values[handle] = value
        gen = self.slot_gen[ens].get(slot, 0) + 1
        self.slot_gen[ens][slot] = gen
        self._note_handle_write(ens, slot)
        self._push(ens, _PendingOp(eng.OP_PUT, slot, handle, fut,
                                   key, gen))
        return fut

    def kput_many(self, ens: int, keys: List[Any],
                  values: List[Any]) -> Future:
        """Vectorized keyed writes: N puts for one ensemble behind ONE
        future, resolving to a list of per-key results (('ok', vsn) |
        'failed') in key order.  The queue entry is struct-of-arrays —
        flush packs it into the [K, E] planes as array slices and
        resolves it with sliced result columns, so the per-op Python
        cost of the scalar kput path (Future + op object + per-op
        resolve) is amortized over the batch.  Duplicate keys
        serialize in order (sequential device rounds); keys that can't
        get a slot resolve 'failed' immediately and consume no device
        round."""
        fut = Future()
        t_sub = time.perf_counter()  # per-op SLO: submit stamp (the
        n = len(keys)                # slot/handle assignment below is
        #                              the op's 'assign' stage)
        if n != len(values):
            # trust-boundary check (this surface is network-exposed
            # via svcnode): zip truncation would leave accumulator
            # positions unfillable and hang the batch future forever
            raise ValueError(
                f"kput_many: {n} keys vs {len(values)} values")
        if self._dead(ens) or n == 0:
            fut.resolve(["failed"] * n)
            return fut
        accum = _BatchAccum(n)
        # hot path (the keyed ceiling is per-key host Python —
        # VERDICT r3 weak #3), vectorized per ARCHITECTURE §12b rung
        # 1: key→slot is ONE dict pass whose loop body is dict work
        # alone; handle allocation is one slab operation
        # (_alloc_handles), the payload store one bulk update, the
        # queued-handle-write notes a by-position bump pass.  Only
        # the generation bump stays order-sensitive — duplicate keys
        # in one batch must observe each other's bump.
        slot_l: List[int] = []
        pos_l: List[int] = []
        live_keys: List[Any] = []
        miss_pos: List[int] = []
        ks = self.key_slot[ens]
        fs = self.free_slots[ens]
        for i, key in enumerate(keys):
            s = ks.get(key)
            if s is None:
                if not fs:
                    miss_pos.append(i)   # capacity-fail: no round
                    continue
                s = fs.pop()
                ks[key] = s
            slot_l.append(s)
            pos_l.append(i)
            live_keys.append(key)
        m = len(slot_l)
        handle_l = self._alloc_handles(m)
        self.values.update(zip(handle_l,
                               (values[i] for i in pos_l)))
        sg = self.slot_gen[ens]
        gen_l: List[int] = []
        g_append = gen_l.append
        for s in slot_l:
            g = sg.get(s, 0) + 1
            sg[s] = g
            g_append(g)
        qh = self._queued_handle_writes[ens]
        for s in slot_l:
            qh[s] += 1
        if miss_pos:
            accum.fill(fut, miss_pos, ["failed"] * len(miss_pos),
                       self._safe_resolve)
        if live_keys:
            # fields stay PLAIN LISTS end to end: the flush's lane
            # build extends them straight into its flat lanes, the
            # WAL encoder walks them, and the oracle arm zips them
            self._push(ens, _PendingBatch(
                eng.OP_PUT, slot_l, handle_l, fut, pos_l, live_keys,
                gen_l, accum=accum, n=m, t_sub=t_sub))
        return fut

    def kupdate_many(self, ens: int, keys: List[Any],
                     expected_vsns: List[Tuple[int, int]],
                     values: List[Any]) -> Future:
        """Vectorized CAS batch (the kupdate/kput_once semantics per
        key): commit values[i] iff keys[i]'s current version equals
        expected_vsns[i] ((0, 0) = create-if-missing).  One future,
        per-key ('ok', new_vsn) | 'failed' in order."""
        fut = Future()
        t_sub = time.perf_counter()
        n = len(keys)
        if n != len(values) or n != len(expected_vsns):
            raise ValueError(
                f"kupdate_many: {n} keys vs {len(expected_vsns)} vsns "
                f"vs {len(values)} values")
        if self._dead(ens) or n == 0:
            fut.resolve(["failed"] * n)
            return fut
        accum = _BatchAccum(n)
        # same vectorized shape as kput_many: one dict pass for
        # key→slot (CAS expectations ride it — per-key ints, no
        # allocation), one handle slab op, one store update, one
        # generation pass, one queued-handle note
        slot: List[int] = []
        pos: List[int] = []
        exp_e: List[int] = []
        exp_s: List[int] = []
        live_keys: List[Any] = []
        miss_pos: List[int] = []
        ks = self.key_slot[ens]
        fs = self.free_slots[ens]
        for i, (key, vsn) in enumerate(zip(keys, expected_vsns)):
            s = ks.get(key)
            if s is None:
                if not fs:
                    miss_pos.append(i)
                    continue
                s = fs.pop()
                ks[key] = s
            slot.append(s)
            pos.append(i)
            exp_e.append(int(vsn[0]))
            exp_s.append(int(vsn[1]))
            live_keys.append(key)
        m = len(slot)
        handle = self._alloc_handles(m)
        self.values.update(zip(handle, (values[i] for i in pos)))
        sg = self.slot_gen[ens]
        gen: List[int] = []
        g_append = gen.append
        for s in slot:
            g = sg.get(s, 0) + 1
            sg[s] = g
            g_append(g)
        qh = self._queued_handle_writes[ens]
        for s in slot:
            qh[s] += 1
        if miss_pos:
            accum.fill(fut, miss_pos, ["failed"] * len(miss_pos),
                       self._safe_resolve)
        if live_keys:
            self._push(ens, _PendingBatch(
                eng.OP_CAS, slot, handle, fut, pos, live_keys, gen,
                exp_e, exp_s, accum, n=m, t_sub=t_sub))
        return fut

    def kdelete_many(self, ens: int, keys: List[Any]) -> Future:
        """Vectorized tombstone writes: one future, per-key
        ('ok', vsn) | ('ok', NOTFOUND) (no such key) | 'failed' in
        order.  Committed slots recycle like scalar kdelete."""
        fut = Future()
        t_sub = time.perf_counter()
        n = len(keys)
        if self._dead(ens) or n == 0:
            # dead-ensemble rejection, same as scalar kdelete and the
            # other batch ops — never a fake 'ok' for an unserved op
            fut.resolve(["failed"] * n)
            return fut
        accum = _BatchAccum(n)
        slot: List[int] = []
        gen: List[int] = []
        pos: List[int] = []
        live_keys: List[Any] = []
        miss_pos: List[int] = []
        sg = self.slot_gen[ens]
        ks = self.key_slot[ens]  # one dict pass (no allocation)
        for i, key in enumerate(keys):
            s = ks.get(key)
            if s is None:
                miss_pos.append(i)
                continue
            slot.append(s)
            pos.append(i)
            gen.append(sg.get(s, 0))
            live_keys.append(key)
        if miss_pos:
            accum.fill(fut, miss_pos, [("ok", NOTFOUND)] * len(miss_pos),
                       self._safe_resolve)
        if live_keys:
            m = len(live_keys)
            batch = _PendingBatch(
                eng.OP_PUT, slot, [0] * m, fut, pos, live_keys, gen,
                accum=accum, n=m, t_sub=t_sub)
            self._push(ens, batch)
            # deferred recycle per committed tombstone, keyed off the
            # batch result list (the _recycle_on_ok discipline)
            keyslots = list(zip(live_keys, slot, gen, pos))

            def recycle(results):
                if not isinstance(results, list):
                    return
                for key, s, g, p in keyslots:
                    r = results[p]
                    if isinstance(r, tuple) and r[0] == "ok":
                        self._queue_recycle(ens, (key, s, g))
            fut.add_waiter(recycle)
        return fut

    def kget_many(self, ens: int, keys: List[Any],
                  want_vsn: bool = False) -> Future:
        """Vectorized keyed reads: one future resolving to a list of
        (('ok', value|NOTFOUND) | 'failed') in key order (with
        ``want_vsn`` each hit is ('ok', value, (epoch, seq)) — the
        kget_vsn contract).  Unknown keys resolve ('ok', NOTFOUND)
        immediately and consume no device round."""
        fut = Future()
        t_sub = time.perf_counter()
        n = len(keys)
        if self._dead(ens) or n == 0:
            fut.resolve(["failed"] * n)
            return fut
        accum = _BatchAccum(n)
        slot_l: List[int] = []
        pos_l: List[int] = []
        miss_pos: List[int] = []
        fast_pos: List[int] = []
        fast_res: List[Any] = []
        ks = self.key_slot[ens]
        # ensemble-level fast-path gate checked ONCE for the batch;
        # per-key conditions (pending write, mirror coverage) below
        ens_reason = self._fast_read_ok(ens, self.runtime.now)
        for i, key in enumerate(keys):
            s = ks.get(key)
            if s is None:
                miss_pos.append(i)
                continue
            if ens_reason is None:
                reason, res = self._fast_read_result(ens, s, want_vsn)
            else:
                reason, res = ens_reason, None
            if self._count_fast(ens, reason):
                fast_pos.append(i)
                fast_res.append(res)
            else:
                slot_l.append(s)
                pos_l.append(i)
        if miss_pos:
            nf = (("ok", NOTFOUND, (0, 0)) if want_vsn
                  else ("ok", NOTFOUND))
            accum.fill(fut, miss_pos, [nf] * len(miss_pos),
                       self._safe_resolve)
        if fast_pos:
            accum.fill(fut, fast_pos, fast_res, self._safe_resolve)
        if slot_l:
            m = len(slot_l)
            self._push(ens, _PendingBatch(
                eng.OP_GET, slot_l, [0] * m, fut, pos_l, accum=accum,
                want_vsn=want_vsn, n=m, t_sub=t_sub))
        return fut

    def kget(self, ens: int, key: Any) -> Future:
        """Linearizable read; resolves ('ok', value|NOTFOUND) or
        'failed'.  Served from the leader's committed host mirror —
        no device round — while the lease-protected fast path's
        conditions hold (see the module docstring); otherwise the read
        rides an ``OP_GET`` round like always."""
        fut = Future()
        if self._dead(ens):
            fut.resolve("failed")
            return fut
        slot = self._slot_for(ens, key, allocate=False)
        if slot is None:
            fut.resolve(("ok", NOTFOUND))
            return fut
        hit, res = self._try_fast(ens, slot, False)
        if hit:
            self._safe_resolve(fut, res)
            return fut
        self._push(ens, _PendingOp(eng.OP_GET, slot, 0, fut))
        return fut

    def kget_vsn(self, ens: int, key: Any) -> Future:
        """Read returning the version too: ('ok', value|NOTFOUND,
        (epoch, seq)) — the handle a subsequent :meth:`kupdate` /
        :meth:`ksafe_delete` CAS needs.  An absent key reads as
        ('ok', NOTFOUND, (0, 0)); CAS'ing against (0, 0) is
        create-if-missing (the kput_once semantics)."""
        fut = Future()
        if self._dead(ens):
            fut.resolve("failed")
            return fut
        slot = self._slot_for(ens, key, allocate=False)
        if slot is None:
            fut.resolve(("ok", NOTFOUND, (0, 0)))
            return fut
        hit, res = self._try_fast(ens, slot, True)
        if hit:
            self._safe_resolve(fut, res)
            return fut
        self._push(ens, _PendingOp(eng.OP_GET, slot, 0, fut,
                                   want_vsn=True))
        return fut

    def kupdate(self, ens: int, key: Any, expected_vsn: Tuple[int, int],
                value: Any) -> Future:
        """Compare-and-swap (do_kupdate, peer.erl:259-270): commit
        `value` iff the key's current version equals `expected_vsn`
        (from a :meth:`kput`/:meth:`kupdate` result or
        :meth:`kget_vsn`); (0, 0) on an absent key is
        create-if-missing (kput_once).  Resolves ('ok', new_vsn) or
        'failed' (version mismatch / no quorum)."""
        fut = Future()
        if self._dead(ens):
            fut.resolve("failed")
            return fut
        slot = self._slot_for(ens, key, allocate=True)
        if slot is None:
            fut.resolve("failed")
            return fut
        handle = self._alloc_handle()
        self.values[handle] = value
        gen = self.slot_gen[ens].get(slot, 0) + 1
        self.slot_gen[ens][slot] = gen
        self._note_handle_write(ens, slot)
        self._push(ens, _PendingOp(
            eng.OP_CAS, slot, handle, fut, key, gen,
            exp=(int(expected_vsn[0]), int(expected_vsn[1]))))
        return fut

    def kput_once(self, ens: int, key: Any, value: Any) -> Future:
        """Create-if-missing (do_kput_once, peer.erl:278-284): the
        (0, 0)-expected CAS — commits only when the key holds nothing
        (true absence or a tombstone).  Resolves ('ok', vsn) |
        'failed' (exists / no quorum)."""
        return self.kupdate(ens, key, (0, 0), value)

    def ksafe_delete(self, ens: int, key: Any,
                     expected_vsn: Tuple[int, int]) -> Future:
        """Version-guarded delete (ksafe_delete): CAS to a tombstone."""
        fut = Future()
        if self._dead(ens):
            fut.resolve("failed")
            return fut
        slot = self._slot_for(ens, key, allocate=False)
        if slot is None:
            fut.resolve("failed")  # nothing at this key to guard
            return fut
        op = _PendingOp(eng.OP_CAS, slot, 0, fut, key,
                        self.slot_gen[ens].get(slot, 0),
                        exp=(int(expected_vsn[0]), int(expected_vsn[1])))
        self._push(ens, op)
        self._recycle_on_ok(fut, ens, key, slot)
        return fut

    def kdelete(self, ens: int, key: Any) -> Future:
        """Tombstone write (slot recycled once committed)."""
        fut = Future()
        if self._dead(ens):
            fut.resolve("failed")
            return fut
        slot = self._slot_for(ens, key, allocate=False)
        if slot is None:
            fut.resolve(("ok", NOTFOUND))
            return fut
        handle = 0  # 0 = tombstone handle
        # key rides along for the WAL record (replay must drop the
        # checkpoint-era key→slot mapping this delete invalidated);
        # gen matches the slot so a failed delete can't queue a bogus
        # recycle through _fail_op.
        op = _PendingOp(eng.OP_PUT, slot, handle, fut, key,
                        self.slot_gen[ens].get(slot, 0))
        self._push(ens, op)
        self._recycle_on_ok(fut, ens, key, slot)
        return fut

    # -- version-preserving bulk install (tenant handoff) ------------------

    def install_objs(self, ens: int,
                     items: List[Tuple[Any, Tuple[int, int], Any]]
                     ) -> List[Any]:
        """Install keyed objects WITH their versions — the
        version-continuity half of a placement move (the reference's
        membership changes move consensus while objects keep their
        {epoch, seq}: a client's CAS token survives member replacement,
        replace_members_test.erl:26-30, doc/Readme.md:156-167).  A
        re-ingest through kput would mint fresh versions and void
        every outstanding CAS token.

        ``items``: ``[(key, (epoch, seq), payload), ...]``.  Applied
        synchronously: slots/handles allocate host-side, the objects
        scatter into EVERY replica lane of the row (they are committed
        data from the previous owner), the row's trees rebuild, and
        the row's ballot epoch rises to the max installed epoch with
        the leader cleared — the next election runs at a strictly
        higher epoch, so post-move writes always version-dominate the
        installed objects.  Committed to the WAL with the real
        versions.  Returns per-item ``("ok", (epoch, seq))`` |
        ``"failed"`` (no slot).
        """
        self._drain_launches()  # installs splice device state directly
        results, applied = self._allocate_install(ens, items)
        if applied:
            self._apply_installed(ens, applied,
                                  self._install_lead(ens))
        return results

    def _install_lead(self, ens: int) -> int:
        """The install's leadership decision, made ONCE (on a
        replication-group leader it ships with the frame — deciding
        per lane from local host mirrors would diverge the lanes):
        a leaderless row gets the first view member declared at the
        installed epoch (no election bump → no stale-read re-version
        → CAS tokens survive); a live row keeps its leader (-1) —
        the late-merge path must not stomp a serving leader."""
        return (int(np.argmax(self.member_np[ens]))
                if int(self.leader_np[ens]) < 0 else -1)

    def _allocate_install(self, ens: int, items):
        """Host-side half: slots + handles for the installable items
        (separated so a replication-group leader can ship the exact
        allocation to its replicas — independent allocation could
        diverge free-list orders across lanes)."""
        results: List[Any] = []
        applied: List[Tuple] = []
        for key, (ve, vs), payload in items:
            slot = self._slot_for(ens, key, allocate=True)
            if slot is None:
                results.append("failed")
                continue
            handle = self._alloc_handle()
            self.values[handle] = payload
            applied.append((key, int(slot), int(handle), int(ve),
                            int(vs), payload))
            results.append(("ok", (int(ve), int(vs))))
        return results, applied

    def _apply_installed(self, ens: int, applied: List[Tuple],
                         lead: int = -1,
                         extra_records: Optional[List[Tuple]] = None
                         ) -> None:
        """Device + mirror + WAL application of an allocation from
        :meth:`_allocate_install` (verbatim on replicas).  ``lead``
        is the leader's :meth:`_install_lead` decision (-1 = keep);
        ``extra_records`` overrides :meth:`_wal_extra_records` in the
        install's durability barrier — a replication-group replica
        passes its REAL (promised, ge, seq, cfg) (its inherited
        leader-side fields would write regressed group meta)."""
        jnp = self._jnp
        ens = int(ens)
        slots = np.asarray([a[1] for a in applied], np.int32)
        eps = np.asarray([a[3] for a in applied], np.int32)
        sqs = np.asarray([a[4] for a in applied], np.int32)
        hds = np.asarray([a[2] for a in applied], np.int32)
        st = self.state
        s_j = jnp.asarray(slots)
        # index (int, :, array) puts the advanced axes FIRST — the
        # update target is [n_items, M], so the per-item vectors need
        # an explicit lane axis (a bare [n_items] only broadcast for
        # the single-item installs the early tests happened to do)
        st = st._replace(
            obj_epoch=st.obj_epoch.at[ens, :, s_j].set(
                jnp.asarray(eps)[:, None]),
            obj_seq=st.obj_seq.at[ens, :, s_j].set(
                jnp.asarray(sqs)[:, None]),
            obj_val=st.obj_val.at[ens, :, s_j].set(
                jnp.asarray(hds)[:, None]))
        # Version continuity requires NO epoch change on first touch:
        # a read at a ballot epoch above the objects' epochs triggers
        # the stale-epoch rewrite (update_key), re-versioning every
        # object and voiding the tokens this install exists to
        # preserve.  So: raise the row's ballot epoch to the max
        # installed epoch and DECLARE leadership at that epoch (the
        # row is brand-new to this service — no straggler writer at
        # that epoch can exist here, the old owner's row was destroyed
        # before the offer), and raise the per-row seq counter past
        # the installed seqs so same-epoch writes version-dominate.
        # If the recycled row's epoch already EXCEEDS the installed
        # max (its previous tenant's straggler fence), the fence wins:
        # first reads re-version, exactly like the reference after an
        # election (update_key, peer.erl:1564-1596).
        row_max = jnp.asarray(int(eps.max()) if len(eps) else 0,
                              jnp.int32)
        st = st._replace(
            epoch=st.epoch.at[ens, :].set(
                jnp.maximum(st.epoch[ens], row_max)),
            obj_seq_ctr=st.obj_seq_ctr.at[ens].set(
                jnp.maximum(st.obj_seq_ctr[ens],
                            jnp.asarray(int(sqs.max())
                                        if len(sqs) else 0,
                                        jnp.int32))))
        if lead >= 0:
            st = st._replace(leader=st.leader.at[ens].set(lead))
        mask = np.zeros((self.n_ens, self.n_peers), bool)
        mask[ens] = True
        self.state = self.engine.rebuild_trees(st, jnp.asarray(mask))
        for key, slot, handle, ve, vs, payload in applied:
            self._inline_slots[ens].discard(slot)
            self._inline_np[ens, slot] = False
            self._inline_value_ok[ens, slot] = False
            # installs carry their committed versions: the fast
            # path's vsn mirror adopts them (CAS-token continuity
            # extends to leased reads)
            self._slot_vsn_np[ens, slot] = (ve, vs)
            self._slot_vsn_ok[ens, slot] = True
            old = self.slot_handle[ens].pop(slot, 0)
            if old and old != handle:
                # values-only drop, NEVER the handle pool: the handle
                # numbers are the allocating leader's; pooling them on
                # a replica would let a later promotion re-allocate a
                # number the leader still has live (cross-key payload
                # corruption).  The handle number leaks; numbers are
                # 31-bit and installs are rare.
                self.values.pop(old, None)
            self.values[handle] = payload
            self.slot_handle[ens][slot] = handle
            if handle >= self._next_handle:
                self._next_handle = handle + 1
            self.key_slot[ens][key] = slot
        if lead >= 0:
            self.leader_np[ens] = lead
            self.lease_until[ens] = 0.0
        self._up_dev = None
        if self._wal is not None:
            recs = [(("kv", ens, slot),
                     (key, handle, ve, vs, payload, False))
                    for key, slot, handle, ve, vs, payload in applied]
            self._wal.log(recs + (extra_records
                                  if extra_records is not None
                                  else self._wal_extra_records()))

    def kmodify(self, ens: int, key: Any, mod_fun: Any, default: Any,
                retries: int = 8) -> Future:
        """Server-side modify — the batched analog of the put FSM's
        kmodify (do_kmodify, peer.erl:303-317; modify FSM
        :1404-1416): read the key, apply ``mod_fun`` to the current
        value (``default`` when absent), and commit the result under
        the read version's CAS guard, retrying the whole
        read→fn→CAS cycle on conflict (another writer's commit landed
        between our read and our write — the seq discipline the
        reference gets from running the fun inside the leader's FSM).

        ``mod_fun`` is a callable or a wire-safe funref
        (:mod:`riak_ensemble_tpu.funref`), called as
        ``mod_fun(vsn, current_value) -> new_value | "failed"`` —
        the actor plane's signature, except ``vsn`` is the version
        the value was READ at, not the prospective commit version
        (the batched engine assigns versions on device at commit
        time, so they are unknowable host-side; root-style vsn-pinned
        merges use the CAS guard itself for that).  Returning
        "failed" (or raising) aborts without writing.  Resolves
        ('ok', new_vsn) | 'failed'.

        The DEVICE FAST PATH: a ``mod_fun`` funref that resolves to a
        mod-fun table entry (:func:`funref.device_entry` — rmw:add,
        rmw:max, ..., one bound int32 operand) on a key holding a
        device-native value (fresh, or previously written by the fast
        path) runs as ONE ``OP_RMW`` engine round instead: the read,
        the fun and the commit fuse under the round's seq discipline,
        so the op costs one flush and can never CAS-conflict.
        Requires ``default == 0`` (the engine reads absence as 0);
        anything else keeps the host path below.

        The host path's chain rides the flush cadence: each attempt's
        read and CAS are ordinary queued ops, so concurrent kmodifys
        of one key serialize through device-round order and the
        losers retry — N concurrent increments converge to exactly
        +N.  The CAS half of an attempt is CHAINED into the flush
        that resolved its read (flush() runs a bounded extra launch
        cycle when a resolve enqueued follow-ups), and conflicted
        retries back off by a jittered number of flushes.
        """
        from riak_ensemble_tpu import funref

        fut = Future()
        try:
            fn = funref.resolve(mod_fun)
        except ValueError:
            fut.resolve("failed")
            return fut
        if self._dead(ens):
            fut.resolve("failed")
            return fut
        dev = funref.device_entry(mod_fun)
        if dev is not None and funref.is_int32(default) \
                and int(default) == 0:
            slot = self._slot_for(ens, key, allocate=True)
            if slot is None:
                fut.resolve("failed")
                return fut
            if self._rmw_eligible(ens, slot):
                # A device RMW cannot CAS-conflict, so a failed round
                # is a transient (quorum blip / unverifiable absence)
                # — honor ``retries`` for those, exactly like the
                # host path's on_cas does.  Each attempt re-resolves
                # the slot: a racing put may have flipped the key to
                # host storage (then 'failed' is the honest outcome —
                # retrying device arithmetic there would corrupt it).
                def dev_attempt(tries_left: int) -> None:
                    s = self._slot_for(ens, key, allocate=True)
                    if s is None or not self._rmw_eligible(ens, s):
                        self._safe_resolve(fut, "failed")
                        return
                    inner = Future()
                    self._push_rmw(ens, key, s, dev, inner)

                    def on_res(r: Any) -> None:
                        if fut.done:
                            return
                        if (isinstance(r, tuple) and r[0] == "ok") \
                                or tries_left <= 1 \
                                or self._dead(ens):
                            self._safe_resolve(fut, r)
                            return
                        if (dev[0] == funref.RMW_PIA
                                and self.slot_handle[ens].get(s, 0)
                                == -1):
                            # deterministic refuse: the slot provably
                            # holds a live device value — retrying a
                            # put-if-absent can't change the outcome
                            self._safe_resolve(fut, r)
                            return
                        self._retry_later(
                            ens, fut, 0,
                            lambda: dev_attempt(tries_left - 1))
                    inner.add_waiter(on_res)

                dev_attempt(max(1, retries))
                return fut
            # key holds a host payload: the host retry path below
            # (its read returns the stored value, not an int32 lane)
        if funref.device_code(mod_fun) == funref.RMW_PIA \
                and len(mod_fun[2]) == 1:
            # put-if-absent over a host-payload key cannot go through
            # the fn (a live payload of int 0 would read as 'absent'
            # and be clobbered); the (0,0)-CAS IS the exact
            # do_kput_once semantics — a live payload of ANY value
            # refuses, absence/tombstone commits.  Routed by NAME,
            # not device_entry: a non-int32 operand (put-if-absent of
            # an arbitrary payload) must take this path too.
            self.kput_once(ens, key, mod_fun[2][0]).add_waiter(
                lambda r: self._safe_resolve(fut, r))
            return fut

        def attempt(tries_left: int, conflicts: int) -> None:
            g = self.kget_vsn(ens, key)

            def on_read(res: Any) -> None:
                if fut.done:
                    return
                if not (isinstance(res, tuple) and res[0] == "ok"):
                    self._safe_resolve(fut, "failed")
                    return
                cur, vsn = res[1], tuple(res[2])
                try:
                    new = fn(vsn, default if cur is NOTFOUND else cur)
                except Exception:
                    self._emit_kmodify_error()
                    self._safe_resolve(fut, "failed")
                    return
                if isinstance(new, str) and new == "failed":
                    self._safe_resolve(fut, "failed")
                    return
                if (dev is not None and funref.is_int32(new)
                        and int(new) == 0
                        and self._slot_for(ens, key, allocate=False)
                        is not None):
                    # a TABLE fun computing 0 means the tombstone on
                    # the device path — mirror it here whenever the
                    # key HAS a slot (vsn (0,0) included: an
                    # absent-read over an existing slot still CASes
                    # the tombstone); a kupdate would store a live
                    # int-0 payload and the key would read back found
                    # where the device path reads notfound.  A truly
                    # slotless key (only reachable with a non-zero
                    # default, outside the device-equivalence domain)
                    # keeps the generic path.
                    c = self.ksafe_delete(ens, key, vsn)
                else:
                    c = self.kupdate(ens, key, vsn, new)
                # the CAS was enqueued by a resolve: let the flush
                # that is settling this read serve it too
                self._chain_kick = True

                def on_cas(r: Any) -> None:
                    if fut.done:
                        return
                    if isinstance(r, tuple) and r[0] == "ok":
                        self._safe_resolve(fut, r)
                    elif tries_left > 1 and not self._dead(ens):
                        # counts retried CAS losses: true write races
                        # plus transient quorum failures (the client
                        # can't tell them apart from 'failed'; a
                        # destroyed row stops retrying entirely)
                        self.rmw_conflicts += 1
                        self._retry_later(
                            ens, fut, conflicts,
                            lambda: attempt(tries_left - 1,
                                            conflicts + 1))
                    else:
                        self._safe_resolve(fut, "failed")
                c.add_waiter(on_cas)
            g.add_waiter(on_read)

        attempt(max(1, retries), 0)
        return fut

    def kmodify_many(self, ens: int, keys: List[Any], mod_fun: Any,
                     default: Any = 0, retries: int = 8) -> Future:
        """Vectorized server-side modify: apply ONE ``mod_fun`` to N
        keys behind one future, resolving to per-key ('ok', new_vsn) |
        'failed' in key order.  A device-table funref takes one
        ``OP_RMW`` round per key — the whole batch is a single
        struct-of-arrays queue entry costing one flush, conflict-free
        by construction.  Non-table funs (or keys holding host
        payloads) fall back to per-key :meth:`kmodify` chains sharing
        the batch accumulator.

        Enqueue-side coalescing (docs/ARCHITECTURE.md §18): when the
        comm lane is on and the fun is commutative/semilattice,
        duplicate keys in one call fold into a SINGLE device row —
        operands merged with the same int32-exact fold the replication
        merge section uses (sub normalizes to add of the negated
        operand), so the slot's final value and version are bit-equal
        to the sequenced chain's.  All members of a coalesced group
        share the row's ('ok', vsn): the group commits or fails as
        one op, and the version is the slot's post-group version, the
        only one a subsequent CAS could use anyway.  Ordered funs
        (set/bxor/put_if_absent) never coalesce."""
        from riak_ensemble_tpu import funref

        fut = Future()
        n = len(keys)
        if self._dead(ens) or n == 0:
            fut.resolve(["failed"] * n)
            return fut
        accum = _BatchAccum(n)
        dev = funref.device_entry(mod_fun)
        device_ok = (dev is not None and funref.is_int32(default)
                     and int(default) == 0)

        def host_one(i: int, key: Any) -> None:
            f = self.kmodify(ens, key, mod_fun, default, retries)
            f.add_waiter(lambda r, i=i: accum.fill(
                fut, [i], [r], self._safe_resolve))

        if not device_ok:
            for i, key in enumerate(keys):
                host_one(i, key)
            return fut
        code, operand = dev
        coalesce = (self._comm_repl
                    and funref.merge_class(code) is not None)
        sg = self.slot_gen[ens]
        inline = self._inline_slots[ens]
        ks = self.key_slot[ens]
        fs = self.free_slots[ens]
        slot_l: List[int] = []
        ops_l: List[int] = []
        gen_l: List[int] = []
        live_keys: List[Any] = []
        members: List[List[int]] = []   # result positions per row
        row_of: Dict[int, int] = {}
        miss_pos: List[int] = []
        # one dict pass for key→slot + eligibility; the storage-class
        # set/slab adopt the whole batch in bulk below
        for i, key in enumerate(keys):
            s = ks.get(key)
            if s is None:
                if not fs:
                    miss_pos.append(i)
                    continue
                s = fs.pop()
                ks[key] = s
            if not self._rmw_eligible(ens, s):
                host_one(i, key)  # host-payload key: per-key fallback
                continue
            if coalesce:
                r = row_of.get(s)
                if r is not None:
                    ops_l[r] = funref.fold_operand(
                        code, ops_l[r], operand)
                    members[r].append(i)
                    self.rmw_enqueue_coalesced += 1
                    continue
                row_of[s] = len(slot_l)
            g = sg.get(s, 0) + 1
            sg[s] = g
            slot_l.append(s)
            ops_l.append(funref.fold_seed(code, operand) if coalesce
                         else operand)
            gen_l.append(g)
            live_keys.append(key)
            members.append([i])
        if slot_l:
            inline.update(slot_l)
            self._inline_np[ens, np.asarray(slot_l, np.int32)] = True
        if miss_pos:
            accum.fill(fut, miss_pos, ["failed"] * len(miss_pos),
                       self._safe_resolve)
        if live_keys:
            m = len(slot_l)
            self.rmw_device_fastpath += sum(
                len(mb) for mb in members)
            # sub ships as add of the (folded) negated operand when
            # coalescing — fold_seed/fold_operand live in the
            # MERGE_ADD-normalized domain, so the row's fun code must
            # match it (bit-equal value either way: cur-a-b == cur+
            # (-(a+b)) under int32 wraparound)
            ship_code = (funref.RMW_ADD
                         if coalesce and code == funref.RMW_SUB
                         else code)
            # the batch rides an INNER future so transiently-failed
            # rows (quorum blips — a device RMW cannot CAS-conflict)
            # get their remaining ``retries`` through the scalar
            # path, same contract as kmodify; a failed coalesced
            # group applied NOTHING (all-or-nothing row), so each
            # member retrying its own single op is exact
            inner = Future()
            self._push(ens, _PendingBatch(
                eng.OP_RMW, slot_l, ops_l, inner,
                list(range(m)), live_keys, gen_l, [ship_code] * m,
                [0] * m, _BatchAccum(m), want_vsn=True, n=m))

            def on_batch(results: Any) -> None:
                if not isinstance(results, list):
                    allp = [p for mb in members for p in mb]
                    accum.fill(fut, allp, ["failed"] * len(allp),
                               self._safe_resolve)
                    return
                for mb, key, r in zip(members, live_keys, results):
                    if (isinstance(r, tuple) and r[0] == "ok") \
                            or retries <= 1 or self._dead(ens):
                        accum.fill(fut, mb, [r] * len(mb),
                                   self._safe_resolve)
                    else:
                        for pos in mb:
                            f = self.kmodify(ens, key, mod_fun,
                                             default, retries - 1)
                            f.add_waiter(
                                lambda r2, pos=pos: accum.fill(
                                    fut, [pos], [r2],
                                    self._safe_resolve))
            inner.add_waiter(on_batch)
        return fut

    # -- runtime-controller actuation points (ARCHITECTURE §14) -------------

    def set_pipeline_depth(self, depth: int) -> int:
        """Retune the launch pipeline depth at runtime (the ack-RTT
        actuator's knob; also callable by an operator).  Settles
        every in-flight launch first so no launch ever observes a
        depth change mid-stream — submission order, the WAL barrier
        and rollback snapshots are exactly as at construction.
        Returns the previous depth."""
        depth = max(1, int(depth))
        old = self.pipeline_depth
        if depth != old:
            self._drain_launches()
            self.pipeline_depth = depth
            self._emit("svc_autotune",
                       {"knob": "pipeline_depth", "old": old,
                        "new": depth})
        return old

    def set_admission_caps(self,
                           caps: Optional[Dict[int, int]]) -> None:
        """Install (or clear, with None) per-row flush-admission
        round caps — the tenant guard's knob.  Each capped row gets
        a token bucket: refill = cap per flush, burst 2x cap, so a
        capped tenant's flush share is bounded without starving it.
        ``None``/empty restores the exact uncapped take path."""
        caps = {int(e): max(1, int(c))
                for e, c in caps.items()} if caps else None
        self._admission_caps = caps
        # fresh buckets start full: the first capped flush admits a
        # full cap rather than zero (no spurious stall on install)
        self._admission_tokens = (
            {e: float(c) for e, c in caps.items()} if caps else {})
        self._emit("svc_autotune",
                   {"knob": "admission_caps",
                    "new": dict(caps) if caps else None})

    def set_autotune(self, enabled: bool) -> None:
        """Arm/disarm the runtime controller for THIS service (the
        programmatic form of ``RETPU_AUTOTUNE``; svcnode's
        ``--autotune``).  Disarming also clears any installed
        admission caps so the service returns to the exact
        pre-controller take path."""
        enabled = bool(enabled)
        if enabled == self._autotune:
            return
        self._autotune = enabled
        self.controller.enabled = enabled
        if enabled:
            # re-anchor the heal target at ARM time: the operator may
            # have moved the knobs since construction, and the tuner
            # must never walk below the configuration it was armed on
            self._autotune_base_depth = int(self.pipeline_depth)
            self._autotune_base_window = int(
                getattr(self, "repl_window", 1))
        if not enabled and self._admission_caps:
            self.controller.guard.throttled.clear()
            self.set_admission_caps(None)
        self._emit("svc_autotune",
                   {"knob": "autotune", "new": enabled})

    # -- lease-protected read fast path -------------------------------------

    def set_fast_reads(self, enabled: bool) -> None:
        """Runtime opt-in/out for the lease-protected read fast path
        (the programmatic form of ``RETPU_FAST_READS``); disabling
        routes every read through the device round again."""
        enabled = bool(enabled) and self.config.trust_lease
        if enabled:
            # the safety inequality is a precondition of SERVING, so
            # it is (re-)checked at every enable, not just the one in
            # __init__ — a config whose margin doesn't fit the
            # lease/follower gap may run, but never fast-serve
            self._assert_read_margin()
        self._fast_reads = enabled

    def _assert_read_margin(self) -> None:
        assert (0.0 <= self._read_margin
                and self.config.lease() + self._read_margin
                < self.config.follower()), \
            "need 0 <= read_margin and lease + read_margin " \
            "< follower_timeout to enable lease-protected reads"

    def _fast_read_ok(self, ens: int, now: float) -> Optional[str]:
        """None when ensemble ``ens`` may serve lease-protected reads
        right now; otherwise the miss reason.  Subclasses layer their
        own gates (a replication-group leader adds the host-quorum
        lease and the leader-only rule)."""
        if not self._fast_reads:
            return "disabled"
        lead = self.leader_np[ens]
        if lead < 0 or not self.up[ens, lead]:
            # leaderless / leader-down rows are electing (the next
            # flush folds the election in) — never serve around that
            return "no_leader"
        if self._corrupt_rows[ens]:
            # flagged rows take the device round so the synctree
            # integrity gate still vets the read (reference read-path
            # validation); cleared once the exchange syncs the row
            return "corrupt"
        if self.lease_until[ens] <= now + self._read_margin:
            return "no_lease"
        return None

    def _try_fast(self, ens: int, slot: int, want_vsn: bool
                  ) -> Tuple[bool, Any]:
        """The whole fast-path gate for one scalar read: (hit,
        result).  Accounts the attempt either way; ``result`` is only
        valid on a hit.  (``kget_many`` inlines the same sequence so
        it can check the ensemble-level gate once per batch.)"""
        reason = self._fast_read_ok(ens, self.runtime.now)
        if reason is None:
            reason, res = self._fast_read_result(ens, slot, want_vsn)
        else:
            res = None
        return self._count_fast(ens, reason), res

    def _fast_read_result(self, ens: int, slot: int, want_vsn: bool
                          ) -> Tuple[Optional[str], Any]:
        """(miss_reason, result) for one slot read off the committed
        host mirror; ``result`` is only valid when the reason is None.
        The caller has already passed :meth:`_fast_read_ok`."""
        if self._pending_writes[ens][slot]:
            return "pending_write", None
        vsn: Any = None
        if want_vsn:
            if not self._slot_vsn_ok[ens, slot]:
                # unmirrored version (fresh restore / post-election
                # invalidation): the device round re-versions and its
                # resolve refreshes the mirror
                return "vsn_unmirrored", None
            ve, vs = self._slot_vsn_np[ens, slot]
            vsn = (int(ve), int(vs))
        h = self.slot_handle[ens].get(slot, 0)
        if h == -1:
            if not self._inline_value_ok[ens, slot]:
                return "inline_unmirrored", None
            out: Any = int(self._inline_value_np[ens, slot])
        elif h:
            out = self.values.get(h, NOTFOUND)
        else:
            # nothing committed (tombstone or never-written slot): the
            # device reads it notfound too; a tombstone's real vsn
            # rides along so CAS chains still work
            out = NOTFOUND
        return None, (("ok", out, vsn) if want_vsn else ("ok", out))

    def _count_fast(self, ens: int, reason: Optional[str]) -> bool:
        """Account one fast-path attempt; True = hit (serve now)."""
        if reason is None:
            self.read_fastpath_hits += 1
            # a mirror-served read is a served op: keep the
            # throughput counter honest when 90% of traffic never
            # reaches a resolve path
            self.ops_served += 1
            if self._obs:
                # mirror-served reads are tenant ops too — without
                # them a read-heavy tenant would look idle — and they
                # contribute a lowest-bucket latency sample (a mirror
                # hit is microseconds, far under the ladder's 50 µs
                # floor), so a read-heavy tenant's p50/p99 reflects
                # its real service time instead of reporting 0
                self.tenant_ops[ens] += 1
                if self._slo is not None:
                    # per-op + per-tenant latency samples follow the
                    # ring knob (RETPU_SLO_RING=0 freezes BOTH —
                    # leaving only the lowest-bucket fast-read
                    # samples live would skew p50/p99, worse than
                    # frozen): one lowest-bucket 'get_fast' sample —
                    # mirror hits ARE the client's experienced
                    # latency for these reads
                    self._tenant_lat[ens, 0] += 1
                    child = self._h_op.labels(obs.opslo.KIND_NAMES[
                        obs.opslo.KIND_FAST_READ])
                    child.counts[0] += 1
                    child.count += 1
            return True
        self.read_fastpath_misses += 1
        r = self.read_fastpath_miss_reasons
        r[reason] = r.get(reason, 0) + 1
        return False

    def _note_write(self, ens: int, slot: int) -> None:
        self._pending_writes[ens][slot] += 1

    def _unnote_write(self, ens: int, slot: int) -> None:
        # clamped at 0 like the old dict pop — an unpaired un-note is
        # a bug, but it must park reads on the safe device round, not
        # underflow into "every later write is invisible"
        row = self._pending_writes[ens]
        if row[slot] > 0:
            row[slot] -= 1

    def _rmw_eligible(self, ens: int, slot: int) -> bool:
        """A slot the device fast path may RMW: no QUEUED host-payload
        write racing it, and device-native already or holding no
        committed host payload (fresh/tombstoned) — running int32
        arithmetic over a payload HANDLE (committed or about to
        commit earlier in the same flush) would corrupt the data
        while acking 'ok'."""
        if self._queued_handle_writes[ens][slot]:
            return False
        return (slot in self._inline_slots[ens]
                or self.slot_handle[ens].get(slot, 0) == 0)

    def _note_handle_write(self, ens: int, slot: int) -> None:
        self._queued_handle_writes[ens][slot] += 1

    def _unnote_handle_write(self, ens: int, slot: int) -> None:
        row = self._queued_handle_writes[ens]
        if row[slot] > 0:
            row[slot] -= 1

    def _push_rmw(self, ens: int, key: Any, slot: int,
                  dev: Tuple[int, int], fut: Future) -> None:
        code, operand = dev
        gen = self.slot_gen[ens].get(slot, 0) + 1
        self.slot_gen[ens][slot] = gen
        # optimistic inline marking: a second kmodify racing this
        # one's commit must still see the slot as device-native
        self._inline_slots[ens].add(slot)
        self._inline_np[ens, slot] = True
        self.rmw_device_fastpath += 1
        self._push(ens, _PendingOp(eng.OP_RMW, slot, operand, fut,
                                   key, gen, exp=(code, 0),
                                   want_vsn=True))

    def _retry_later(self, ens: int, fut: Future, conflict_idx: int,
                     thunk) -> None:
        """Jittered backoff between CAS-conflict retries, in flush
        calls: retry 0 is immediate (the common two-writer race wins
        on the second round), later ones pick a uniformly random
        delay from a doubling window so N stampeding writers spread
        over ~N flushes instead of re-colliding every round."""
        delay = self._rng.randrange(1 << min(conflict_idx, 4))
        if delay == 0:
            thunk()
            # an immediate retry enqueued during a resolve is a chain
            # follow-up like the CAS half
            self._chain_kick = True
        else:
            self._retry_at.append((self._flush_calls + delay, ens,
                                   fut, thunk))

    def _run_due_retries(self) -> None:
        if not self._retry_at:
            return
        now = self._flush_calls
        due = [t for at, _e, fut, t in self._retry_at
               if at <= now and not fut.done]
        self._retry_at = [r for r in self._retry_at
                          if r[0] > now and not r[2].done]
        for thunk in due:
            thunk()

    def _purge_retries(self, ens: int) -> None:
        """Fail and drop parked retries addressed to a destroyed row
        — a thunk firing after the row recycles would run the dead
        tenant's mod-fun against the NEW tenant's ensemble (its
        create-if-missing CAS would even commit)."""
        keep: List[Tuple[int, int, Future, Any]] = []
        for at, e, fut, thunk in self._retry_at:
            if e == ens:
                self._safe_resolve(fut, "failed")
            else:
                keep.append((at, e, fut, thunk))
        self._retry_at = keep

    def _emit_kmodify_error(self) -> None:
        """Trace a mod-fun exception, rate-limited to one traceback
        per second (a hot fun bug at flush rate would otherwise emit
        thousands); suppressed counts ride the next emission."""
        now = time.monotonic()
        if now - self._kmodify_err_at >= 1.0:
            import traceback
            self._kmodify_err_at = now
            self._emit("svc_kmodify_error",
                       {"error": traceback.format_exc(limit=8),
                        "suppressed": self._kmodify_err_dropped})
            self._kmodify_err_dropped = 0
        else:
            self._kmodify_err_dropped += 1

    def _recycle_on_ok(self, fut: Future, ens: int, key: Any,
                       slot: int) -> None:
        """Once a delete commits, queue the slot for deferred
        recycling (validated and applied by _drain_recycles)."""
        gen = self.slot_gen[ens].get(slot, 0)

        def recycle(result):
            if isinstance(result, tuple) and result[0] == "ok":
                self._queue_recycle(ens, (key, slot, gen))
        fut.add_waiter(recycle)

    def watch_leader(self, ens: int, fn) -> None:
        """Leader-status watcher for one ensemble — the scale-path
        ``watch_leader_status`` (peer.erl:212-218, 2070-2075):
        ``fn(ens, old_leader, new_leader)`` fires immediately with the
        CURRENT status at registration (old == new, the reference's
        initial notify) and then after any flush or membership change
        that moved the leader (-1 = none).  Watcher exceptions are
        contained and traced like client waiters; remove with
        :meth:`unwatch_leader` (the stop_watching counterpart)."""
        self._leader_watchers.setdefault(ens, []).append(fn)
        cur = int(self.leader_np[ens])
        self._safe_notify(fn, ens, cur, cur)

    def unwatch_leader(self, ens: int, fn) -> bool:
        """Deregister a leader watcher (stop_watching,
        peer.erl:220-226); True iff it was registered."""
        fns = self._leader_watchers.get(ens)
        if fns is None or fn not in fns:
            return False
        fns.remove(fn)
        if not fns:
            del self._leader_watchers[ens]
        return True

    def _safe_notify(self, fn, *args) -> None:
        """Run a watcher callback, containing and tracing exceptions
        (the _safe_resolve contract for watchers)."""
        try:
            fn(*args)
        except Exception:
            import traceback
            self._emit("svc_watcher_error",
                       {"error": traceback.format_exc(limit=8)})

    def _notify_leader_changes(self, old: np.ndarray) -> None:
        if not self._leader_watchers:
            return
        changed = np.nonzero(old != self.leader_np)[0]
        for e in changed.tolist():
            # snapshot: a watcher may watch/unwatch from its callback
            for fn in list(self._leader_watchers.get(e, ())):
                self._safe_notify(fn, e, int(old[e]),
                                  int(self.leader_np[e]))

    def set_peer_up(self, ens: int, peer: int, up: bool) -> None:
        """Failure-detector input (the host's nodedown/suspend signal)."""
        self.up[ens, peer] = up
        self._up_dev = None

    def _up_device(self):
        """Device copy of the up mask, re-uploaded only after a
        failure-detector change (steady state: zero h2d bytes)."""
        if self._up_dev is None:
            self._up_dev = self._jnp.asarray(self.up)
        return self._up_dev

    def update_members(self, sel: np.ndarray,
                       new_view: np.ndarray) -> np.ndarray:
        """Batched joint-consensus membership change for the selected
        ensembles — the update_members → transition dance
        (peer.erl:655-672, 751-774) as two device launches: install
        the joint view (old AND new quorums gate commits while it
        holds), then collapse to the new view once the joint quorum
        confirms.

        sel [E] bool — change these ensembles; new_view [E, M] bool —
        their new membership (rows of unselected ensembles ignored).
        Returns ``changed [E]``: ensembles whose membership finished
        changing during this call (including changes left in flight by
        an earlier call that completed now).  A change whose install
        or collapse could not commit yet (no leader, quorum missing)
        stays in flight and EVERY later call advances it — an
        all-False ``sel`` makes this a pure retry.  A new request for
        an ensemble whose previous change is still joint on device is
        QUEUED (latest request wins the queue slot) and becomes the
        next proposal once that change collapses — so ``changed[e]``
        means e's membership reached its in-flight view this call, not
        necessarily this call's ``new_view`` row; compare
        ``member_np`` when that distinction matters.  Ensembles whose
        leader left the membership (or was down) get an election
        folded into the next flush via the host mirrors, exactly like
        a reference leader shutting down after transitioning itself
        out (peer.erl:763-771).
        """
        jnp = self._jnp
        # reconfig reads the leader/membership mirrors and fetches
        # device results synchronously: settle in-flight launches
        # first (no-op in steady state)
        self._drain_launches()
        sel = np.asarray(sel, bool)
        if self.dynamic:
            sel = sel & self._live  # free rows have no membership
        new_view = np.asarray(new_view, bool)

        # Record the request.  An ensemble already joint on device
        # keeps its in-flight view until that collapses; the new
        # request waits in the queued tier.
        accept = sel & ~self._pending_mask
        defer = sel & self._pending_mask
        self._desired_view_np = np.where(accept[:, None], new_view,
                                         self._desired_view_np)
        self._desired_mask = self._desired_mask | accept
        self._queued_view_np = np.where(defer[:, None], new_view,
                                        self._queued_view_np)
        self._queued_mask = self._queued_mask | defer

        up_j = self._up_device()
        # Proposing is leader work (leading({update_members,_}),
        # peer.erl:655): only ensembles with a live leader install —
        # leaderless ones keep the change desired until a flush's
        # election gives them one.
        idx = np.arange(self.n_ens)
        leader = self.leader_np
        leader_ok = np.zeros((self.n_ens,), bool)
        has = leader >= 0
        leader_ok[has] = self.up[idx[has], leader[has]]
        propose = self._desired_mask & ~self._pending_mask & leader_ok
        dv_j = jnp.asarray(self._desired_view_np)
        # Same rollback discipline as _launch: an async device failure
        # surfaces at the np.asarray fetches below, after self.state
        # was replaced — restore the pre-launch state so the request
        # stays queued/desired and a later call retries cleanly.
        state_snapshot = self.state
        try:
            state, installed, collapsed1 = self.engine.reconfig_step(
                self.state, jnp.asarray(propose), dv_j, up_j)
            # Launch 2 only exists to collapse views launch 1 freshly
            # installed (launch 1's transition half already attempted
            # every leftover); skip the device round trip if nothing
            # could have installed.
            if propose.any():
                state, _, collapsed2 = self.engine.reconfig_step(
                    state, jnp.zeros((self.n_ens,), bool), dv_j, up_j)
                collapsed2 = np.asarray(collapsed2)
            else:
                collapsed2 = np.zeros((self.n_ens,), bool)
            self.state = state
            installed_now = propose & np.asarray(installed)
            collapsed1 = np.asarray(collapsed1)
        except BaseException:
            self.state = state_snapshot
            raise
        # Collapses land in EITHER launch: joint views left over from
        # earlier calls transition during launch 1 (its ~propose
        # half), fresh installs during launch 2.
        collapsed = collapsed1 | collapsed2

        # Host mirrors.  Installs move desired -> pending; a collapse
        # promotes its pending view to the live membership and lets a
        # queued next request advance to desired.
        self._pending_view_np = np.where(installed_now[:, None],
                                         self._desired_view_np,
                                         self._pending_view_np)
        self._pending_mask = self._pending_mask | installed_now
        self._desired_mask = self._desired_mask & ~installed_now
        changed = self._pending_mask & collapsed
        self.member_np = np.where(changed[:, None],
                                  self._pending_view_np, self.member_np)
        self._pending_mask = self._pending_mask & ~changed
        promote = self._queued_mask & changed
        self._desired_view_np = np.where(promote[:, None],
                                         self._queued_view_np,
                                         self._desired_view_np)
        self._desired_mask = self._desired_mask | promote
        self._queued_mask = self._queued_mask & ~promote

        # A leader no longer in (or not up in) its membership forces
        # an election on the next flush.
        still_ok = np.zeros((self.n_ens,), bool)
        still_ok[has] = self.member_np[idx[has], leader[has]] & \
            self.up[idx[has], leader[has]]
        dropped = changed & has & ~still_ok
        self.leader_np = np.where(dropped, -1, leader)
        self.lease_until[dropped] = 0.0
        self._notify_leader_changes(leader)
        # Durability: committed membership rows persist before the
        # caller observes `changed` (the fact-save-on-meaningful-change
        # discipline, peer.erl:2201-2228).
        if self._wal is not None and changed.any():
            self._wal.log([(("mem", int(e)),
                            (self._row_name.get(int(e)),
                             self.member_np[e].tolist()))
                           for e in np.nonzero(changed)[0]])
        return changed

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._resolve_pool is not None:
            self._resolve_pool.shutdown(wait=True)
            self._resolve_pool = None

    # -- sharded resolve/enqueue workers (ARCHITECTURE §16) ---------------

    def _shard_bounds(self, n: int):
        """Contiguous [lo, hi) chunk bounds partitioning ``n`` run
        descriptors (or taken columns) across the worker pool, or
        ``None`` when sharding is off or pointless — the caller then
        takes the untouched single-threaded path.  Chunks are
        descriptor-granular: a descriptor's lane run never splits, so
        each chunk touches a disjoint set of plane cells."""
        s = self._resolve_shards
        if s <= 1 or n <= 1:
            return None
        s = min(s, n)
        step = -(-n // s)
        return [(lo, min(lo + step, n)) for lo in range(0, n, step)]

    def _shard_map(self, fn, bounds) -> list:
        """Run ``fn(lo, hi)`` over every chunk, tail chunks on the
        pool and the first on the calling thread, and return results
        in chunk order (the order invariant every sharded site's
        concatenation relies on)."""
        if self._resolve_pool is None:
            self._resolve_pool = ThreadPoolExecutor(
                max_workers=self._resolve_shards,
                thread_name_prefix="retpu-resolve")
        futs = [self._resolve_pool.submit(fn, lo, hi)
                for lo, hi in bounds[1:]]
        out = [fn(*bounds[0])]
        out.extend(f.result() for f in futs)
        return out

    # -- checkpoint / resume -----------------------------------------------

    def save(self, path: Optional[str] = None) -> None:
        """Checkpoint the whole service: the device ``EngineState``
        via orbax plus the host mirrors (key→slot maps, payload store,
        membership pipeline) as one 4-copy CRC blob (save.erl's
        paranoid format; pickle is fine here — local disk is the same
        trust boundary as the reference's term_to_binary files).

        Crash atomicity: each save writes a fresh ``ckpt.<n>``
        directory and only then flips the CRC-protected ``CURRENT``
        pointer — a crash at any point leaves the previous checkpoint
        (engine+host pair, always from the same save) restorable.

        Queued ops are flushed (resolved) first so the persisted host
        mirrors carry no half-applied side effects (a saved key→slot
        allocation whose put never ran would leak the slot forever).
        Leases are not persisted — a restarted service must never
        trust a pre-crash lease (the reference restarts into probe; we
        restart lease-less and re-establish via the next quorum
        round).
        """
        import pickle

        from riak_ensemble_tpu import save as savelib
        from riak_ensemble_tpu.ops import checkpoint as ckpt

        if path is None:
            path = self.data_dir
        assert path is not None, "save() needs a path or data_dir"
        self._in_save = True
        try:
            while self._active:
                self.flush()
            # settle the launch pipeline too: an unresolved launch's
            # host bookkeeping (slot_handle, recycles) must land
            # before the mirrors persist
            self._drain_launches()
        finally:
            self._in_save = False
        os.makedirs(path, exist_ok=True)
        n = self._current_ckpt(path) + 1
        d = os.path.join(path, f"ckpt.{n}")
        ckpt.save(os.path.join(d, "engine"), self.state)
        from riak_ensemble_tpu.ops import hash as hashk
        host = {
            "shape": (self.n_ens, self.n_peers, self.n_slots),
            # Device-tree hash-format version: tree_leaf/tree_node are
            # persisted verbatim, so a restore under a different fold
            # must rebuild every tree (docs/MIGRATION.md).
            "hash_format": hashk.HASH_FORMAT,
            "key_slot": self.key_slot,
            "free_slots": self.free_slots,
            "slot_gen": self.slot_gen,
            "slot_handle": self.slot_handle,
            "inline_slots": [sorted(s) for s in self._inline_slots],
            "recycle_pending": self._recycle_pending,
            "values": self.values,
            "free_handles": self._free_handles,
            "next_handle": self._next_handle,
            "leader": self.leader_np,
            "member": self.member_np,
            "desired_view": self._desired_view_np,
            "desired_mask": self._desired_mask,
            "queued_view": self._queued_view_np,
            "queued_mask": self._queued_mask,
            "pending_view": self._pending_view_np,
            "pending_mask": self._pending_mask,
            "up": self.up,
            "dynamic": self.dynamic,
            "live": self._live,
            "free_rows": self._free_rows,
            "ens_names": self._ens_names,
        }
        savelib.write(os.path.join(d, "host"),
                      pickle.dumps(host, protocol=4),
                      crash_class="ckpt")
        savelib.write(os.path.join(path, "CURRENT"), str(n).encode(),
                      crash_class="ckpt")
        # Old checkpoints are garbage once CURRENT moved (best effort).
        import shutil
        for name in os.listdir(path):
            if name.startswith("ckpt.") and name != f"ckpt.{n}":
                shutil.rmtree(os.path.join(path, name),
                              ignore_errors=True)
        # Checkpoint n subsumes every WAL record: start generation n
        # fresh and drop the old ones.  Restore replays ONLY the WAL
        # generation matching CURRENT, so a crash between the CURRENT
        # flip and this rotation leaves stale wal.<n-1> dirs that are
        # simply ignored (and cleaned by the next rotation).
        if self._wal is not None and path == self.data_dir:
            from riak_ensemble_tpu.parallel.wal import ServiceWAL
            self._wal = ServiceWAL.rotate(self.data_dir, n, self._wal,
                                          self.wal_sync)

    @staticmethod
    def _current_ckpt(path: str) -> int:
        from riak_ensemble_tpu import save as savelib

        raw = savelib.read(os.path.join(path, "CURRENT"))
        try:
            return int(raw.decode()) if raw else 0
        except ValueError:
            return 0

    @classmethod
    def restore(cls, runtime: Runtime, path: str, **kw
                ) -> "BatchedEnsembleService":
        """Bring a service back from :meth:`save`; ``kw`` forwards
        construction options (tick, config, engine, ...).

        When the directory is a ``data_dir`` (META present / WAL
        generations on disk), every write acked after the latest
        checkpoint is replayed from the WAL — including the case where
        the service crashed before its FIRST checkpoint (restore from
        META shape + WAL alone).  Callers restoring a durable service
        should pass ``data_dir=path`` in ``kw`` so logging continues.
        """
        import pickle

        from riak_ensemble_tpu import save as savelib
        from riak_ensemble_tpu.ops import checkpoint as ckpt
        from riak_ensemble_tpu.parallel.wal import ServiceWAL

        n = cls._current_ckpt(path)
        d = os.path.join(path, f"ckpt.{n}")
        raw = savelib.read(os.path.join(d, "host"))
        if raw is None:
            # No checkpoint: a durable service that crashed before its
            # first save() restores from META shape + WAL generation 0.
            meta_raw = savelib.read(os.path.join(path, "META"))
            if meta_raw is None:
                raise FileNotFoundError(
                    f"no service checkpoint at {path}")
            meta = pickle.loads(meta_raw)
            kw = cls._merge_dynamic(kw, bool(meta.get("dynamic",
                                                      False)))
            svc = cls(runtime, *meta["shape"], **kw)
            svc._replay_wal_from(path, 0, ServiceWAL)
            return svc
        host = pickle.loads(raw)
        n_ens, n_peers, n_slots = host["shape"]
        kw = cls._merge_dynamic(kw, bool(host.get("dynamic", False)))
        svc = cls(runtime, n_ens, n_peers, n_slots, **kw)
        svc.state = ckpt.load(os.path.join(d, "engine"),
                              template=svc.state)
        # Hash-format migration: checkpoints persist tree_leaf/
        # tree_node verbatim, so an image written under a different
        # fold would fail _verify_path on EVERY slot (reads of
        # committed data returning failures cluster-wide).  Rebuild
        # every replica tree from the restored object store before any
        # WAL replay touches a subset of slots.  Format history:
        # riak_ensemble_tpu/ops/hash.py HASH_FORMAT; docs/MIGRATION.md.
        from riak_ensemble_tpu.ops import hash as hashk
        if host.get("hash_format", 2) != hashk.HASH_FORMAT:
            svc.state = svc.engine.rebuild_trees(
                svc.state,
                jnp.ones((svc.n_ens, svc.n_peers), bool))
        svc.key_slot = host["key_slot"]
        svc.free_slots = host["free_slots"]
        svc.slot_gen = host["slot_gen"]
        svc.slot_handle = host["slot_handle"]
        svc._inline_slots = [set(s) for s in host.get(
            "inline_slots", [[] for _ in range(n_ens)])]
        for row, slots_ in enumerate(svc._inline_slots):
            if slots_:  # keep the kernel's storage-class slab in step
                svc._inline_np[row, list(slots_)] = True
        svc._recycle_pending = host["recycle_pending"]
        # restored pending recycles must re-enter the dirty set or
        # the sparse drain would never revisit them (leaked slots)
        svc._recycle_dirty = {e for e, p in
                              enumerate(svc._recycle_pending) if p}
        svc.values = host["values"]
        svc._free_handles = host["free_handles"]
        svc._next_handle = host["next_handle"]
        svc.leader_np = np.asarray(host["leader"])
        svc.member_np = np.asarray(host["member"])
        svc._desired_view_np = np.asarray(host["desired_view"])
        svc._desired_mask = np.asarray(host["desired_mask"])
        svc._queued_view_np = np.asarray(host["queued_view"])
        svc._queued_mask = np.asarray(host["queued_mask"])
        svc._pending_view_np = np.asarray(host["pending_view"])
        svc._pending_mask = np.asarray(host["pending_mask"])
        svc.up = np.asarray(host["up"])
        if host.get("dynamic"):
            svc.dynamic = True
            svc._live = np.asarray(host["live"])
            svc._free_rows = list(host["free_rows"])
            svc._ens_names = dict(host["ens_names"])
            svc._row_name = {r: n_ for n_, r in svc._ens_names.items()}
        # lease_until stays zero: no pre-crash lease is ever trusted.
        svc._replay_wal_from(path, n, ServiceWAL)
        return svc

    @staticmethod
    def _merge_dynamic(kw: Dict[str, Any], persisted: bool
                       ) -> Dict[str, Any]:
        """The persisted lifecycle mode WINS at restore: a static
        image restored as dynamic would mark every row free (the
        first create would wipe restored data on device); a dynamic
        image restored as static would drop the name directory.  An
        explicitly mismatched caller flag fails loudly."""
        if "dynamic" in kw and bool(kw["dynamic"]) != persisted:
            raise ValueError(
                f"restore: service was persisted with "
                f"dynamic={persisted}; cannot restore with "
                f"dynamic={kw['dynamic']}")
        kw = dict(kw)
        kw["dynamic"] = persisted
        return kw

    def _replay_wal_from(self, path: str, gen: int, wal_cls) -> None:
        """Replay WAL generation ``gen`` under ``path`` if it exists
        (re-using the already-open handle when this service logs to
        the same generation)."""
        if not os.path.isdir(wal_cls.gen_path(path, gen)):
            return
        wal = (self._wal if self._wal is not None
               and self._wal.dir_path == wal_cls.gen_path(path, gen)
               else wal_cls.open_gen(path, gen))
        try:
            self._replay_wal(wal)
        finally:
            if wal is not self._wal:
                wal.close()

    def _replay_wal(self, wal) -> None:
        """Install every WAL record — the writes acked after the
        checkpoint this service was restored from — into the device
        state and host mirrors.

        Objects land on every replica at their committed (epoch, seq)
        (a fully-repaired configuration, what exchange would converge
        to); ballot epochs are raised to at least the newest installed
        object epoch so the restart's elections propose higher — any
        epoch skew left over is healed by the read path's stale-epoch
        rewrite (update_key, peer.erl:1564-1596), exactly like the
        reference restarting into probe with persisted facts.
        """
        jnp = self._jnp
        recs = wal.records()
        if not recs:
            return
        e_, m_, s_ = self.n_ens, self.n_peers, self.n_slots
        obj_epoch = np.asarray(self.state.obj_epoch).copy()
        obj_seq = np.asarray(self.state.obj_seq).copy()
        obj_val = np.asarray(self.state.obj_val).copy()
        epoch = np.asarray(self.state.epoch).copy()
        view_mask = np.asarray(self.state.view_mask).copy()
        #: (ens -> slot -> replayed owner key or None): replayed slots
        #: whose checkpoint-era key mapping must not survive if it
        #: disagrees (the slot was recycled to another key — or
        #: tombstoned — after the checkpoint)
        owners: Dict[int, Dict[int, Any]] = {}
        touched = False
        for key, value in recs:
            if key[0] == "mem":
                ens = key[1]
                name, row_l = value
                row = np.asarray(row_l, bool)
                self.member_np[ens] = row
                view_mask[ens] = False
                view_mask[ens, 0] = row
                # The replayed row is the newest COMMITTED membership;
                # any checkpoint-era in-flight pipeline state for this
                # ensemble predates it.
                self._pending_mask[ens] = False
                self._desired_mask[ens] = False
                self._queued_mask[ens] = False
                if self.dynamic:
                    # Lifecycle replay: a non-empty row is a live
                    # (possibly renamed) tenant; an empty row was
                    # destroyed.  Directory rebuilt below.
                    old_name = self._row_name.pop(ens, None)
                    if old_name is not None:
                        self._ens_names.pop(old_name, None)
                    if row.any() and name is not None:
                        self._ens_names[name] = ens
                        self._row_name[ens] = name
                    self._live[ens] = bool(row.any())
                    if not row.any():
                        self._reset_row_host(ens)
                touched = True
            elif key[0] == "kv":
                _, ens, slot = key
                key_obj, handle, oe, os_, payload, inline = value
                obj_epoch[ens, :, slot] = oe
                obj_seq[ens, :, slot] = os_
                obj_val[ens, :, slot] = handle
                touched = True
                if inline:
                    # Inline write: the int32 value IS the payload (no
                    # handle indirection).  With a key AND a live
                    # value it is a keyed device-native slot (a
                    # committed RMW) — restore the mapping and the
                    # inline marking.  A keyed inline TOMBSTONE (RMW
                    # computed 0) replays like a delete: the live
                    # leader recycled the slot, so retaining the
                    # mapping would leak the slot and shadow its next
                    # tenant.  Keyless records are bulk-array writes.
                    if key_obj is not None and handle:
                        self._inline_slots[ens].add(slot)
                        self._inline_np[ens, slot] = True
                        self._inline_value_np[ens, slot] = handle
                        self._inline_value_ok[ens, slot] = True
                        self._slot_vsn_np[ens, slot] = (oe, os_)
                        self._slot_vsn_ok[ens, slot] = True
                        self.slot_handle[ens][slot] = -1
                        self.key_slot[ens][key_obj] = slot
                        owners.setdefault(ens, {})[slot] = key_obj
                    else:
                        if key_obj is not None:
                            self._inline_slots[ens].discard(slot)
                            self._inline_np[ens, slot] = False
                            self._inline_value_ok[ens, slot] = False
                            self.slot_handle[ens].pop(slot, None)
                        owners.setdefault(ens, {})[slot] = None
                    continue
                self._inline_slots[ens].discard(slot)
                self._inline_np[ens, slot] = False
                self._inline_value_ok[ens, slot] = False
                self._slot_vsn_np[ens, slot] = (oe, os_)
                self._slot_vsn_ok[ens, slot] = True
                if handle:
                    self.values[handle] = payload
                    self._next_handle = max(self._next_handle,
                                            handle + 1)
                    self.slot_handle[ens][slot] = handle
                    if key_obj is not None:
                        self.key_slot[ens][key_obj] = slot
                    owners.setdefault(ens, {})[slot] = key_obj
                else:
                    # Tombstone: the slot holds nothing and stays
                    # reusable; any mapping is stale.
                    self.slot_handle[ens].pop(slot, None)
                    owners.setdefault(ens, {})[slot] = None
        if not touched:
            return
        # Checkpoint-era key mappings that disagree with a replayed
        # slot's owner are stale (the slot was recycled/tombstoned
        # after the checkpoint) — without this sweep two keys could
        # share a slot and reads of the dead key would serve the live
        # key's value.
        for ens, owner in owners.items():
            ks = self.key_slot[ens]
            for k in [k for k, s in ks.items()
                      if s in owner and owner[s] != k]:
                del ks[k]
        # Rebuild the free lists from the surviving mappings (mapped
        # slots are live; everything else — including tombstoned
        # slots — is allocatable).
        for ens in range(e_):
            used = set(self.key_slot[ens].values())
            self.free_slots[ens] = [s for s in range(s_)
                                    if s not in used]
        # Ballot epochs >= newest installed object epoch per ensemble.
        epoch = np.maximum(epoch, obj_epoch.max(-1))
        state = self.state._replace(
            epoch=jnp.asarray(epoch),
            view_mask=jnp.asarray(view_mask),
            obj_epoch=jnp.asarray(obj_epoch),
            obj_seq=jnp.asarray(obj_seq),
            obj_val=jnp.asarray(obj_val))
        # Every replica's tree rebuilds over its replayed store (the
        # repair-by-rehash-from-data discipline).
        self.state = self.engine.rebuild_trees(
            state, jnp.ones((e_, m_), bool))
        # Replayed membership/leader state invalidates cached planes
        # and any pre-crash leader claim.
        self._up_dev = None
        self.leader_np = np.full((e_,), -1, dtype=np.int32)
        self.lease_until[:] = 0.0
        if self.dynamic:
            # Free pool = rows with no live tenant after replay.
            self._free_rows = [r for r in range(e_ - 1, -1, -1)
                               if not self._live[r]]

    # -- internals ---------------------------------------------------------

    def _alloc_handle(self) -> int:
        if self._free_handles:
            return self._free_handles.pop()
        h = self._next_handle
        assert h <= 0x7FFFFFFF, "2^31 live payloads cannot fit int32 handles"
        self._next_handle += 1
        return h

    def _alloc_handles(self, m: int) -> List[int]:
        """``m`` payload handles in ONE slab operation — the pooled
        tail (in the exact order ``m`` sequential pops would have
        yielded) then a fresh contiguous range — replacing ``m``
        per-key :meth:`_alloc_handle` calls on the vectorized keyed
        enqueue paths (docs/ARCHITECTURE.md §12, rung 1)."""
        free = self._free_handles
        t = min(m, len(free))
        out = free[len(free) - t:][::-1]
        if t:
            del free[len(free) - t:]
        if t < m:
            h0 = self._next_handle
            self._next_handle = h0 + (m - t)
            assert self._next_handle - 1 <= 0x7FFFFFFF, \
                "2^31 live payloads cannot fit int32 handles"
            out.extend(range(h0, self._next_handle))
        return out

    def _release_handle(self, handle: int) -> None:
        """Drop a payload and make its handle reusable (double release
        is a no-op — the handle returns to the pool once)."""
        if handle and self.values.pop(handle, None) is not None:
            self._free_handles.append(handle)

    def _slot_for(self, ens: int, key: Any, allocate: bool) -> Optional[int]:
        slot = self.key_slot[ens].get(key)
        if slot is not None or not allocate:
            return slot
        if not self.free_slots[ens]:
            return None
        slot = self.free_slots[ens].pop()
        self.key_slot[ens][key] = slot
        return slot

    def _drain_recycles(self) -> None:
        """Free slots whose recycle was deferred, once nothing queued
        references them and the conditions still hold: no later put
        bumped the generation, nothing live is committed, and the key
        still owns the slot."""
        if not self._recycle_dirty:
            return
        dirty, self._recycle_dirty = self._recycle_dirty, set()
        for e in dirty:
            pend = self._recycle_pending[e]
            if not pend:
                continue
            busy = set()
            for op in self.queues[e]:
                if isinstance(op, _PendingBatch):
                    busy.update(op.slot)
                else:
                    busy.add(op.slot)
            keep = []
            for key, slot, gen in pend:
                if slot in busy:
                    keep.append((key, slot, gen))
                elif self.slot_gen[e].get(slot, 0) == gen \
                        and self.slot_handle[e].get(slot, 0) == 0 \
                        and self.key_slot[e].get(key) == slot:
                    # (a committed device-native value holds the -1
                    # sentinel in slot_handle, so inline slots with
                    # live values never reach this branch)
                    del self.key_slot[e][key]
                    self._inline_slots[e].discard(slot)
                    self._inline_np[e, slot] = False
                    self.free_slots[e].append(slot)
                # else: the slot was re-used meanwhile — drop the stale
                # recycle request
            self._recycle_pending[e] = keep
            if keep:  # still blocked: revisit on a later drain
                self._recycle_dirty.add(e)

    def _push(self, ens: int, op) -> None:
        """Enqueue one pending entry (timestamped for the queue-wait
        latency component) and arm the burst trigger.  Write entries
        register in the per-slot pending-write index here — the ONE
        choke point every keyed write passes — and deregister when
        their entry resolves or fails; a slot with a nonzero count
        never serves a lease-protected fast read."""
        if op.kind != eng.OP_GET:
            if isinstance(op, _PendingBatch):
                # whole-batch note on the [E][S] slab row
                pw = self._pending_writes[ens]
                for s in op.slot:
                    pw[s] += 1
            else:
                self._note_write(ens, op.slot)
            if self._storage_degraded is not None:
                # read-only degradation (ARCHITECTURE §15): the WAL
                # cannot take the durability barrier, so no write may
                # queue toward an ack.  The entry fails through the
                # normal path (notes just taken are un-noted, handles
                # released, slots recycled); reads flow on.
                self._fail_entry(ens, op)
                return
            if self._obs and op.kind in (eng.OP_PUT, eng.OP_CAS):
                self._obs_note_put_bytes(
                    ens, op.handle if isinstance(op, _PendingBatch)
                    else (op.handle,))
        op.t_enq = time.perf_counter()
        self.queues[ens].append(op)
        self._queue_rounds[ens] += op.n
        self._active.add(ens)
        self._maybe_kick(ens)

    def _queue_recycle(self, ens: int, item: Tuple[Any, int, int]
                       ) -> None:
        self._recycle_pending[ens].append(item)
        self._recycle_dirty.add(ens)

    def _maybe_kick(self, ens: int) -> None:
        """Burst trigger: a queue that just reached a full launch's
        depth flushes NOW (deferred to the next runtime turn, never
        reentrant inside an enqueue) instead of waiting out the tick —
        batching is for amortization, not added latency.  Only in
        timer-driven mode; caller-driven services control their own
        flush points."""
        if self.tick is None or self._kick_pending:
            return
        if self._queue_rounds[ens] < self.max_k:
            return
        self._kick_pending = True

        def kick() -> None:
            self._kick_pending = False
            self.flush()
            # One flush serves max_k per ensemble; a burst deeper
            # than that (its later enqueues hit the _kick_pending
            # guard) keeps draining — including its sub-threshold
            # tail, which is part of the same burst, not a fresh
            # trickle that should wait for the tick.
            if self._active:
                self._kick_pending = True
                self.runtime.defer(kick)
        self.runtime.defer(kick)

    def _schedule(self) -> None:
        if self.tick is None:
            return
        self._timer = self.runtime.schedule(self.tick, self._on_tick)

    def _on_tick(self) -> None:
        try:
            self.flush()
        finally:
            self._schedule()

    def _election_inputs(self) -> Tuple[np.ndarray, np.ndarray]:
        """Elect wherever there is no leader or the leader is down;
        candidate = lowest-index up member (the randomized-timeout
        winner in the reference; the host picks deterministically).
        Runs entirely on the host mirrors — no device round trip."""
        leader = self.leader_np
        leader_up = np.zeros((self.n_ens,), dtype=bool)
        has = leader >= 0
        leader_up[has] = self.up[np.nonzero(has)[0], leader[has]]
        cand_ok = self.up & self.member_np
        any_up = cand_ok.any(1)
        cand = np.where(any_up, cand_ok.argmax(1), -1).astype(np.int32)
        elect = (~has | ~leader_up) & any_up
        return elect, cand

    def _launch(self, kind: np.ndarray, slot: np.ndarray,
                val: np.ndarray, k: int, want_vsn: bool,
                exp_e: Optional[np.ndarray] = None,
                exp_s: Optional[np.ndarray] = None,
                entries: Optional[List[Tuple[int, List[Any]]]] = None,
                elect: Optional[np.ndarray] = None,
                cand: Optional[np.ndarray] = None,
                lease_ok: Optional[np.ndarray] = None):
        """One SYNCHRONOUS ``full_step`` launch + host bookkeeping —
        the two pipeline halves (:meth:`_launch_enqueue` /
        :meth:`_launch_resolve`) composed back to back, shared by
        :meth:`execute` (bulk) and the replica apply path.  Returns np
        result arrays (vsn None unless asked — it is the largest
        transfer and bulk callers rarely need it).

        ``entries`` is the flush's taken queue entries as
        (ensemble, ops) pairs over the ACTIVE ensembles (None for
        bulk execute); the base launch doesn't need them, but the
        replicated subclass (:mod:`..parallel.repgroup`) ships their
        key/payload metadata to its peer hosts.  ``elect``/``cand``/
        ``lease_ok`` may be passed precomputed so a wrapper that must
        OBSERVE the exact launch inputs (to replicate them) sees the
        same vectors this launch consumes — recomputing lease_ok from
        a later ``runtime.now`` could differ.
        """
        fl = self._launch_enqueue(kind, slot, val, k, want_vsn, exp_e,
                                  exp_s, entries, elect, cand, lease_ok)
        out = self._launch_resolve(fl)
        if self._obs:
            # synchronous launches (bulk execute, replica applies,
            # heartbeats) settle here — their obs record must not
            # depend on the pipelined settle path running
            self._obs_flush_settled(fl)
        return out

    def _step_fns(self) -> Tuple[Any, Any, Any, Any]:
        """The (full_step, full_step_wide, full_step_sliced,
        full_step_wide_sliced) programs the launch path dispatches:
        the donated-state variants when donation is on and the engine
        provides them (mesh engines may not).

        An engine subclass that overrides the PLAIN step but inherits
        a specialized variant (test fault injectors, wrappers) must
        not have its override silently bypassed: a donated or SLICED
        variant is only trusted when it is defined by the same class
        (or instance) that defines the plain step — otherwise the
        launch falls back to the plain full-grid program (slicing is
        an optimization, never a semantic requirement)."""
        e = self.engine

        def definer(attr):
            for c in type(e).__mro__:
                if attr in c.__dict__:
                    return c
            return None

        def variant(name: str, plain_name: str, fallback):
            """The named specialized program, trusted only when its
            definer matches the plain step's (None = use fallback)."""
            fn = getattr(e, name, None)
            if fn is None:
                return fallback
            if name in getattr(e, "__dict__", {}):
                return fn  # instance-level pair: trust it
            return (fn if definer(name) is definer(plain_name)
                    else fallback)

        wide = getattr(e, "full_step_wide", None)
        sliced = variant("full_step_sliced", "full_step", None)
        wide_sliced = variant("full_step_wide_sliced",
                              "full_step_wide", None)
        if self._donate:
            fns = (variant("full_step_donate", "full_step",
                           e.full_step),
                   variant("full_step_wide_donate", "full_step_wide",
                           wide),
                   # a rejected sliced step stays rejected: its
                   # donated form must not resurrect it
                   (variant("full_step_sliced_donate",
                            "full_step_sliced", sliced)
                    if sliced is not None else None),
                   (variant("full_step_wide_sliced_donate",
                            "full_step_wide_sliced", wide_sliced)
                    if wide_sliced is not None else None))
        else:
            fns = (e.full_step, wide, sliced, wide_sliced)
        if not self._obs:
            return fns
        # compile telemetry: every step variant the launch dispatches
        # reports its executable-cache misses (ARCHITECTURE §11)
        names = ("step", "step_wide", "step_sliced",
                 "step_wide_sliced")
        return tuple(self._watched(n, f)
                     for n, f in zip(names, fns))

    def _watched(self, name: str, fn):
        """Memoized CompileWatch wrapper around a launch program (a
        changed underlying fn — engine swap, donate flip — re-wraps)."""
        if fn is None:
            return None
        w = self._compile_watch.get(name)
        if w is None or w.fn is not fn:
            w = self._compile_watch[name] = obs.CompileWatch(
                fn, name, on_miss=self._on_compile_event)
        return w

    def _on_compile_event(self, ev: Dict[str, Any]) -> None:
        """One watched program compiled: count it by phase (warmup
        coverage vs a serve-time first-use leak — the latter is the
        dispatch-p99 bug the counter exists to catch) and keep the
        event in the service-local log the flight dumps carry."""
        phase = "warmup" if self._in_warmup else "serve"
        self._c_compile.labels(phase).inc()
        self._c_compile_ms.labels(phase).inc(ev.get("compile_ms", 0.0))
        self._compile_log.append({**ev, "phase": phase})

    def _launch_enqueue(self, kind: np.ndarray, slot: np.ndarray,
                        val: np.ndarray, k: int, want_vsn: bool,
                        exp_e: Optional[np.ndarray] = None,
                        exp_s: Optional[np.ndarray] = None,
                        entries: Optional[List[Tuple[int,
                                                     List[Any]]]] = None,
                        elect: Optional[np.ndarray] = None,
                        cand: Optional[np.ndarray] = None,
                        lease_ok: Optional[np.ndarray] = None
                        ) -> _InFlightLaunch:
        """ENQUEUE half of a launch: build + upload the inputs,
        dispatch the fused step, the result pack, and the packed d2h
        transfer — all asynchronous — and return the in-flight record.
        No host read of device data happens here, so while batch N's
        packed vector is in flight the host is free to enqueue batch
        N+1 against the new (not yet materialized) ``EngineState`` —
        the overlap :meth:`flush` exploits at ``pipeline_depth`` > 1.
        """
        del entries  # base launch doesn't need them (subclass hook)
        jnp = self._jnp
        if elect is None:
            elect, cand = self._election_inputs()
        now = self.runtime.now
        if lease_ok is None:
            lease_ok = self.lease_until > now

        t0 = time.perf_counter()
        plan = self._wide_plan(kind, slot, val, k, exp_e, exp_s)
        step, step_wide, step_sliced, step_wide_sliced = \
            self._step_fns()
        # Active-column compaction, two strengths (the payload and
        # the grid both decouple from E):
        # - SLICED launch (single-shard engines, E >= SLICE_MIN_E,
        #   |A| bucketed at or under E/4): the fused step itself
        #   runs on the gathered [K, A] grid — compute, HBM traffic,
        #   op-plane h2d and the packed result all scale with the
        #   live working set.  The active set must include every
        #   electing column (their rounds run inside the same
        #   launch).
        # - PACK-GATHER (mesh engines, small/mid grids, or |A| above
        #   E/4): the step keeps the full grid; only the packed
        #   result gathers down to [K, A] (the d2h cut alone).
        # Buckets ride the pow2 A ladder (mirroring the K ladder's
        # compile-reuse discipline).  Device-resident planes skip
        # compaction (reading the kind plane back would break the
        # zero-transfer contract).  The wide path compacts too: the
        # scheduler only rearranges ops WITHIN their ensemble column,
        # so the [K, E] planes' active set is the plan's as well.
        active = aidx_j = shard_active = None
        a_width = 0
        sliced = False
        if self._compact and k and not isinstance(kind, jax.Array):
            cols = np.flatnonzero(
                (np.asarray(kind) != eng.OP_NOOP).any(axis=0)
                | np.asarray(elect, bool))
            if cols.size and self._mesh_shards:
                # Compaction-aware SHARDING: the |A| bucket is
                # computed PER ENS-SHARD — every shard packs the same
                # pow2 width (the busiest shard's bucket) of its own
                # LOCAL columns, so the bucketing, the column gather
                # and the packed d2h payload all stay shard-local
                # (no replicated index constraint, no all-gather).
                # The step itself keeps the full grid (a sharded E
                # axis cannot slice across shards) — this is the
                # pack-gather strength only.
                from riak_ensemble_tpu.ops import schedule as sch
                per_shard, a_loc = sch.shard_active_columns(
                    cols, self.n_ens, self._mesh_shards, A_BUCKET_MIN)
                if a_loc < self.n_ens // self._mesh_shards:
                    active = cols.astype(np.int32)
                    a_width = a_loc
                    shard_active = per_shard
                    pad = np.zeros((self._mesh_shards, a_loc),
                                   np.int32)
                    for si, p in enumerate(per_shard):
                        pad[si, :p.size] = p
                    aidx_j = self._shard_aidx(pad)
            elif cols.size:
                a_b = A_BUCKET_MIN
                while a_b < cols.size:
                    a_b <<= 1
                if a_b < self.n_ens:
                    active = cols.astype(np.int32)
                    a_width = a_b
                    have = (step_wide_sliced if plan is not None
                            else step_sliced)
                    sliced = (have is not None
                              and self.n_ens >= SLICE_MIN_E
                              and a_b * 4 <= self.n_ens)
                    # sliced pads aim OUT OF RANGE (index E) so the
                    # state scatter drops them; the pack gather pads
                    # with column 0 (ignored by the host unpack)
                    pad = np.full((a_b,),
                                  self.n_ens if sliced else 0,
                                  np.int32)
                    pad[:cols.size] = active
                    aidx_j = jnp.asarray(pad)
        # h2d slimming (the tunnel link is the throughput ceiling in
        # both directions): the lease plane uploads as [E] (sliced:
        # [A]) and broadcasts to the op-plane shape device-side; the
        # up mask uploads only when the failure detector actually
        # changed it (sliced launches gather it on device).  EVERY
        # input upload belongs to the h2d mark — an asarray inlined
        # into the step call would bill its (synchronous) transfer to
        # 'dispatch' and make the async-enqueue number read
        # milliseconds of jitter it doesn't have (VERDICT r3 #4).
        a_n = 0 if active is None else len(active)

        def cslice(p):
            """Host column slice [K, E](, W) → [K, a_width](, W);
            padding columns stay NOOP/zero."""
            out = np.zeros(p.shape[:1] + (a_width,) + p.shape[2:],
                           p.dtype)
            out[:, :a_n] = np.asarray(p)[:, active]
            return out

        def vslice(v, dtype):
            out = np.zeros((a_width,), dtype)
            out[:a_n] = np.asarray(v)[active]
            return out

        e_w = a_width if sliced else self.n_ens
        if plan is not None:
            g_b, _, w_b = plan.kind.shape
            lease_np = (vslice(lease_ok, bool) if sliced
                        else np.asarray(lease_ok))
            lease_j = jnp.broadcast_to(
                jnp.asarray(lease_np)[None, :, None],
                (g_b, e_w, w_b))
            kp = (cslice(plan.kind), cslice(plan.slot),
                  cslice(plan.val), cslice(plan.exp_epoch),
                  cslice(plan.exp_seq)) if sliced else (
                  plan.kind, plan.slot, plan.val, plan.exp_epoch,
                  plan.exp_seq)
            kind_j, slot_j, val_j = (jnp.asarray(kp[0]),
                                     jnp.asarray(kp[1]),
                                     jnp.asarray(kp[2]))
            exp_e_j = jnp.asarray(kp[3])
            exp_s_j = jnp.asarray(kp[4])
        else:
            g_b = w_b = 0
            lease_np = (vslice(lease_ok, bool) if sliced
                        else np.asarray(lease_ok))
            lease_j = (jnp.broadcast_to(jnp.asarray(lease_np),
                                        (k, e_w))
                       if k else jnp.zeros((0, self.n_ens), bool))
            if sliced:
                kind_j, slot_j, val_j = (jnp.asarray(cslice(kind)),
                                         jnp.asarray(cslice(slot)),
                                         jnp.asarray(cslice(val)))
                exp_e_j = (None if exp_e is None
                           else jnp.asarray(cslice(exp_e)))
                exp_s_j = (None if exp_s is None
                           else jnp.asarray(cslice(exp_s)))
            else:
                kind_j, slot_j, val_j = (jnp.asarray(kind),
                                         jnp.asarray(slot),
                                         jnp.asarray(val))
                exp_e_j = None if exp_e is None else jnp.asarray(exp_e)
                exp_s_j = None if exp_s is None else jnp.asarray(exp_s)
        if sliced:
            elect_j = jnp.asarray(vslice(elect, bool))
            cand_j = jnp.asarray(vslice(cand, np.int32))
        else:
            elect_j, cand_j = jnp.asarray(elect), jnp.asarray(cand)
        up_j = self._up_device()
        t1 = time.perf_counter()

        # Rollback snapshots: under async dispatch a device failure
        # surfaces at the d2h fetch in the RESOLVE half, after
        # self.state was replaced with the failed computation's
        # poisoned arrays; without rolling back, every later launch
        # would consume the poison and fail forever.  (JAX arrays are
        # immutable, so the state snapshot stays valid — unless the
        # step DONATED them; lease_until is mutated in place, so it
        # needs a copy.)
        state_snapshot = self.state
        leader_snapshot = self.leader_np
        lease_snapshot = self.lease_until.copy()
        attr = ("full_step_wide_sliced_donate"
                if plan is not None and sliced
                else "full_step_wide_donate" if plan is not None
                else "full_step_sliced_donate" if sliced
                else "full_step_donate")
        donated = (self._donate
                   and getattr(self.engine, attr, None) is not None)
        try:
            if plan is not None:
                if sliced:
                    state, won, res = step_wide_sliced(
                        self.state, aidx_j, elect_j, cand_j, kind_j,
                        slot_j, val_j, lease_j, up_j,
                        exp_epoch=exp_e_j, exp_seq=exp_s_j)
                else:
                    state, won, res = step_wide(
                        self.state, elect_j, cand_j, kind_j, slot_j,
                        val_j, lease_j, up_j, exp_epoch=exp_e_j,
                        exp_seq=exp_s_j)
                res = _wide_to_packed_layout(res, g_b, w_b, e_w)
                k_eff = g_b * w_b
                self.wide_launches += 1
            else:
                if sliced:
                    state, won, res = step_sliced(
                        self.state, aidx_j, elect_j, cand_j, kind_j,
                        slot_j, val_j, lease_j, up_j,
                        exp_epoch=exp_e_j, exp_seq=exp_s_j)
                else:
                    state, won, res = step(
                        self.state, elect_j, cand_j, kind_j, slot_j,
                        val_j, lease_j, up_j, exp_epoch=exp_e_j,
                        exp_seq=exp_s_j)
                k_eff = k
            self.state = state
            # a sliced launch's result planes are ALREADY A-width;
            # pack-gather mode hands the pack the index vector
            flat = self._pack(won, res, want_vsn,
                              active_idx=None if sliced else aidx_j)
            # Kick the packed vector's d2h transfer off NOW — the
            # resolve half (possibly a full flush later) only blocks
            # on its completion, so the transfer rides under the next
            # batch's device step instead of serializing after it.
            start = getattr(flat, "copy_to_host_async", None)
            if start is not None:
                start()
        except BaseException:
            self._rollback_launch(state_snapshot, leader_snapshot,
                                  lease_snapshot, donated)
            raise
        t2 = time.perf_counter()
        host_planes = not isinstance(kind, jax.Array)
        return _InFlightLaunch(
            flat=flat, rec={"h2d": t1 - t0, "dispatch": t2 - t1},
            k=k, k_eff=k_eff, want_vsn=want_vsn, plan=plan, w_b=w_b,
            kind_np=np.asarray(kind) if host_planes else None,
            elect=elect, cand=cand, now=now,
            state_snapshot=state_snapshot,
            leader_snapshot=leader_snapshot,
            lease_snapshot=lease_snapshot, donated=donated,
            active=active, a_width=a_width, sliced=sliced,
            n_shards=self._mesh_shards, shard_active=shard_active,
            op_slot_np=np.asarray(slot) if host_planes else None,
            flush_id=obs.next_flush_id() if self._obs else 0,
            t_join=t0)

    def _shard_aidx(self, pad: np.ndarray):
        """Place a ``[n_shards, A_loc]`` per-shard local active-index
        matrix so row s lands on ens-shard s (the shard-wise packer's
        P('ens', None) operand) — an uncommitted upload would leave
        the placement to GSPMD and could round-trip through a
        replicate step."""
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(
            pad, NamedSharding(self.engine.mesh,
                               PartitionSpec("ens", None)))

    def _fetch_packed(self, fl: _InFlightLaunch) -> np.ndarray:
        """Block until the launch's packed result is on the host (the
        ONE device→host transfer per launch).  Isolated as a seam so
        tests can inject d2h latency deterministically."""
        return np.asarray(fl.flat)

    def _rollback_launch(self, state_snapshot, leader_snapshot,
                         lease_snapshot, donated: bool) -> None:
        """Restore the pre-launch device state + host mirrors after a
        failed launch.  The host mirrors roll back with the state: a
        mirror claiming a leader the restored device state doesn't
        have would suppress re-election forever.  A DONATED launch has
        no rollback — the snapshot's buffers were consumed by the
        failed program — so the state stays poisoned (restart or
        restore() recovers); surfaced as a trace event."""
        arr = state_snapshot.epoch
        deleted = getattr(arr, "is_deleted", None)
        if donated and deleted is not None and deleted():
            self._emit("svc_state_poisoned",
                       {"reason": "donated launch failed; no rollback "
                                  "snapshot survives buffer donation"})
            return
        self.state = state_snapshot
        self.leader_np = leader_snapshot
        self.lease_until = lease_snapshot

    def _launch_resolve(self, fl: _InFlightLaunch,
                        wait_key: str = "device_d2h"):
        """RESOLVE half of a launch: block on the packed transfer,
        unpack, apply the host mirrors (leader/lease), run the
        corruption→exchange sweep, and finish the launch's latency
        record.  Under the pipelined flush this runs one round LATE —
        after batch N+1's enqueue — so the block recorded under
        ``wait_key`` (``inflight_wait`` there) is only the part of the
        device round + transfer the host failed to overlap.

        Corruption deferral rides the same structure: the ``corrupt``
        planes are the only host inspection of a round's integrity
        gate, and they are read HERE — one round late in the pipelined
        flush — with the exchange dispatched onto the CURRENT device
        state chain (which already includes batch N+1's step).  The
        exchange therefore lands before batch N+1's results are
        resolved: a flagged ensemble is repaired before its next
        result is acked, the semantics the in-round sweep provided.
        """
        rec = fl.rec
        t2 = time.perf_counter()
        try:
            flat = self._fetch_packed(fl)
            rec[wait_key] = time.perf_counter() - t2
            t3 = time.perf_counter()
            e, m = self.n_ens, self.n_peers
            # Native single-pass unpack (docs/ARCHITECTURE.md §12):
            # one C traversal scatters the packed payload straight
            # into full-width planes; election-only launches (k == 0)
            # and layout surprises fall back to the Python oracle.
            planes8 = None
            if fl.n_shards:
                # shard-wise mesh payload: per-shard blocks, Python
                # unpack per block (the native kernel walks the
                # single-block layout; this path trades it for zero
                # cross-device gathers on the pack side)
                planes8 = unpack_results_sharded(
                    flat, e, m, fl.k_eff, fl.want_vsn, fl.n_shards,
                    shard_active=fl.shard_active, a_width=fl.a_width)
                native_arm = False
            else:
                if self._native_resolve is not None and fl.k_eff:
                    planes8 = self._native_resolve.unpack(
                        flat, e, m, fl.k_eff, fl.want_vsn, fl.active,
                        fl.a_width, fl.sliced)
                native_arm = planes8 is not None
            if planes8 is None:
                planes8 = unpack_results(flat, e, m, fl.k_eff,
                                         fl.want_vsn, active=fl.active,
                                         a_width=fl.a_width,
                                         sliced=fl.sliced)
            (won_np, quorum_ok, corrupt_np, committed, get_ok, found,
             value, vsn) = planes8
            # per-flush attribution of the resolve half's arm: the
            # derived resolve_native/resolve_fallback marks accumulate
            # every native-eligible stage (unpack here; the mirror
            # scatter and WAL encode add theirs), excluded from the
            # additive total like 'enqueue'
            arm_key = ("resolve_native" if native_arm
                       else "resolve_fallback")
            rec[arm_key] = (rec.get(arm_key, 0.0)
                            + (time.perf_counter() - t3))
            if native_arm:
                self.native_resolve_flushes += 1
            else:
                self.fallback_resolve_flushes += 1
            # Compaction observability: the actual d2h bytes vs the
            # full-width [K, E] layout's, and the packed-grid
            # occupancy (skewed/partial load drives this toward 0).
            self.payload_bytes += int(flat.nbytes)
            self.payload_bytes_full_width += packed_nbytes(
                e, m, fl.k_eff, fl.want_vsn)
            # shard-wise launches pack a_width columns PER SHARD, so
            # the effective packed width is a_width * n_shards
            self._occ_sum += (fl.a_width * max(fl.n_shards, 1) / e
                              if fl.active is not None else 1.0)
            self._occ_launches += 1
            if self._obs:
                fl.payload_nbytes = int(flat.nbytes)
                self._launches_total += 1
                # device-round share: the rows this launch actually
                # carried (the compacted active set, or every live
                # row for a full-width launch)
                if fl.active is not None:
                    self.tenant_rounds[fl.active] += 1
                else:
                    self.tenant_rounds[self._live] += 1
            corrupt = corrupt_np if fl.k else None
            if fl.plan is not None:
                # Route the [G*W, E] results back to the caller's
                # [K, E] op order; padding/NOOP rows read garbage
                # lanes, so they are masked to the scalar path's NOOP
                # results (all-false, zero value/vsn).
                w_b = fl.w_b
                ee_idx = np.arange(e, dtype=np.int32)[None, :]
                fli = fl.plan.map_g * w_b + fl.plan.map_w
                act = fl.kind_np != eng.OP_NOOP
                committed = committed[fli, ee_idx] & act
                get_ok = get_ok[fli, ee_idx] & act
                found = found[fli, ee_idx] & act
                value = np.where(act, value[fli, ee_idx], 0)
                if vsn is not None:
                    vsn = np.where(act[..., None], vsn[fli, ee_idx], 0)

            # Host mirror: a won election installed our candidate.
            self.leader_np = np.where(won_np, fl.cand, self.leader_np)
            #: the round's quorum confirmations, kept on the launch
            #: record for subclass resolve hooks (the replication
            #: group's delta frames ship them so replica lanes renew
            #: leases exactly as a re-executed launch would)
            fl.quorum_np = quorum_ok

            # Lease renewal: a won election, or any round in which the
            # leader confirmed its epoch with a quorum — the
            # leader_tick renewal (peer.erl:1092-1095), which covers
            # read-only leaders (reads ride the epoch-check round),
            # not just committers.
            renew = won_np | quorum_ok
            self.lease_until[renew] = fl.now + self.config.lease()

            # Device-detected integrity failures -> anti-entropy
            # exchange for the affected ensembles (the tree_corrupted
            # -> repair -> exchange flow, peer.erl:1276-1277 +
            # riak_ensemble_exchange): divergent slots re-adopt the
            # newest hash-valid copy and the replicas' trees are
            # rebuilt; unreplaceable (all-copies-bad) slots stay
            # flagged rather than being blessed.
            if corrupt is not None and corrupt.any():
                jnp = self._jnp
                tx = time.perf_counter()
                self.corruptions += int(corrupt.sum())
                run = corrupt.any(1)
                # flagged rows fall off the read fast path until the
                # exchange syncs them — a known-corrupt row's reads
                # must keep taking the device round (its integrity
                # gate vets every access)
                self._corrupt_rows |= run
                self.state, diverged, synced = self.engine.exchange_step(
                    self.state, jnp.asarray(run), self._up_device())
                synced_np = np.asarray(synced)
                self.repairs += int(
                    np.asarray(diverged)[synced_np].sum())
                # rows the exchange synced re-admit fast reads; any
                # residual damage re-flags on its next device access
                self._corrupt_rows &= ~(run & synced_np)
                self._emit("svc_exchange", {"ensembles": int(run.sum())})
                rec["exchange"] = time.perf_counter() - tx
            self.flushes += 1
            rec["unpack"] = (time.perf_counter() - t3
                             - rec.get("exchange", 0.0))
        except BaseException:
            self._rollback_launch(fl.state_snapshot, fl.leader_snapshot,
                                  fl.lease_snapshot, fl.donated)
            raise
        # A won election bumped the row's ballot epoch: the next
        # device access of each object re-versions it (update_key,
        # peer.erl:1564-1596), so the fast path's vsn mirror is stale
        # for the whole row — drop it (want_vsn reads take the device
        # round, whose resolve re-mirrors the rewritten versions;
        # plain value reads stay fast, the rewrite never changes
        # values).  Only on a SUCCESSFUL launch: the except path
        # rolled the election back.
        if won_np.any():
            self._slot_vsn_ok[won_np] = False
            # election-churn mirror (the health verb's signal): one
            # count per won election per row, successful launches only
            self.elections_np[won_np] += 1
        # Leader changes (won elections) notify watchers only on a
        # SUCCESSFUL launch — the except path above rolled the mirror
        # back, and a watcher told of a rolled-back leader would act
        # on state the device never kept.
        self._notify_leader_changes(fl.leader_snapshot)
        self._emit("svc_launch", {
            "k": fl.k, "elections": int(fl.elect.sum()),
            "won": int(won_np.sum()),
            "corrupt_replicas": (int(corrupt.sum())
                                 if corrupt is not None else 0),
        })
        # Launch-side latency record; the flush settle augments the
        # same dict with queue_wait/wal/resolve (bulk execute()
        # callers get the launch components alone).  'enqueue' is a
        # DERIVED mark (h2d + dispatch — the whole enqueue half) kept
        # out of the total sum.
        rec["k"] = fl.k
        rec["enqueue"] = rec.get("h2d", 0.0) + rec.get("dispatch", 0.0)
        rec["total"] = sum(v for c, v in rec.items()
                           if c not in DERIVED_MARKS)
        self.lat_records.append(rec)
        return committed, get_ok, found, value, vsn

    def _wide_plan(self, kind, slot, val, k, exp_e, exp_s):
        """Schedule host [K, E] planes into conflict-free wide rounds
        when enabled and profitable (G <= 2 — the warmed shapes); None
        keeps the scalar scan.  Pure function of the op planes, so a
        replication-group replica recomputes the identical plan from
        the shipped planes.

        Serialization contract: a wide flush executes its ops in
        (group, lane) order — per-SLOT order is preserved (the g-th
        same-slot op runs in round g), but commit seqs across
        DIFFERENT slots may interleave differently than the scalar
        scan's k order.  That is a valid serialization with exactly
        the reference's freedom (key-hashed workers complete distinct
        keys in unspecified relative order, peer.erl:1220-1225); both
        orders are deterministic per mode, which is what replication
        needs."""
        if (not self._wide or k <= 1 or isinstance(kind, jax.Array)
                or getattr(self.engine, "full_step_wide", None) is None):
            return None
        from riak_ensemble_tpu.ops import schedule as sched_mod
        zeros = np.zeros((k, self.n_ens), np.int32)
        plan = sched_mod.schedule_wide(
            kind, slot, val, None,  # lease rides [E]-broadcast instead
            zeros if exp_e is None else exp_e,
            zeros if exp_s is None else exp_s,
            max_groups=2)
        if plan is not None and os.environ.get(
                "RETPU_VALIDATE_WIDE", "") == "1":
            # opt-in guard for the kernel's conflict-free precondition
            # (kv_step_scan_wide docstring): a scheduler bug here would
            # otherwise surface as silent nondeterministic state
            eng.validate_wide_plane(plan.kind, plan.slot)
        return plan

    def _emit(self, kind: str, payload: Any) -> None:
        """Feed the runtime's tracing hook (utils.trace.Tracer) when
        one is installed; free otherwise."""
        tr = getattr(self.runtime, "trace", None)
        if tr is not None:
            tr(kind, payload)

    def scrub(self) -> Dict[str, int]:
        """Full anti-entropy sweep — the maintenance form of the
        corruption-triggered exchange (riak_ensemble_exchange +
        peer_tree:do_repair): verify EVERY replica's tree (the BFS
        verify, synctree.erl:549-571), run the exchange over
        ensembles holding damage (newest hash-valid copy wins,
        adopters rebuild), and report what was found/healed.  Reads
        only touch accessed slots, so damage on cold slots is
        invisible to the data path until a scrub or access — the
        operator cadence knob the reference gets from AAE timers."""
        jnp = self._jnp
        # settle in-flight launches: the sweep's damage/heal counters
        # must not race a pending round's own repair bookkeeping
        self._drain_launches()
        self._scrubbed_at_flush = self.flushes
        node_bad, leaf_bad = self.engine.verify_trees(self.state)
        bad = np.asarray(node_bad) | np.asarray(leaf_bad)    # [E, M]
        found = int(bad.sum())
        if not found:
            return {"replicas_damaged": 0, "replicas_healed": 0,
                    "ensembles_swept": 0}
        run = bad.any(1)
        self.corruptions += found
        state_snapshot = self.state
        try:
            self.state, diverged, synced = self.engine.exchange_step(
                self.state, jnp.asarray(run), self._up_device())
            node_bad2, leaf_bad2 = self.engine.verify_trees(self.state)
            still = (np.asarray(node_bad2)
                     | np.asarray(leaf_bad2)) & bad
        except BaseException:
            self.state = state_snapshot
            raise
        healed = found - int(still.sum())
        self.repairs += int(
            np.asarray(diverged)[np.asarray(synced)].sum())
        # the sweep's verdict updates the read fast path's corrupt
        # flags: swept rows with residual damage stay off the fast
        # path, healed ones re-admit it
        self._corrupt_rows = np.where(run, still.any(1),
                                      self._corrupt_rows)
        self._emit("svc_scrub", {"damaged": found, "healed": healed})
        return {"replicas_damaged": found, "replicas_healed": healed,
                "ensembles_swept": int(run.sum())}

    def latency_breakdown(self) -> Dict[str, Dict[str, float]]:
        """Per-component launch-latency percentiles (ms) over the
        recent flushes: where a commit's latency actually goes —
        queue_wait (enqueue → launch), h2d (input build + upload),
        dispatch (async enqueue of the step/pack/transfer), then
        EITHER device_d2h (depth-1: device math + packed result
        fetch, serial) OR inflight_wait (pipelined: the part of the
        device round + transfer the overlap failed to hide — this
        shrinking below device_d2h is the pipeline working), unpack,
        exchange (corruption-triggered), wal (durability barrier),
        resolve (future fan-out).  'enqueue' is a derived mark
        (h2d + dispatch — the whole enqueue half) excluded from the
        'total' sum, as are 'resolve_native'/'resolve_fallback' (the
        resolve half's per-arm share — unpack + mirror scatter + WAL
        encode attributed to whichever arm ran, ARCHITECTURE §12)
        and 'enqueue_native'/'enqueue_fallback' (the slab enqueue
        path's lane-build + op-plane-pack share, already inside
        queue_wait, attributed to whichever pack arm ran — §12b).  ``svc_compaction`` (the deferred WAL fold, a
        rare EVENT rather than a per-launch component) is reported
        over its own occurrences only — averaging it into 1000+
        launch records would both hide the pause (p99 = 0) and
        inject zero samples into every launch component.  This is
        what makes the BASELINE p99 target analyzable before and
        after a platform change (VERDICT r2)."""
        recs = list(self.lat_records)
        out: Dict[str, Dict[str, float]] = {}
        events = [r for r in recs if "svc_compaction" in r]
        recs = [r for r in recs if "svc_compaction" not in r]
        if events:
            vals = np.asarray([r["svc_compaction"]
                               for r in events]) * 1e3
            out["svc_compaction"] = {
                "p50_ms": float(np.percentile(vals, 50)),
                "p99_ms": float(np.percentile(vals, 99)),
                "mean_ms": float(vals.mean())}
        if not recs:
            return out
        comps = sorted({c for r in recs for c in r if c != "k"})
        for c in comps:
            vals = np.asarray([r.get(c, 0.0) for r in recs]) * 1e3
            out[c] = {"p50_ms": float(np.percentile(vals, 50)),
                      "p99_ms": float(np.percentile(vals, 99)),
                      "mean_ms": float(vals.mean())}
        return out

    def stats(self) -> Dict[str, Any]:
        """Observability snapshot (the get_info/count_quorum analog
        for the scale path)."""
        wal_stats = (self._wal.stats() if self._wal is not None
                     else None)
        return {
            "flushes": self.flushes,
            "ops_served": self.ops_served,
            "svc_backpressure": dict(self.svc_backpressure),
            "corruptions_detected": self.corruptions,
            "replicas_repaired": self.repairs,
            "live_payloads": len(self.values),
            "ensembles_with_leader": int((self.leader_np >= 0).sum()),
            "membership_changes_in_flight": int(
                (self._desired_mask | self._pending_mask
                 | self._queued_mask).sum()),
            "queued_ops": sum(self._queue_rounds),
            "execute_unlogged": self._dev_exec_unlogged,
            "wide_launches": self.wide_launches,
            "pipeline_depth": self.pipeline_depth,
            "launches_in_flight": len(self._inflight_launches),
            "rmw_conflicts": self.rmw_conflicts,
            "rmw_device_fastpath": self.rmw_device_fastpath,
            "rmw_enqueue_coalesced": self.rmw_enqueue_coalesced,
            # lease-protected read fast path: mirror-served reads vs
            # device-round fallbacks (by reason), and what fraction of
            # live ensembles hold a margin-valid lease right now
            "read_fastpath_hits": self.read_fastpath_hits,
            "read_fastpath_misses": self.read_fastpath_misses,
            "read_fastpath_miss_reasons": dict(
                self.read_fastpath_miss_reasons),
            "lease_valid_fraction": self._lease_valid_fraction(),
            # active-column compaction: packed d2h bytes actually
            # moved vs the full-width [K, E] layout, and the mean
            # packed-grid occupancy (a_width / E; 1.0 = uncompacted)
            "payload_bytes": self.payload_bytes,
            "payload_bytes_full_width": self.payload_bytes_full_width,
            "grid_occupancy": (self._occ_sum / self._occ_launches
                               if self._occ_launches else 1.0),
            # WAL-compaction pauses (deferred off the hot path; the
            # svc_compaction latency mark carries the same numbers
            # into latency_breakdown())
            "svc_compaction": {
                "count": self.wal_compactions,
                "last_ms": round(self.wal_compaction_ms_last, 3),
                "total_ms": round(self.wal_compaction_ms_total, 3),
            },
            # storage-recovery plane (ARCHITECTURE §15): the WAL
            # store's corruption-handling evidence plus the
            # degradation decision — same payload as health().  One
            # stats() call feeds both keys: each takes the WAL lock,
            # which the flush path holds across the fsync barrier
            "storage": self._storage_health_section(wal_stats),
            "wal": wal_stats,
            # observability plane (docs/ARCHITECTURE.md §11): the
            # full registry exports via the svcnode `metrics` verb;
            # stats() carries the headline plus per-tenant
            # attribution so existing stats consumers see both
            "obs_enabled": self._obs,
            "flight_anomalies": self.flight.anomalies,
            "tenants": self.tenant_stats(top=8),
            # native single-pass resolve kernel (ARCHITECTURE §12):
            # which arm each settled flush's resolve half ran on —
            # the per-flush split rides the resolve_native /
            # resolve_fallback latency marks
            "native_resolve": {
                "enabled": self._native_resolve is not None,
                "flushes": self.native_resolve_flushes,
                "fallback_flushes": self.fallback_resolve_flushes,
            },
            # slab enqueue half (ARCHITECTURE §12): which pack arm
            # each flush's op planes were scattered by (C++ kernel vs
            # numpy lanes; both zero when RETPU_NATIVE_ENQUEUE=0
            # pinned the per-entry oracle pack), and the completion
            # slab's one-wake-per-flush ledger
            "native_enqueue": {
                "slab_path": self._enq_slab,
                "kernel": self._native_enqueue is not None,
                "flushes": self.native_enqueue_flushes,
                "fallback_flushes": self.fallback_enqueue_flushes,
            },
            "completion_slab": {
                "wakes": self.completion_wakes,
                "rows": self.completion_rows,
            },
            # sharded resolve/enqueue workers (ARCHITECTURE §16):
            # pool width and how many flushes actually chunked
            "resolve_shards": {
                "shards": self._resolve_shards,
                "sharded_flushes": self.sharded_flushes,
            },
        }

    def _lease_valid_fraction(self) -> float:
        """Fraction of live ensembles whose lease is margin-valid on
        the monotonic clock right now — the fast path's best-case
        coverage (stats observability for the read router)."""
        live = self._live
        if not live.any():
            return 0.0
        horizon = self.runtime.now + self._read_margin
        return float((self.lease_until[live] > horizon).mean())

    def health(self, ens: Optional[int] = None) -> Dict[str, Any]:
        """Ensemble-health snapshot — the scale path's analog of the
        reference's cluster status / ``get_info`` surface, sourced
        ENTIRELY from host mirrors (leader_np, lease_until, the
        corruption flags, the committed-vsn slab): zero device
        rounds, callable on a loaded service at verb rate.

        ``ens=None`` answers the service level: per-row aggregates
        (leadered/electing/corrupt/lease-valid row counts, total
        election churn) plus the service depths a capacity dashboard
        needs — WAL record depth, queued device rounds, launches in
        flight, per-slot pending writes, live payload handles.

        ``ens=N`` answers one row: leader, margin-valid lease (and
        raw remaining seconds), election churn, corrupt flag, queue
        depth, pending writes, live keys, and the row's COMMITTED
        epoch/seq high-water (the max (epoch, seq) over the
        committed-vsn mirror — the host-visible analog of the ballot
        epoch; rows whose mirror was invalidated by a fresh election
        report the pre-election watermark until their next device
        read re-mirrors).

        Everything is plain ints/floats/strings — the svcnode
        ``("health",)`` verb ships it through the restricted wire
        codec verbatim."""
        now = self.runtime.now
        horizon = now + self._read_margin
        if ens is not None:
            assert 0 <= ens < self.n_ens, f"bad ensemble {ens}"
            vsn_row = self._slot_vsn_np[ens]
            ok_row = self._slot_vsn_ok[ens]
            if ok_row.any():
                epochs = vsn_row[ok_row]
                hi = epochs[np.lexsort((epochs[:, 1], epochs[:, 0]))][-1]
                committed = (int(hi[0]), int(hi[1]))
            else:
                committed = (0, 0)
            return {
                "ens": int(ens),
                "live": bool(self._live[ens]),
                "leader": int(self.leader_np[ens]),
                "members": [bool(b) for b in self.member_np[ens]],
                "lease_valid": bool(self.lease_until[ens] > horizon),
                "lease_remaining_s": round(
                    max(0.0, float(self.lease_until[ens]) - now), 6),
                "elections": int(self.elections_np[ens]),
                "corrupt": bool(self._corrupt_rows[ens]),
                "committed_epoch": committed[0],
                "committed_seq": committed[1],
                "queued_ops": int(self._queue_rounds[ens]),
                "pending_writes": (self.n_slots
                                   - self._pending_writes[ens]
                                   .count(0)),
                "live_keys": len(self.key_slot[ens]),
                "tenant": self.tenant_label(ens),
            }
        live = self._live
        elect, _cand = self._election_inputs()
        fp = faults.active_plan()
        out = {
            "schema": "retpu-health-v1",
            "n_ens": int(self.n_ens),
            "live_ensembles": int(live.sum()),
            "ensembles_with_leader": int(
                ((self.leader_np >= 0) & live).sum()),
            "electing": int((elect & live).sum()),
            "lease_valid_fraction": round(
                self._lease_valid_fraction(), 4),
            "corrupt_rows": int(self._corrupt_rows.sum()),
            "elections_total": int(self.elections_np.sum()),
            "wal_records": (int(self._wal.count)
                            if self._wal is not None else None),
            "queued_ops": int(sum(self._queue_rounds)),
            "launches_in_flight": len(self._inflight_launches),
            "pending_writes": int(sum(
                self.n_slots - row.count(0)
                for row in self._pending_writes)),
            "live_payloads": len(self.values),
            "flushes": int(self.flushes),
            "ops_served": int(self.ops_served),
            # the runtime controller's section (ARCHITECTURE §14):
            # always present — `enabled: false` on a stock service —
            # so a dashboard's queries keep their shape when the
            # controller arms, the fault-gauge discipline
            "controller": self.controller.health_section(),
            # storage-recovery plane (ARCHITECTURE §15): always
            # present (degraded: false on a healthy disk) — same
            # constant-shape discipline as the controller section
            "storage": self._storage_health_section(),
        }
        if fp is not None:
            # active fault-injection plan (docs/ARCHITECTURE.md §13):
            # surfaced so an operator reading the health verb can
            # distinguish a running nemesis (injected drops / RTT /
            # fsync delay) from a real outage.  Absent entirely when
            # no plan is armed — a clean box shows a clean verb.
            out["injected"] = fp.describe()
        return out

    def _storage_health_section(self, wal_stats: Optional[Dict[str,
                                Any]] = None) -> Dict[str, Any]:
        """The health verb's storage-recovery section (§15): the
        degradation decision (or its absence), WAL error counts and
        the store's corruption-handling evidence — constant shape so
        dashboard queries survive a disk incident arming it.
        ``wal_stats`` lets a caller that already paid the WAL-lock
        round (stats()) pass it in; the default path reads the
        LOCK-FREE evidence counters so a health scrape never blocks
        behind a flush's fsync barrier."""
        if wal_stats is None:
            wal_stats = (self._wal.evidence()
                         if self._wal is not None else {})
        wal_stats = wal_stats or {}
        return {
            "degraded": self._storage_degraded is not None,
            "mode": (self._storage_degraded or {}).get("mode"),
            "reason": (self._storage_degraded or {}).get("errno"),
            "at_flush": (self._storage_degraded or {}).get("at_flush"),
            "wal_errors": int(self.wal_storage_errors),
            "wal_quarantines": int(wal_stats.get("quarantines", 0)),
            "wal_truncations": int(wal_stats.get("truncations", 0)),
        }

    # -- observability plane (docs/ARCHITECTURE.md §11) ---------------------

    def _register_obs_metrics(self) -> None:
        """Hook this service's counters into its metrics registry.

        Everything the hot path already maintains as a plain
        attribute exports through a COLLECTOR (read at export time —
        no double-writing on the flush path); only genuinely new
        instruments (the flush histogram, per-tenant planes) record
        directly."""
        self.obs_registry.collect(self._obs_service_collect)
        self.obs_registry.collect(self._obs_tenant_collect)
        self.obs_registry.collect(self._obs_cost_collect)
        self.obs_registry.collect(self._obs_fault_collect)
        self.obs_registry.collect(self.controller.collect)
        # live backend memory (device plane telemetry): reads the
        # default device's allocator stats at export time; backends
        # without memory_stats (CPU) export None/NaN rather than 0
        self.obs_registry.gauge(
            "retpu_backend_mem_bytes",
            "bytes in use on the default jax device (NaN when the "
            "backend reports no memory stats)", fn=_backend_mem_bytes)
        self.obs_registry.collect(self._obs_device_mem_collect)

    def _obs_device_mem_collect(self) -> Dict[str, Any]:
        """Per-device memory family (mesh plane telemetry): one
        sample per local device, so an 'ens'-shard imbalance shows up
        as a device-labeled outlier instead of averaging away in the
        default-device gauge."""
        return {
            "retpu_backend_mem_bytes_per_device": obs.registry.family(
                "gauge",
                "bytes in use per local jax device (NaN when the "
                "backend reports no memory stats)",
                _backend_mem_bytes_per_device(), label="device"),
        }

    def _obs_cost_collect(self) -> Dict[str, Any]:
        """Per-bucket XLA cost-analysis gauges captured at warmup
        (labels are step buckets: ``k8``, ``k8_a16``, ...)."""
        return {
            "retpu_step_cost_flops": obs.registry.family(
                "gauge", "warmup-lowered step cost model flops",
                {b: c.get("flops") for b, c in
                 self._step_costs.items()
                 if c.get("flops") is not None}, label="bucket"),
            "retpu_step_cost_bytes": obs.registry.family(
                "gauge", "warmup-lowered step bytes accessed",
                {b: c.get("bytes_accessed") for b, c in
                 self._step_costs.items()
                 if c.get("bytes_accessed") is not None},
                label="bucket"),
        }

    def _obs_fault_collect(self) -> Dict[str, Any]:
        """Injected-fault gauges (docs/ARCHITECTURE.md §13): always
        registered — zeros on a clean box — so a dashboard's queries
        don't change shape when a nemesis arms, and a nonzero
        ``retpu_fault_active`` is the one-glance nemesis flag."""
        def fam(typ, help, val):
            return obs.registry.family(typ, help, {None: val})

        fp = faults.plan()
        c = (fp.counters() if fp is not None else {})
        return {
            "retpu_fault_active": fam(
                "gauge", "1 while a fault-injection plan with live "
                "rules is armed in this process",
                int(fp is not None and fp.active())),
            "retpu_fault_dropped_frames_total": fam(
                "counter", "frames blackholed by injected "
                "directional drops", c.get("dropped_frames", 0)),
            "retpu_fault_delayed_frames_total": fam(
                "counter", "frames delayed by injected per-link RTT",
                c.get("delayed_frames", 0)),
            "retpu_fault_delay_injected_ms_total": fam(
                "counter", "total injected per-link delay",
                c.get("delay_injected_ms", 0.0)),
            "retpu_fault_reordered_frames_total": fam(
                "counter", "adjacent frame pairs swapped by injected "
                "reorder", c.get("reordered_frames", 0)),
            "retpu_fault_fsync_delays_total": fam(
                "counter", "WAL fsync barriers delayed by injection",
                c.get("fsync_delays", 0)),
            "retpu_fault_fsync_delay_injected_ms_total": fam(
                "counter", "total injected fsync delay",
                c.get("fsync_delay_injected_ms", 0.0)),
            # storage fault plane + recovery evidence (§15): same
            # always-registered discipline — zeros on a clean box
            "retpu_fault_storage_errors_total": fam(
                "counter", "injected EIO/ENOSPC storage errors "
                "raised on write/fsync paths",
                c.get("storage_errors_injected", 0)),
            "retpu_fault_torn_writes_total": fam(
                "counter", "injected torn (truncated mid-record) "
                "writes", c.get("torn_writes_injected", 0)),
            "retpu_fault_corrupt_reads_total": fam(
                "counter", "injected bit-flip read corruptions",
                c.get("corrupt_reads_injected", 0)),
            "retpu_recovery_degraded": fam(
                "gauge", "1 while the service is storage-degraded "
                "(read-only / stepped down after WAL EIO/ENOSPC)",
                int(self._storage_degraded is not None)),
            "retpu_recovery_wal_errors_total": fam(
                "counter", "WAL OSErrors observed on the ack path",
                self.wal_storage_errors),
            "retpu_recovery_wal_quarantined_total": fam(
                "counter", "unreplayable WAL logs quarantined aside "
                "(.corrupt.<n>)",
                (self._wal.evidence().get("quarantines", 0)
                 if self._wal is not None else 0)),
        }

    def _flight_extras(self) -> Dict[str, Any]:
        """Flight-dump sections beyond the flush ring (schema v2):
        the per-op SLO tail (slowest acked entries with their stage
        splits), the recent compile events, and — while a fault plan
        is armed — the injected-fault state (so an anomaly dump
        captured mid-nemesis indicts the nemesis, not the code)."""
        fp = faults.active_plan()
        return {
            "slow_ops": (self._slo.slowest(5)
                         if self._slo is not None else []),
            "compile_events": list(self._compile_log),
            "injected_faults": (fp.describe()
                                if fp is not None else {}),
            # the controller's newest journaled decisions: an anomaly
            # captured while the control loop was moving knobs shows
            # WHICH knob moved, and why, next to the flush it hit
            "controller_decisions": self.controller.flight_section(),
        }

    def _obs_service_collect(self) -> Dict[str, Any]:
        def fam(typ, help, val):
            # the collector-family shape lives in obs.registry.family
            return obs.registry.family(typ, help, {None: val})

        occ = (self._occ_sum / self._occ_launches
               if self._occ_launches else 1.0)
        return {
            "retpu_flushes_total": fam(
                "counter", "settled device launches", self.flushes),
            "retpu_ops_served_total": fam(
                "counter", "client ops resolved (fast reads included)",
                self.ops_served),
            "retpu_corruptions_total": fam(
                "counter", "integrity-gate detections",
                self.corruptions),
            "retpu_repairs_total": fam(
                "counter", "replicas the exchange healed",
                self.repairs),
            "retpu_read_fastpath_hits_total": fam(
                "counter", "mirror-served leased reads",
                self.read_fastpath_hits),
            "retpu_read_fastpath_misses_total": fam(
                "counter", "fast-path fallbacks to the device round",
                self.read_fastpath_misses),
            "retpu_svc_backpressure_total": obs.registry.family(
                "counter", "front-end backpressure events (inflight-"
                "cap stalls, slow-reader write-buffer drops)",
                dict(self.svc_backpressure), label="kind"),
            "retpu_rmw_conflicts_total": fam(
                "counter", "host-path kmodify CAS retries",
                self.rmw_conflicts),
            "retpu_rmw_device_fastpath_total": fam(
                "counter", "single-round device RMW commits",
                self.rmw_device_fastpath),
            "retpu_payload_bytes_total": fam(
                "counter", "packed d2h bytes actually fetched",
                self.payload_bytes),
            "retpu_payload_bytes_full_width_total": fam(
                "counter", "what the full-width [K, E] layout would "
                "have moved", self.payload_bytes_full_width),
            "retpu_wal_compactions_total": fam(
                "counter", "WAL folds into a fresh checkpoint",
                self.wal_compactions),
            "retpu_wide_launches_total": fam(
                "counter", "launches through the wide scheduler",
                self.wide_launches),
            "retpu_flight_anomalies_total": fam(
                "counter", "flight-recorder trigger firings (flush "
                "> 5x rolling p50)", self.flight.anomalies),
            "retpu_queued_ops": fam(
                "gauge", "device rounds currently queued",
                sum(self._queue_rounds[e] for e in self._active)),
            "retpu_launches_in_flight": fam(
                "gauge", "dispatched-but-unresolved launches",
                len(self._inflight_launches)),
            "retpu_lease_valid_fraction": fam(
                "gauge", "live rows holding a margin-valid lease",
                round(self._lease_valid_fraction(), 4)),
            "retpu_grid_occupancy": fam(
                "gauge", "mean packed-grid occupancy (a_width / E)",
                round(occ, 4)),
            "retpu_live_payloads": fam(
                "gauge", "host payload-store entries",
                len(self.values)),
            "retpu_ensembles_with_leader": fam(
                "gauge", "rows with a live leader",
                int((self.leader_np >= 0).sum())),
            # process-global by construction (the span store is):
            # every service in the process exports the same counts,
            # which is what a scrape of any one of them should see
            "retpu_span_misses_total": obs.registry.family(
                "counter", "span-store lookups that missed, by "
                "reason (evicted = rolled off the bounded ring; "
                "unknown = this process never recorded the fid)",
                dict(obs.SPANS.misses), label="reason"),
        }

    # -- fleet-scope surfaces (docs/ARCHITECTURE.md §11) --------------------

    def _fleet_self_label(self) -> str:
        """This service's host label in fleet answers: the group
        identity peers dial it by when one exists, else
        hostname:pid — stable within a process, distinct across the
        fleet."""
        addr = getattr(self, "self_addr", None)
        if addr:
            return f"{addr[0]}:{addr[1]}"
        import socket as _socket
        return f"{_socket.gethostname()}:{os.getpid()}"

    def fleet_metrics(self, fmt: Optional[str] = None):
        """Fleet metrics: every host's registry under ``host``
        labels.  On a standalone service the fleet is this host
        alone; :class:`~riak_ensemble_tpu.parallel.repgroup.
        ReplicatedService` overrides the pull to cover its links.
        ``fmt="prometheus"`` answers ONE merged scrape document."""
        label = self._fleet_self_label()
        if fmt == "prometheus":
            return obs.merge_prometheus(
                {label: self.obs_registry.render_prometheus()})
        return {"schema": "retpu-fleet-metrics-v1",
                "hosts": {label: self.obs_registry.snapshot()},
                "clock": {}}

    def fleet_health(self) -> Dict[str, Any]:
        """Fleet health: every host's ``health()`` section keyed by
        host label (standalone: this host alone)."""
        return {"schema": "retpu-fleet-health-v1",
                "hosts": {self._fleet_self_label(): self.health()},
                "clock": {}}

    def fleet_timeline(self, flush_id: int) -> Dict[str, Any]:
        """Clock-aligned cross-host ``obs.timeline``: on a standalone
        service the local record on a trivial axis (in-process
        replica lanes share the store, so their roles ride along);
        the replicated override pulls subprocess replicas' records
        and maps them through the per-link offsets."""
        tl = obs.SPANS.timeline(int(flush_id))
        sides = {} if (not tl or tl.get("miss")) else \
            {r: s for r, s in tl.items() if r != "flush_id"}
        out = obs.align_timeline(int(flush_id), sides, {},
                                 self._fleet_self_label())
        if tl and tl.get("miss"):
            out["miss"] = tl["miss"]
        return out

    def set_tenant_label(self, ens: int, label: Any) -> None:
        """Name a row for per-tenant attribution (dynamic rows are
        already labeled by their create_ensemble name)."""
        self._tenant_labels[int(ens)] = label

    def tenant_label(self, ens: int) -> str:
        lbl = self._tenant_labels.get(ens)
        if lbl is None:
            lbl = self._row_name.get(ens)
        return str(lbl) if lbl is not None else f"ens{ens}"

    def _drop_tenant_series(self, row: int) -> None:
        """Drop ``row``'s labeled registry series on recycle — unless
        another row still serves under the same label.  A tenant
        spanning several ensemble rows is ONE tenant in every export
        (``_tenant_groups``), so recycling one of its rows must not
        reset the survivors' live counters."""
        lbl = self.tenant_label(row)
        for e in set(self._tenant_labels) | set(self._row_name):
            if e != row and 0 <= e < self.n_ens \
                    and self.tenant_label(e) == lbl:
                return
        self.obs_registry.remove_labeled(lbl)

    def _tenant_groups(self, top: int = 16
                       ) -> "List[Tuple[str, List[int]]]":
        """Label -> rows worth exporting: every NAMED tenant plus the
        top-N rows by op count, capped at 64 LABELS ranked by ops (a
        10k-row service exports dozens of tenants, not 10k — and the
        cap keeps the noisy ones, not the lowest row indices).  Rows
        sharing a label group together: a tenant spanning several
        ensemble rows is ONE tenant in every export."""
        rows = set(self._tenant_labels) | set(self._row_name)
        if top and self.tenant_ops.any():
            hot = np.argsort(self.tenant_ops)[-top:]
            rows.update(int(e) for e in hot if self.tenant_ops[e] > 0)
        groups: Dict[str, List[int]] = {}
        for e in rows:
            if 0 <= e < self.n_ens:
                groups.setdefault(self.tenant_label(e), []).append(e)
        ranked = sorted(
            groups.items(),
            key=lambda kv: (-int(self.tenant_ops[kv[1]].sum()),
                            kv[0]))
        return [(lbl, sorted(rr)) for lbl, rr in ranked[:64]]

    def _tenant_pctl(self, rows: List[int], q: float) -> float:
        """Bucket-resolution quantile (ms) over a tenant's (possibly
        multi-row) op-latency histogram — obs.Histogram's estimator."""
        counts = self._tenant_lat[rows].sum(axis=0)
        return obs.registry.percentile_from_counts(
            counts.tolist(), self._lat_edges, q)

    def tenant_stats(self, top: int = 16) -> Dict[str, Dict[str, Any]]:
        """Per-tenant attribution snapshot: ops, committed rounds,
        put payload bytes, device-round share (fraction of this
        service's launches the tenant's rows were active in), and
        p50/p99 op latency — the noisy-neighbor evidence surface."""
        out: Dict[str, Dict[str, Any]] = {}
        launches = max(self._launches_total, 1)
        for lbl, rows in self._tenant_groups(top):
            out[lbl] = {
                "rows": rows,
                "ops": int(self.tenant_ops[rows].sum()),
                "commits": int(self.tenant_commits[rows].sum()),
                "put_bytes": int(self.tenant_bytes[rows].sum()),
                "device_rounds": int(self.tenant_rounds[rows].sum()),
                "device_round_share": round(
                    float(self.tenant_rounds[rows].sum()) / launches,
                    4),
                "p50_ms": round(self._tenant_pctl(rows, 0.5), 3),
                "p99_ms": round(self._tenant_pctl(rows, 0.99), 3),
            }
        return out

    def _obs_tenant_collect(self) -> Dict[str, Any]:
        groups = self._tenant_groups()
        launches = max(self._launches_total, 1)

        def fam(typ, help, per_group):
            return obs.registry.family(
                typ, help, {lbl: per_group(rows)
                            for lbl, rows in groups})

        return {
            "retpu_tenant_ops_total": fam(
                "counter", "keyed + fast-read ops per tenant",
                lambda rr: int(self.tenant_ops[rr].sum())),
            "retpu_tenant_commits_total": fam(
                "counter", "committed device rounds per tenant",
                lambda rr: int(self.tenant_commits[rr].sum())),
            "retpu_tenant_put_bytes_total": fam(
                "counter", "put payload bytes per tenant",
                lambda rr: int(self.tenant_bytes[rr].sum())),
            "retpu_tenant_device_rounds_total": fam(
                "counter", "launches the tenant's rows were active in",
                lambda rr: int(self.tenant_rounds[rr].sum())),
            "retpu_tenant_device_round_share": fam(
                "gauge", "fraction of this service's launches",
                lambda rr: round(
                    float(self.tenant_rounds[rr].sum()) / launches,
                    4)),
            "retpu_tenant_op_p50_ms": fam(
                "gauge", "tenant op latency p50 (each entry charged "
                "its measured submit-to-ack time, per-op SLO ring)",
                lambda rr: round(self._tenant_pctl(rr, 0.5), 3)),
            "retpu_tenant_op_p99_ms": fam(
                "gauge", "tenant op latency p99 (each entry charged "
                "its measured submit-to-ack time, per-op SLO ring)",
                lambda rr: round(self._tenant_pctl(rr, 0.99), 3)),
        }

    def _obs_account_taken(self, taken, committed,
                           t_settle: Optional[float] = None,
                           rec: Optional[Dict[str, float]] = None,
                           fid: int = 0,
                           t_join: float = 0.0,
                           ent_meta=None) -> None:
        """Per-tenant + per-op attribution for one resolved flush:
        ONE pass over the taken entries (C-level attrgetter per
        entry) feeding vectorized folds — O(|entries|) appends, not
        per-op Python dicts.

        The per-op SLO ring records each entry's REAL client-
        perceived submit→ack latency (an entry's ops share its
        stamps — batch granularity within an entry, entry granularity
        within the flush; the join/settle/ack times are the flush's,
        shared); the fold targets are the per-kind
        ``retpu_op_latency_ms`` histogram, the per-tenant ``[E, B]``
        plane, and the span store (the flush's slowest entry attaches
        under ``slow_ops`` with its stage split, so
        ``obs.timeline(fid)`` resolves a tail op to queue wait vs
        flush vs ack).  Leased fast reads contribute their own
        samples from the hit hook.  ``t_settle`` is when the flush's
        outcome was known (on a replicated leader: AFTER the host
        quorum — ack stamps land after quorum settle by
        construction); ``rec`` is the launch's latency record,
        consulted for the slow entry's dominating flush mark."""
        now = time.perf_counter()
        rows: List[int] = [e for e, _ops in taken]
        if not rows:
            return
        if ent_meta is not None:
            # stamps sourced from the ENQUEUE-time pending slab (the
            # slab path collects the per-entry columns while the
            # flush walk builds its op lanes) — the settle fold never
            # re-walks entries whose futures are completion-slab rows
            kk_l, enss, nn_l, ts_l, te_l = ent_meta
        else:
            cols: List[Tuple] = []  # (kind, n, t_sub, t_enq)/entry
            enss = []
            fields = _OP_SLO_FIELDS
            for e, ops in taken:
                cols.extend(map(fields, ops))
                enss.extend([e] * len(ops))
            if cols:
                kk_l, nn_l, ts_l, te_l = zip(*cols)
            else:
                kk_l = nn_l = ts_l = te_l = ()
        rr = np.asarray(rows, np.int64)
        if committed is not None:
            np.add.at(self.tenant_commits, rr,
                      committed[:, rr].sum(axis=0).astype(np.int64))
        if not enss:
            return
        w = np.asarray(nn_l, np.int64)
        ee = np.asarray(enss, np.int64)
        np.add.at(self.tenant_ops, ee, w)
        if self._slo is None:
            return
        folded = self._slo.record_flush(
            kk_l, enss, nn_l, ts_l, te_l, fid,
            t_join if t_join else (t_settle or now),
            t_settle if t_settle else now, now)
        if folded is None:
            return
        _phys, lat_ms = folded
        bidx = np.searchsorted(self._lat_edges, lat_ms)
        # per-tenant: each entry's ops charged the entry's own
        # client-perceived latency (replacing PR 6's flush-oldest
        # upper bound with the measured per-entry value)
        np.add.at(self._tenant_lat, (ee, bidx), w)
        # per-kind registry histogram: fold bucket counts per kind
        # present in this flush (<= 5 kinds, B buckets — bounded)
        kk = np.asarray(kk_l, np.int16)
        nb = len(self._lat_edges) + 1
        for kcode in np.unique(kk):
            sel = kk == kcode
            child = self._h_op.labels(
                obs.opslo.KIND_NAMES[int(kcode)])
            counts = np.bincount(bidx[sel], weights=w[sel],
                                 minlength=nb)
            ccounts = child.counts
            for bi in np.nonzero(counts)[0]:
                ccounts[bi] += int(counts[bi])
            child.count += int(w[sel].sum())
            child.sum += float((lat_ms[sel] * w[sel]).sum())
        # tail attachment: the flush's slowest entry joins the span
        # record under its flush_id, with the launch's dominating
        # mark riding along when the record is at hand.  Built from
        # THIS call's locals, never from the ring row — a flush wider
        # than the ring capacity recycles physical rows within one
        # record_flush, and reading the row back would attach a
        # different entry's identity to the tail sample.
        i = int(np.argmax(lat_ms))
        t_sub_i = ts_l[i] if ts_l[i] > 0.0 else te_l[i]
        tj = t_join if t_join else (t_settle or now)
        tst = t_settle if t_settle else now
        slow = {
            "kind": obs.opslo.KIND_NAMES[int(kk_l[i])],
            "ens": int(enss[i]),
            "n": int(nn_l[i]),
            "flush_id": int(fid),
            "ms": round(max(0.0, now - t_sub_i) * 1e3, 3),
            "stages_ms": {
                "assign": round(max(0.0, te_l[i] - t_sub_i) * 1e3, 3),
                "queue_wait": round(max(0.0, tj - te_l[i]) * 1e3, 3),
                "flush": round(max(0.0, tst - tj) * 1e3, 3),
                "ack": round(max(0.0, now - tst) * 1e3, 3),
            },
        }
        if rec is not None:
            marks = {c: v for c, v in rec.items()
                     if isinstance(v, (int, float))
                     and c not in obs.flightrec.META_FIELDS}
            if marks:
                slow["flush_mark"] = max(marks, key=marks.get)
        if fid:
            obs.SPANS.record(fid, "leader", [], slow_ops=[slow])

    def _obs_note_put_bytes(self, ens: int, handles) -> None:
        """Attribute queued put payload bytes to the row's tenant
        (handles may include 0 = tombstone and -1-style sentinels —
        both length-less)."""
        values = self.values
        total = 0
        for h in handles:
            if h and h > 0:
                v = values.get(h)
                try:
                    total += len(v)
                except TypeError:
                    pass
        if total:
            self.tenant_bytes[ens] += total

    def _obs_flush_settled(self, fl: _InFlightLaunch) -> None:
        """Feed one settled launch into the obs plane: the flush
        histogram, the leader span record (joined with replica spans
        by flush_id), and the flight-recorder ring + anomaly
        trigger."""
        rec = fl.rec
        total = rec.get("total", 0.0)
        self._h_flush.record(total * 1e3)
        # (re-)attach the dump extras provider: tests replace the
        # recorder to lower its trigger thresholds, and the per-op
        # tail + compile-event sections must survive that
        self.flight.extras = self._flight_extras
        obs.SPANS.record(
            fl.flush_id, "leader",
            # META_FIELDS (incl. the derived 'enqueue' = h2d +
            # dispatch) are identity/derived, not spans — including
            # them would double-count a summed timeline
            [(c, v) for c, v in rec.items()
             if c not in obs.flightrec.META_FIELDS],
            k=fl.k, a_width=fl.a_width, total_s=total,
            payload_bytes=fl.payload_nbytes,
            # the fleet-timeline alignment anchor: this role's spans
            # lay out sequentially ENDING here (record time on THIS
            # process's monotonic clock — the clock the per-link
            # offset estimates map between)
            t_mono=time.monotonic())
        self.flight.record({
            "flush_id": fl.flush_id, "t": time.time(),
            "k": fl.k, "a_width": fl.a_width,
            "payload_bytes": fl.payload_nbytes,
            "queued_rounds": sum(self._queue_rounds[e]
                                 for e in self._active),
            "in_flight": len(self._inflight_launches),
            **rec})
        if self._autotune:
            # the runtime controller's cadence: one counted flush,
            # one integer compare; evaluations run every
            # RETPU_AUTOTUNE_CADENCE settled flushes (§14).  Inside
            # the obs-gated settle hook on purpose: the controller
            # CONSUMES the obs plane, so RETPU_OBS=0 starves it too.
            self.controller.tick(fl.flush_id)

    # -- (K, A)-grid pre-compile --------------------------------------------

    def _a_ladder(self) -> List[Optional[int]]:
        """Active-column bucket widths the launch path can pack at:
        full width (None) plus, with compaction on, the pow2 ladder
        from A_BUCKET_MIN strictly below E.  Shard-wise mesh engines
        bucket PER SHARD, so their ladder runs strictly below the
        LOCAL width E/n_shards instead."""
        ladder: List[Optional[int]] = [None]
        if self._compact:
            top = self.n_ens
            if self._mesh_shards:
                top = self.n_ens // self._mesh_shards
            b = A_BUCKET_MIN
            while b < top:
                ladder.append(b)
                b <<= 1
        return ladder

    def warmup(self, buckets=None, capture_costs=None) -> None:
        """Pre-compile the launch path's XLA programs on a THROWAWAY
        state (never the live one: a warmup launch that mutated
        ``self.state`` outside the real op stream would corrupt it —
        and on a replication-group replica, diverge it from its
        group).

        Flush depths are pow2-bucketed and the packed-result program
        is additionally keyed by the active-column bucket AND the
        static want_vsn flag, so the grid is (K, A) × {vsn, no-vsn}:
        K in {0, 1, 2, ..., max_k} × A in the pow2 ladder below E
        plus full width.  The small-K buckets double as the get-only
        / read-miss flush shapes the read fast path's fallback
        produces, and the version-less packs are what execute /
        execute_async dispatch — all pre-compiled here so none of
        them pays a first-use compile inside a client's latency
        window.  Without this, the first
        flush at each new (K, A) bucket pays its compile in the
        middle of serving — the dispatch p99 blip the steady-state
        breakdown can't show.  The pack programs warm on the step's
        REAL outputs so mesh-sharded result placements compile the
        executables the live flush dispatches.

        ``buckets``: optional iterable of ``(k, a_width)`` pairs
        (a_width None = full width) restricting the PACK grid — the
        step ladder always warms in full.  bench.py and svcnode share
        the default full grid.

        ``capture_costs``: XLA cost-analysis gauges per warmed step
        bucket (``retpu_step_cost_flops``/``_bytes``, labeled by
        bucket; engine.lowered_cost_analysis — an extra lowering per
        bucket, ~0.5 s each).  None (default) captures only the
        deepest full-width bucket so routine warmups stay cheap;
        True captures every bucket (svcnode ``--warm`` boots do);
        False skips capture.  Compile events recorded during warmup
        land under ``phase="warmup"`` either way.
        """
        self._in_warmup = True
        try:
            self._warmup(buckets, capture_costs)
        finally:
            self._in_warmup = False

    def _warmup(self, buckets, capture_costs) -> None:
        jnp = self._jnp
        e, m, s = self.n_ens, self.n_peers, self.n_slots
        pack = self._pack
        by_k: Optional[Dict[int, List[Optional[int]]]] = None
        if buckets is not None:
            by_k = {}
            for kb, aw in buckets:
                by_k.setdefault(int(kb), []).append(aw)

        def a_widths(k_eff: int) -> List[Optional[int]]:
            if k_eff == 0:
                return [None]  # no per-round planes to compact
            if by_k is not None:
                return by_k.get(k_eff, [])
            return self._a_ladder()

        # Warm the programs the launch path actually dispatches — with
        # donation on, the donated executables (donation changes the
        # compiled program's aliasing, so the plain warm wouldn't cover
        # it).  The throwaway state is THREADED through the calls: a
        # donated call consumes its input state.  Per (K, A) bucket
        # the launch dispatches EITHER the sliced step (A <= E/4 on a
        # sliced-capable engine: step + plain pack at A-width) OR the
        # full-grid step with the gathering pack — warm exactly that.
        step, step_wide, step_sliced, step_wide_sliced = \
            self._step_fns()
        st = self.engine.init_state(e, m, s)
        elect = jnp.zeros((e,), bool)
        cand = jnp.zeros((e,), jnp.int32)
        up = jnp.ones((e, m), bool)

        def warm_bucket(k_eff: int, aw: int, wide_gw=None):
            """One (K, A) bucket: the sliced program when the launch
            path would slice there, else the pack-gather program on
            the full-grid result already computed by the caller."""
            nonlocal st
            use_sliced = ((step_wide_sliced if wide_gw else
                           step_sliced) is not None
                          and e >= SLICE_MIN_E and aw * 4 <= e)
            if not use_sliced:
                return False
            # all-pad index vector: gathers clip harmlessly, the
            # scatter drops everything — state untouched, program
            # compiled
            aidx = jnp.full((aw,), e, jnp.int32)
            el = jnp.zeros((aw,), bool)
            cd = jnp.zeros((aw,), jnp.int32)
            if wide_gw:
                g, w = wide_gw
                kind_a = jnp.zeros((g, aw, w), jnp.int32)
                lease_a = jnp.zeros((g, aw, w), bool)
                st, won, res = step_wide_sliced(
                    st, aidx, el, cd, kind_a, kind_a, kind_a,
                    lease_a, up, exp_epoch=kind_a, exp_seq=kind_a)
                res = _wide_to_packed_layout(res, g, w, aw)
            else:
                kind_a = jnp.zeros((k_eff, aw), jnp.int32)
                lease_a = jnp.zeros((k_eff, aw), bool)
                st, won, res = step_sliced(
                    st, aidx, el, cd, kind_a, kind_a, kind_a,
                    lease_a, up, exp_epoch=kind_a, exp_seq=kind_a)
                if self._obs and capture_costs:
                    ca = eng.lowered_cost_analysis(
                        step_sliced, st, aidx, el, cd, kind_a, kind_a,
                        kind_a, lease_a, up, exp_epoch=kind_a,
                        exp_seq=kind_a)
                    if ca:
                        self._step_costs[f"k{k_eff}_a{aw}"] = ca
            np.asarray(pack(won, res, True))
            return True

        def warm_pack(won, res, k_eff: int, wide_gw=None) -> None:
            # The flush path (the read fast path's get-only/read-miss
            # fallback batches included) always packs WITH versions —
            # the (K, A) ladder covers those.  The version-less pack
            # is what WAL-less execute/execute_async dispatch, and
            # those skip compaction for device-resident planes — so
            # warming it at FULL WIDTH per K bucket covers the real
            # dispatch without doubling the whole warm grid (its
            # first-use compile was still leaking into the dispatch
            # p99 latency window).
            for aw in a_widths(k_eff):
                if aw is None:
                    np.asarray(pack(won, res, True))
                    np.asarray(pack(won, res, False))
                elif not warm_bucket(k_eff, aw, wide_gw):
                    if self._mesh_shards:
                        # shard-wise: [n_shards, A_loc] local pad-0
                        # index matrix (the live flush's operand form)
                        aidx = self._shard_aidx(np.zeros(
                            (self._mesh_shards, aw), np.int32))
                    else:
                        aidx = jnp.zeros((aw,), jnp.int32)
                    np.asarray(pack(won, res, True, active_idx=aidx))

        k = 0
        while True:
            kind = jnp.zeros((k, e), jnp.int32)
            lease = jnp.zeros((k, e), bool)
            st, won, res = step(
                st, elect, cand, kind, kind, kind, lease, up,
                exp_epoch=kind, exp_seq=kind)
            # per-bucket XLA cost gauges: always the deepest bucket
            # (one extra lowering); every bucket when asked
            if (self._obs and capture_costs is not False
                    and (capture_costs or k >= self.max_k)):
                ca = eng.lowered_cost_analysis(
                    step, st, elect, cand, kind, kind, kind, lease,
                    up, exp_epoch=kind, exp_seq=kind)
                if ca:
                    self._step_costs[f"k{k}"] = ca
            warm_pack(won, res, k)
            if k >= self.max_k:
                break
            k = 1 if k == 0 else k * 2
        if self._wide and step_wide is not None:
            # The wide gate admits plans with G in {1, 2} and pow2 W
            # up to _pow2_at_least(flush depth) — a non-pow2 max_k
            # still schedules into the NEXT pow2 width, so warm
            # through it.
            w_max = 1 << (max(self.max_k, 1) - 1).bit_length()
            for g in (1, 2):
                w = 1
                while w <= w_max:
                    kind = jnp.zeros((g, e, w), jnp.int32)
                    lease = jnp.zeros((g, e, w), bool)
                    st, won, res = step_wide(
                        st, elect, cand, kind, kind, kind, lease, up,
                        exp_epoch=kind, exp_seq=kind)
                    warm_pack(won,
                              _wide_to_packed_layout(res, g, w, e),
                              g * w, wide_gw=(g, w))
                    w *= 2

    def execute(self, kind: np.ndarray, slot: np.ndarray,
                val: np.ndarray,
                exp_epoch: Optional[np.ndarray] = None,
                exp_seq: Optional[np.ndarray] = None
                ) -> Tuple[np.ndarray, np.ndarray,
                           np.ndarray, np.ndarray]:
        """Bulk array API: run ``[K, E]`` op matrices through the
        service in one launch and return ``(committed, get_ok, found,
        value)`` as ``[K, E]`` arrays.

        This is the TPU-native client surface for array-shaped
        workloads: callers address slots directly and carry int32
        payloads inline on the device (no per-op Python objects, no
        host handle store) — the scalar kput/kget API remains for
        keyed/arbitrary-payload use.  Payload 0 is RESERVED as the
        tombstone (a put of 0 is a delete: it commits, and subsequent
        gets return found=False) — puts of live values must use
        1..2^31-1.  OP_RMW rows run the fused single-round
        read-modify-write: ``exp_epoch`` carries the mod-fun table
        code (funref.RMW_*), ``val`` the operand, and the committed
        COMPUTED value comes back in the value plane.  Same semantics
        as queued ops: elections fold in, leases check/renew,
        corruption triggers exchange.

        Callers may pass DEVICE-RESIDENT int32 arrays (jax.Array):
        the op planes then never cross the host↔device link (the
        tunnel link is the throughput ceiling), host-side payload
        validation is skipped (the encoding contract above is the
        caller's to honor), and ``ops_served`` counts every lane
        (k x E) since NOOP rows can't be counted without a transfer.

        Durability: with a ``data_dir``, host-array calls log their
        committed writes to the WAL before returning (the result IS
        the ack).  Device-resident calls are NOT WAL'd — fetching the
        op planes back would defeat the zero-transfer contract; their
        RPO is bounded by the checkpoint cadence instead (documented
        in ARCHITECTURE).
        """
        # A synchronous execute settles the launch pipeline first, so
        # results land in submission order behind any execute_async /
        # pipelined-flush work already in flight.
        self._drain_launches()
        if isinstance(kind, jax.Array):
            self._note_dev_exec_unlogged()
            k = int(kind.shape[0])
            committed, get_ok, found, value, _ = self._launch(
                kind, slot, val, k, want_vsn=False,
                exp_e=exp_epoch, exp_s=exp_seq)
            self.ops_served += k * self.n_ens
            return committed, get_ok, found, value
        kind = np.asarray(kind, np.int32)
        val = np.asarray(val, np.int32)
        if ((kind == eng.OP_PUT) & (val < 0)).any():
            raise ValueError("negative put payloads are not encodable "
                             "(int32 handles; 0 = tombstone/delete)")
        if (self._wal is not None
                and self._storage_degraded is not None
                and (((kind == eng.OP_PUT) | (kind == eng.OP_CAS)
                      | (kind == eng.OP_RMW))).any()):
            # read-only (§15): on this path the RESULT is the ack,
            # so a degraded service must refuse before the launch —
            # it cannot make the writes durable
            raise OSError(
                errno.EIO, "service is read-only (storage degraded): "
                "execute() writes cannot be made durable")
        k = int(kind.shape[0])
        slot = np.asarray(slot, np.int32)
        want_vsn = self._wal is not None
        committed, get_ok, found, value, vsn = self._launch(
            kind, slot, val, k, want_vsn=want_vsn,
            exp_e=None if exp_epoch is None
            else np.asarray(exp_epoch, np.int32),
            exp_s=None if exp_seq is None
            else np.asarray(exp_seq, np.int32))
        if self._wal is not None:
            self._log_execute_wal(kind, slot, val, committed, vsn,
                                  value)
        self.ops_served += int((np.asarray(kind) != eng.OP_NOOP).sum())
        return committed, get_ok, found, value

    def _note_dev_exec_unlogged(self) -> None:
        if self._wal is not None and not self._dev_exec_unlogged:
            # The durability contract weakens on this path (no WAL
            # record; RPO = checkpoint cadence) purely because of
            # the argument TYPE — make that observable once per
            # service instead of silent (ADVICE r3): a trace event
            # plus a stats() counter.
            self._dev_exec_unlogged = True
            self._emit("svc_execute_unlogged", {
                "reason": "device-resident op planes skip the WAL;"
                          " RPO is the checkpoint cadence"})

    def _log_execute_wal(self, kind, slot, val, committed, vsn,
                         value=None) -> None:
        """WAL records for a bulk execute's committed inline writes
        (shared by the sync path and the execute_async settle).  An
        RMW row logs the value it COMPUTED (the ``value`` result
        plane), not its operand."""
        wmask = (((kind == eng.OP_PUT) | (kind == eng.OP_CAS)
                  | (kind == eng.OP_RMW))
                 & committed)
        wval = (val if value is None
                else np.where(kind == eng.OP_RMW, value, val))
        js, es = np.nonzero(wmask)
        recs = [(("kv", int(e), int(slot[j, e])),
                 (None, int(wval[j, e]), int(vsn[j, e, 0]),
                  int(vsn[j, e, 1]), None, True))
                for j, e in zip(js.tolist(), es.tolist())]
        if recs:
            self._wal.log(recs + self._wal_extra_records())

    def execute_async(self, kind: np.ndarray, slot: np.ndarray,
                      val: np.ndarray,
                      exp_epoch: Optional[np.ndarray] = None,
                      exp_seq: Optional[np.ndarray] = None) -> Future:
        """Pipelined bulk array API: dispatch a ``[K, E]`` batch and
        return a :class:`Future` resolving to ``(committed, get_ok,
        found, value)`` (or ``'failed'`` on a failed launch / WAL
        error).  The enqueue half returns immediately; the resolve
        half (unpack, mirrors, WAL, corruption sweep) runs when a
        later call — or an idle :meth:`flush` — settles the launch,
        so up to ``pipeline_depth`` batches overlap: batch N's d2h
        transfer + host resolve ride under batch N+1's device step.
        Results resolve strictly in submission order.  Same
        device-resident vs host-array contract (payload encoding,
        WAL/RPO) as :meth:`execute`.
        """
        fut = Future()
        if isinstance(kind, jax.Array):
            self._note_dev_exec_unlogged()
            k = int(kind.shape[0])
            exec_wal = None
            want_vsn = False
            n_ops = k * self.n_ens
            exp_e = exp_epoch
            exp_s = exp_seq
        else:
            kind = np.asarray(kind, np.int32)
            val = np.asarray(val, np.int32)
            if ((kind == eng.OP_PUT) & (val < 0)).any():
                raise ValueError(
                    "negative put payloads are not encodable "
                    "(int32 handles; 0 = tombstone/delete)")
            k = int(kind.shape[0])
            slot = np.asarray(slot, np.int32)
            want_vsn = self._wal is not None
            exec_wal = (kind, slot, val) if want_vsn else None
            n_ops = int((kind != eng.OP_NOOP).sum())
            exp_e = (None if exp_epoch is None
                     else np.asarray(exp_epoch, np.int32))
            exp_s = (None if exp_seq is None
                     else np.asarray(exp_seq, np.int32))
        # Same election-mirror discipline as the pipelined flush: an
        # in-flight launch may be about to install a leader; electing
        # again would re-version its objects.
        elect, cand = self._election_inputs()
        if elect.any() and self._inflight_launches:
            self._drain_launches()
            elect, cand = self._election_inputs()
        try:
            fl = self._launch_enqueue(kind, slot, val, k,
                                      want_vsn=want_vsn, exp_e=exp_e,
                                      exp_s=exp_s, elect=elect,
                                      cand=cand)
        except BaseException:
            self._safe_resolve(fut, "failed")
            raise
        fl.exec_fut = fut
        fl.exec_wal = exec_wal
        fl.exec_ops = n_ops
        self._inflight_launches.append(fl)
        self._drain_launches(keep=self.pipeline_depth - 1)
        return fut

    def flush(self) -> int:
        """One device launch for everything queued; returns ops served
        (by launches SETTLED during this call).

        With ``pipeline_depth`` > 1 the launch is only ENQUEUED here:
        its packed result rides the d2h link and its host resolve
        (unpack → WAL → future fan-out) runs while a LATER flush's
        device step is already dispatched — up to ``pipeline_depth``
        launches deep.  The WAL-before-ack barrier and submission
        order are preserved (settles are strictly FIFO); only the ack
        point moves later in wall time.  A flush that empties the
        queues settles everything before returning, so flush-until-
        done callers observe resolved futures exactly as at depth 1.
        """
        self._flush_calls += 1
        self._run_due_retries()
        active = self._active
        caps = self._admission_caps
        admit: Optional[Dict[int, int]] = None
        if not caps:
            k = min(self.max_k,
                    max((self._queue_rounds[e] for e in active),
                        default=0))
        else:
            # tenant-guard flush admission (ARCHITECTURE §14): a
            # capped row contributes at most its token-bucket
            # allowance (refill = cap/flush, burst 2x) both to the
            # batch-depth choice and to the take loop below — a hot
            # tenant's queue stops forcing every flush to its own
            # max depth, which is exactly what the quiet tenants'
            # p99 was paying for
            tokens = self._admission_tokens
            admit = {}
            k = 0
            for e in active:
                qr = self._queue_rounds[e]
                cap = caps.get(e)
                if cap is not None:
                    t = min(tokens.get(e, float(cap)) + cap,
                            2.0 * cap)
                    tokens[e] = t
                    qr = min(qr, int(t))
                admit[e] = qr
                if qr > k:
                    k = qr
            k = min(self.max_k, k)
        served = 0
        if k == 0:
            # Idle flush: settle the launch pipeline first (callers
            # that flush until done must observe resolved futures),
            # then see whether an election-only launch is needed —
            # settles may have chained follow-up ops (kmodify CAS
            # halves), which get their own launch cycle here.
            served += self._drain_launches()
            served += self._chain_flush()
            if not self._election_inputs()[0].any():
                # tail settles count toward maintenance too (their
                # WAL records / flush count advanced just the same)
                self._flush_maintenance()
                return served
        # Bucket the batch depth to the next power of two (capped at
        # max_k): XLA compiles one program per distinct [K, E] shape,
        # so under skewed load a raw longest-queue K would trigger a
        # 20-40 s compile for every new depth seen.  Padding rounds
        # are NOOPs — microseconds of device math vs seconds of
        # compile churn; at most 1+log2(max_k) variants ever compile.
        if k:
            b = 1
            while b < k:
                b <<= 1
            k = min(b, self.max_k)

        t_pack0 = time.perf_counter()
        kind = np.zeros((k, self.n_ens), dtype=np.int32)
        slot = np.zeros((k, self.n_ens), dtype=np.int32)
        val = np.zeros((k, self.n_ens), dtype=np.int32)
        exp_e = np.zeros((k, self.n_ens), dtype=np.int32)
        exp_s = np.zeros((k, self.n_ens), dtype=np.int32)
        #: (ensemble, taken ops) pairs — ACTIVE ensembles only (the
        #: op matrices stay full-width [K, E]; only the host loops
        #: skip idle columns)
        taken: List[Tuple[int, List[Any]]] = []
        still_active = set()
        #: slab enqueue path (ARCHITECTURE §12b): instead of a numpy
        #: slice assignment per entry per plane, the walk collects
        #: the PENDING SLAB and the planes scatter from it in one
        #: pass — the C++ kernel's single traversal, or one
        #: numpy-expanded fancy assignment per plane as fallback.
        #: ``offs`` records each taken entry's first slab row
        #: (flattened taken order) — the completion-slab resolve
        #: indexes by it.
        use_slab = self._enq_slab
        #: pending-slab RUN DESCRIPTORS (one per taken entry: its
        #: ensemble column, first plane row, run length, uniform op
        #: kind) over concatenated per-op field lanes — what both
        #: native passes (pack, completion-slab gather) walk; the
        #: Python→C conversion cost scales with entries, not ops
        ent_col: List[int] = []
        ent_row0: List[int] = []
        ent_len: List[int] = []
        ent_kind: List[int] = []
        slot_l: List[int] = []
        val_l: List[int] = []
        expe_l: List[int] = []
        exps_l: List[int] = []
        offs: List[int] = []
        lane_n = 0
        #: per-entry SLO stamp columns (obs.opslo satellite): t_sub/
        #: t_enq collected HERE at enqueue time off the pending
        #: entries (kind/ens/weight are the run descriptors above) —
        #: the settle-side fold then sources stamps from the pending
        #: slab instead of re-walking the taken entries after their
        #: futures were replaced by completion-slab rows
        tsub_l: List[float] = []
        tenq_l: List[float] = []
        for e in sorted(active):
            q = self.queues[e]
            # limit == k on the uncapped path; a tenant-guard cap
            # lowers it to the row's admitted allowance
            limit = k if admit is None else min(k, admit.get(e, k))
            ops: List[Any] = []
            rounds = idx = 0
            while idx < len(q) and rounds < limit:
                op = q[idx]
                if rounds + op.n <= limit:
                    ops.append(op)
                    rounds += op.n
                    idx += 1
                else:
                    # K cap (or the row's admission limit) lands
                    # inside a batch: take the head rounds now; the
                    # tail (same Future/accumulator) leads the next
                    # flush.
                    head, tail = op.split(limit - rounds)
                    ops.append(head)
                    rounds = limit
                    q[idx] = tail
                    break
            self.queues[e] = q[idx:]
            self._queue_rounds[e] -= rounds
            if admit is not None and e in self._admission_tokens:
                self._admission_tokens[e] = max(
                    0.0, self._admission_tokens[e] - rounds)
            if self.queues[e]:
                still_active.add(e)
            if ops:
                taken.append((e, ops))
            j = 0
            if use_slab:
                # pending-slab build: C-level list appends/extends
                # only — per-entry numpy work is zero; one asarray
                # per column below converts the whole flush at once
                for op in ops:
                    n = op.n
                    offs.append(lane_n)
                    lane_n += n
                    ent_col.append(e)
                    ent_row0.append(j)
                    ent_len.append(n)
                    ent_kind.append(op.kind)
                    tsub_l.append(op.t_sub)
                    tenq_l.append(op.t_enq)
                    if isinstance(op, _PendingBatch):
                        slot_l.extend(op.slot)
                        val_l.extend(op.handle)
                        if op.exp_e is not None:
                            expe_l.extend(op.exp_e)
                            exps_l.extend(op.exp_s)
                        else:
                            z = [0] * n
                            expe_l.extend(z)
                            exps_l.extend(z)
                    else:
                        slot_l.append(op.slot)
                        val_l.append(op.handle)
                        expe_l.append(op.exp[0])
                        exps_l.append(op.exp[1])
                    j += n
            else:
                for op in ops:
                    if isinstance(op, _PendingBatch):
                        n = op.n
                        kind[j:j + n, e] = op.kind
                        slot[j:j + n, e] = op.slot
                        val[j:j + n, e] = op.handle
                        if op.exp_e is not None:
                            exp_e[j:j + n, e] = op.exp_e
                            exp_s[j:j + n, e] = op.exp_s
                        j += n
                    else:
                        kind[j, e] = op.kind
                        slot[j, e] = op.slot
                        val[j, e] = op.handle
                        exp_e[j, e], exp_s[j, e] = op.exp
                        j += 1

        lanes = None
        pack_mark = None
        if use_slab and lane_n:
            ec = np.asarray(ent_col, np.int32)
            er = np.asarray(ent_row0, np.int32)
            el = np.asarray(ent_len, np.int32)
            ek = np.asarray(ent_kind, np.int32)
            l_slot = np.asarray(slot_l, np.int32)
            l_val = np.asarray(val_l, np.int32)
            l_expe = np.asarray(expe_l, np.int32)
            l_exps = np.asarray(exps_l, np.int32)
            bounds = self._shard_bounds(len(ec))
            if self._native_enqueue is None:
                native_pack = False
            elif bounds is None:
                native_pack = self._native_enqueue.pack(
                    k, self.n_ens, ec, er, el, ek,
                    l_slot, l_val, l_expe, l_exps, kind,
                    slot, val, exp_e, exp_s)
            else:
                # sharded pending-slab build (ARCHITECTURE §16): each
                # chunk's descriptors write disjoint [K, E] cells
                # (rows [er, er+el) of their columns), so concurrent
                # packs into the shared planes never overlap; lanes
                # slice at the chunk's global offset because pack
                # consumes them in descriptor order
                def _pack_chunk(lo, hi):
                    s = offs[lo]
                    t = offs[hi] if hi < len(offs) else lane_n
                    return self._native_enqueue.pack(
                        k, self.n_ens, ec[lo:hi], er[lo:hi],
                        el[lo:hi], ek[lo:hi], l_slot[s:t],
                        l_val[s:t], l_expe[s:t], l_exps[s:t],
                        kind, slot, val, exp_e, exp_s)
                # a chunk falling back is harmless: the numpy rewrite
                # below re-fills EVERY cell with identical values
                native_pack = all(self._shard_map(_pack_chunk,
                                                  bounds))
                self.sharded_flushes += 1
            if not native_pack:
                rows, cols = _lane_indices(ec, er, el)
                kind[rows, cols] = np.repeat(ek, el)
                slot[rows, cols] = l_slot
                val[rows, cols] = l_val
                exp_e[rows, cols] = l_expe
                exp_s[rows, cols] = l_exps
            lanes = (ec, er, el, lane_n, offs,
                     (ent_kind, ent_col, ent_len, tsub_l, tenq_l)
                     if self._obs else None)
            if native_pack:
                self.native_enqueue_flushes += 1
                pack_mark = "enqueue_native"
            else:
                self.fallback_enqueue_flushes += 1
                pack_mark = "enqueue_fallback"
        pack_dt = time.perf_counter() - t_pack0

        self._active = still_active
        # Elections plan from the HOST MIRRORS, which in-flight
        # launches may still be about to update (a won election lands
        # at resolve) — settle first, or the same ensemble re-elects
        # and the epoch bump re-versions its objects on first read
        # (spurious CAS failures).  Elections are rare; the
        # steady-state pipelined path never takes this drain.
        elect, cand = self._election_inputs()
        if elect.any() and self._inflight_launches:
            served += self._drain_launches()
            elect, cand = self._election_inputs()
        try:
            fl = self._launch_enqueue(kind, slot, val, k,
                                      want_vsn=True, exp_e=exp_e,
                                      exp_s=exp_s, entries=taken,
                                      elect=elect, cand=cand)
        except BaseException:
            # A failed device launch (XLA error, OOM, dead backend)
            # must not orphan the taken ops: clients would block on
            # their futures forever.  Fail them all — the reference's
            # request_failed path (worker crash -> step_down,
            # peer.erl:1274-1275) — then let the error propagate to
            # whoever drives flush().  The enqueue already rolled the
            # device state back, so the next flush starts clean.
            for e, ops in taken:
                for op in ops:
                    self._fail_entry(e, op)
            raise
        fl.taken = taken
        fl.lanes = lanes
        if pack_mark is not None:
            # derived A/B mark (flightrec.DERIVED_MARKS — outside the
            # additive total; the wall time is already inside
            # queue_wait): the enqueue half's lane-build + plane-pack
            # share, attributed to whichever pack arm ran
            fl.rec[pack_mark] = pack_dt
        self._inflight_launches.append(fl)
        # Settle: everything when the queues drained (nothing queued
        # to overlap with), else down to depth-1 still in flight —
        # the window the NEXT flush's enqueue overlaps.
        keep = self.pipeline_depth - 1 if self._active else 0
        served += self._drain_launches(keep=keep)
        served += self._chain_flush()
        self._flush_maintenance()
        return served

    def _chain_flush(self) -> int:
        """Same-flush chaining (the kmodify round-halving): when a
        settle's resolutions enqueued follow-up ops — a host-path
        kmodify read's CAS half, or an immediate conflict retry — run
        ONE more bounded launch cycle inside the same flush() call, so
        the follow-up costs this flush instead of the next.  Depth is
        capped at 2 nested cycles: a chain may re-arm once (CAS →
        conflict → fresh read) per level, and the cap bounds rounds
        per flush call while the backoff queue carries the rest."""
        if not self._chain_kick:
            return 0
        self._chain_kick = False
        if not self._active or self._chain_depth >= 2:
            return 0
        self._chain_depth += 1
        try:
            return self.flush()
        finally:
            self._chain_depth -= 1

    def _flush_maintenance(self) -> None:
        """Post-settle upkeep shared by the normal and idle flush
        paths: WAL compaction past the record bound, and the periodic
        scrub against its flush-count watermark."""
        if (self._wal is not None and not self._in_save
                and self._storage_degraded is None
                and self._wal.count >= self.wal_compact_records):
            # degraded gate: a read-only service must never compact —
            # save() would write the same dead/full disk and the
            # OSError would crash the flush loop the degradation
            # exists to protect (reads must keep serving)
            # WAL grew past the compaction bound: fold it into a fresh
            # checkpoint (save() rotates the generation) — but OFF the
            # hot path.  save() is a full checkpoint (hundreds of ms);
            # running it synchronously inside a loaded flush billed
            # the pause to whatever client op was in flight (the
            # mixed-load p99 spike vs a ~20 ms p50).  Defer to an idle
            # flush — queues empty AND launch pipeline drained — and
            # fall back to in-line only past a hard 2x record bound,
            # so sustained load still bounds replay time.
            idle = not self._active and not self._inflight_launches
            if idle or self._wal.count >= 2 * self.wal_compact_records:
                self._compact_wal(idle)
        if (self.scrub_every_flushes
                and self.flushes - self._scrubbed_at_flush
                >= self.scrub_every_flushes):
            self.scrub()
        if (self._retry_at and not self._active
                and not self._inflight_launches):
            # A fully-idle flush with only backed-off kmodify retries
            # parked: there are no concurrent writers left to
            # de-collide from, so the backoff delay is pure latency —
            # and a driver using the `while any(svc.queues): flush()`
            # idiom would stop flushing with the futures unresolved.
            # Collapse the delays: fire every parked retry now, so
            # their reads re-enter the queues (and re-arm the driver's
            # loop condition) before this flush returns.
            parked, self._retry_at = self._retry_at, []
            for _at, _e, fut, thunk in parked:
                if not fut.done:
                    thunk()

    def _compact_wal(self, idle: bool) -> None:
        """Fold the WAL into a fresh checkpoint, timed and marked:
        an ``svc_compaction`` latency record (latency_breakdown) +
        trace event + stats() counters make the pause attributable
        instead of vanishing into some client op's p99."""
        records = self._wal.count
        t0 = time.perf_counter()
        self.save()
        dt = time.perf_counter() - t0
        self.wal_compactions += 1
        self.wal_compaction_ms_last = dt * 1e3
        self.wal_compaction_ms_total += dt * 1e3
        self.lat_records.append({"svc_compaction": dt})
        self._emit("svc_compaction",
                   {"ms": round(dt * 1e3, 3), "records": records,
                    "idle": idle})

    # -- launch pipeline (two-phase async service execution) ---------------

    def _drain_launches(self, keep: int = 0) -> int:
        """Settle in-flight launches oldest-first until at most
        ``keep`` remain; returns ops served.

        A DEVICE-side settle failure abandons every LATER in-flight
        launch too: the failed launch rolled the device state back to
        ITS pre-launch snapshot, and the later launches consumed the
        poisoned chain — their ops fail without touching state (their
        snapshots postdate the poison).  A WAL-append failure is
        different: the launch's device commits are REAL (its clients
        got 'failed' — the allowed unacked-commit outcome — but the
        device/host bookkeeping stands), so later launches keep
        settling normally (abandoning them would release handles and
        recycle slots the device still populates); after the drain,
        a fatal-disk errno (EIO/ENOSPC) degrades the service to
        read-only (§15) while any other disk error re-raises to the
        flush driver."""
        served = 0
        wal_err: Optional[BaseException] = None
        fatal_err: Optional[BaseException] = None
        while len(self._inflight_launches) > keep:
            fl = self._inflight_launches.popleft()
            try:
                n, err = self._settle_launch(fl)
                served += n
                if err is not None:
                    if wal_err is None:
                        wal_err = err
                    if isinstance(err, OSError):
                        self.wal_storage_errors += 1
                        # the fatal bad-disk signal may arrive on a
                        # LATER launch than the first (non-fatal)
                        # error of the drain — it must still win the
                        # degrade decision below, not be masked
                        if (fatal_err is None
                                and getattr(err, "errno", None)
                                in (errno.EIO, errno.ENOSPC)):
                            fatal_err = err
            except BaseException:
                while self._inflight_launches:
                    self._abandon_launch(self._inflight_launches.popleft())
                raise
        if fatal_err is not None:
            # a dead/full disk under the WAL: degrade to read-only
            # (journaled, observable) instead of crashing the
            # serving loop — ARCHITECTURE §15
            self._degrade_storage("wal", fatal_err)
        elif wal_err is not None:
            # other disk errors keep the historical
            # raise-to-driver contract
            raise wal_err
        return served

    def _degrade_storage(self, plane: str, exc: BaseException) -> None:
        """Flip the service read-only after a fatal storage error on
        the ack path (EIO/ENOSPC under the WAL): subsequent writes
        fail fast at enqueue, reads keep serving, and the decision is
        journaled — a trace event, the health()["storage"] section,
        and the retpu_recovery_* gauges (ARCHITECTURE §15).  Recovery
        is a restart: restore() replays the WAL onto a healthy disk.
        Idempotent; the first error wins the record."""
        if self._storage_degraded is not None:
            return
        code = getattr(exc, "errno", None)
        self._storage_degraded = {
            "plane": plane,
            "mode": "read_only",
            "errno": errno.errorcode.get(code, str(code)),
            "error": repr(exc)[:200],
            "at_flush": int(self.flushes),
        }
        # the read-only contract covers writes ALREADY QUEUED too:
        # left in place they would flush later, and if the disk
        # flickered back they would WAL-log and ack from a
        # "read_only" service — onto a log whose fate the degrade
        # already distrusts (review r15).  Fail them now through the
        # normal release/recycle path; reads stay queued.
        self._fail_queued_writes()
        # subclass hook next: a replicated leader demotes itself
        # through the group's step-down machinery (repgroup
        # override, which rewrites mode to "step_down") — the
        # journaled decision below must record what actually
        # happened, not the base default
        self._on_storage_degraded()
        self._emit("svc_storage_degraded",
                   dict(self._storage_degraded))

    def _fail_queued_writes(self) -> None:
        """Fail every queued write entry (scalar or batch), keeping
        queued reads — the enqueue half of flipping read-only."""
        for e in list(self._active):
            q = self.queues[e]
            drop = [op for op in q if op.kind != eng.OP_GET]
            if not drop:
                continue
            keep = [op for op in q if op.kind == eng.OP_GET]
            self.queues[e] = keep
            self._queue_rounds[e] = sum(op.n for op in keep)
            for op in drop:
                self._fail_entry(e, op)

    def _on_storage_degraded(self) -> None:
        """Subclass seam: called once when the storage plane
        degrades.  The base service has no leadership to shed beyond
        the per-row device ballots (reads stay served; the row
        leaders are device state, not a group role)."""

    def _abandon_launch(self, fl: _InFlightLaunch) -> None:
        """Fail a poisoned in-flight launch's clients (launch N < this
        one failed and rolled the device state back under it)."""
        if fl.exec_fut is not None:
            self._safe_resolve(fl.exec_fut, "failed")
        if fl.taken:
            for e, ops in fl.taken:
                for op in ops:
                    self._fail_entry(e, op)

    def _settle_launch(self, fl: _InFlightLaunch
                       ) -> Tuple[int, Optional[BaseException]]:
        """Resolve one in-flight launch end to end: block on its
        packed result, then WAL-log and fan out its futures (the
        durability barrier stands — WAL before any ack).  Returns
        (ops served, wal error or None) — a WAL failure is reported,
        not raised, so the drain can keep settling later launches
        whose device commits are independent of this one's disk
        error; the drain then degrades or re-raises per the errno
        (see :meth:`_drain_launches`)."""
        rec = fl.rec
        wait_key = ("inflight_wait" if self.pipeline_depth > 1
                    else "device_d2h")
        try:
            planes = self._launch_resolve(fl, wait_key=wait_key)
        except BaseException:
            self._abandon_launch(fl)
            raise
        if fl.exec_fut is not None:
            return self._settle_execute(fl, planes)
        taken = fl.taken or []
        # Durability barrier: committed writes reach the WAL (synced
        # per wal_sync) BEFORE any future resolves — the never-ack-
        # unpersisted-writes contract (basic_backend.erl:120-125).  If
        # the WAL write itself fails, the commits stand on device (the
        # bookkeeping proceeds) but their clients get 'failed' — an
        # unacked commit is an allowed linearizable outcome; a lost
        # acked one is not — and the drain either degrades the
        # service (fatal EIO/ENOSPC, §15) or re-raises to the flush
        # driver.
        wal_err: Optional[BaseException] = None
        # a degraded (read-only) service must not WAL-log or ack
        # in-flight writes either — if the disk flickered back the
        # append could succeed and ack from a service whose log tail
        # the degrade already distrusts (review r15); their reads
        # still serve (ack=False spares reads by design)
        degraded = self._storage_degraded is not None
        t_wal = time.perf_counter()
        if self._wal is not None and not degraded:
            try:
                self._log_wal(taken, planes, rec=rec)
            except Exception as exc:
                wal_err = exc
        t_res = time.perf_counter()
        served = self._resolve_flush(taken, planes,
                                     ack=wal_err is None
                                     and not degraded,
                                     op_planes=(fl.kind_np,
                                                fl.op_slot_np),
                                     rec=rec, fid=fl.flush_id,
                                     t_join=fl.t_join,
                                     lanes=fl.lanes)
        t_end = time.perf_counter()
        # Finish the breakdown the launch recorded: oldest-op queue
        # wait, WAL append+sync, per-future resolve.  Per-component
        # percentiles over these records are what makes a p99 target
        # analyzable (VERDICT r2 weak #2).
        oldest = min((op.t_enq for _e, ops in taken for op in ops
                      if op.t_enq), default=t_wal)
        rec["queue_wait"] = max(0.0, t_wal - oldest
                                - rec.get("total", 0.0))
        rec["wal"] = t_res - t_wal
        rec["resolve"] = t_end - t_res
        rec["total"] = sum(v for c, v in rec.items()
                           if c not in DERIVED_MARKS)
        if self._obs:
            self._obs_flush_settled(fl)
        return served, wal_err

    def _settle_execute(self, fl: _InFlightLaunch, planes
                        ) -> Tuple[int, Optional[BaseException]]:
        """Resolve one ``execute_async`` launch: WAL-log committed
        writes (host-array path; the resolution IS the ack), then
        resolve the client future with the result planes.  Same
        (served, wal error) reporting contract as
        :meth:`_settle_launch` — an unpersisted commit may never be
        acked (the future resolves 'failed'), but later launches'
        settles proceed."""
        committed, get_ok, found, value, vsn = planes
        if fl.exec_wal is not None and self._wal is not None:
            if self._storage_degraded is not None:
                # read-only: the commit may be real on device, but
                # no ack may ride a distrusted log (see
                # _settle_launch's degraded gate)
                self._safe_resolve(fl.exec_fut, "failed")
                return 0, None
            kind, slot, val = fl.exec_wal
            try:
                self._log_execute_wal(kind, slot, val, committed, vsn,
                                      value)
            except Exception as exc:
                self._safe_resolve(fl.exec_fut, "failed")
                return 0, exc
        t_res = time.perf_counter()
        self.ops_served += fl.exec_ops
        self._safe_resolve(fl.exec_fut,
                           (committed, get_ok, found, value))
        # the future fan-out is the execute path's whole resolve
        # stage; recording it here gives the pipelined bench loop's
        # latency_breakdown a `resolve` entry like the flush path's
        fl.rec["resolve"] = time.perf_counter() - t_res
        fl.rec["total"] = sum(v for c, v in fl.rec.items()
                              if c not in DERIVED_MARKS)
        if self._obs:
            self._obs_flush_settled(fl)
        return fl.exec_ops, None

    def _wal_extra_records(self) -> List[Tuple[Any, Any]]:
        """Records a subclass wants persisted in the SAME durability
        barrier as a flush's committed writes (one log() call = one
        sync) — the replication group rides its (epoch, seq) meta
        here so a leader restart can never mistake its own data-
        bearing position for an older one."""
        return []

    def _log_wal(self, taken, planes, rec=None) -> None:
        """Append this flush's committed client writes to the WAL
        (latest record per (ens, slot)); called BEFORE any future
        resolves.

        Native arm: batch-only flushes whose keys/payloads fit the
        kernel's pickle subset (str keys, bytes/None payloads) encode
        every record into one byte arena in a single C pass and the
        WAL appends it verbatim (:meth:`ServiceWAL.log_arena`) —
        byte-identical store contents to the Python path below, which
        remains the oracle and the fallback (scalar write ops, exotic
        key/payload types, RETPU_NATIVE_RESOLVE=0)."""
        committed, _get_ok, _found, value, vsn = planes
        if committed is None:
            return
        if (self._native_resolve is not None and vsn is not None
                and self._log_wal_native(taken, planes, rec)):
            return
        committed_l = committed.tolist()
        vsn_l = vsn.tolist()
        puts = (eng.OP_PUT, eng.OP_CAS)
        recs = []
        for e, ops in taken:
            j = -1
            for op in ops:
                if isinstance(op, _PendingBatch):
                    if op.kind in (eng.OP_PUT, eng.OP_CAS):
                        comm = committed[j + 1:j + 1 + op.n, e]
                        vs2 = vsn[j + 1:j + 1 + op.n, e]
                        for i in np.nonzero(comm)[0]:
                            h = int(op.handle[i])
                            recs.append((
                                ("kv", e, int(op.slot[i])),
                                (op.keys[i], h, int(vs2[i, 0]),
                                 int(vs2[i, 1]),
                                 self.values.get(h) if h else None,
                                 False)))
                    elif op.kind == eng.OP_RMW:
                        # keyed inline record: the committed COMPUTED
                        # value (result plane) rides the handle field
                        comm = committed[j + 1:j + 1 + op.n, e]
                        vs2 = vsn[j + 1:j + 1 + op.n, e]
                        vv = value[j + 1:j + 1 + op.n, e]
                        for i in np.nonzero(comm)[0]:
                            recs.append((
                                ("kv", e, int(op.slot[i])),
                                (op.keys[i], int(vv[i]),
                                 int(vs2[i, 0]), int(vs2[i, 1]),
                                 None, True)))
                    j += op.n
                    continue
                j += 1
                if op.kind in puts and committed_l[j][e]:
                    payload = (self.values.get(op.handle)
                               if op.handle else None)
                    ve, vs = vsn_l[j][e]
                    recs.append((("kv", e, op.slot),
                                 (op.key, op.handle, ve, vs, payload,
                                  False)))
                elif op.kind == eng.OP_RMW and committed_l[j][e]:
                    # direct ndarray index: RMW scalar ops are rare
                    # enough that a full value.tolist() per flush
                    # would tax the pure put/get WAL hot path
                    ve, vs = vsn_l[j][e]
                    recs.append((("kv", e, op.slot),
                                 (op.key, int(value[j, e]), ve, vs,
                                  None, True)))
        if recs:
            self._wal.log(recs + self._wal_extra_records())

    def _log_wal_native(self, taken, planes, rec=None) -> bool:
        """Single-pass WAL encode (docs/ARCHITECTURE.md §12): gather
        the flush's write lanes as flat arrays + joined key/payload
        arenas (bulk C-level string ops, no per-record pickle), hand
        them to the kernel, and append the returned byte arena
        verbatim.  Returns False when any lane is outside the native
        subset — the caller's Python path then logs EVERY record, so
        record order (latest-per-key within the flush) is preserved
        exactly."""
        committed, _get_ok, _found, value, vsn = planes
        t0 = time.perf_counter()
        lane_j: List[int] = []
        lane_e: List[int] = []
        lane_slot: List[int] = []
        lane_f2: List[int] = []
        lane_inl: List[int] = []
        keys: List[str] = []
        pays: List[Any] = []
        values = self.values
        for e, ops in taken:
            j = -1
            for op in ops:
                if not isinstance(op, _PendingBatch):
                    j += 1
                    if op.kind != eng.OP_GET:
                        # scalar write lanes interleave with batch
                        # records on the same (ens, slot): only the
                        # Python walk preserves that order
                        return False
                    continue
                if op.kind in (eng.OP_PUT, eng.OP_CAS, eng.OP_RMW):
                    ks = op.keys
                    if ks is None or not all(
                            type(kk) is str for kk in ks):
                        return False
                    if op.kind == eng.OP_RMW:
                        pays.extend([None] * op.n)
                        lane_f2.extend([0] * op.n)
                        lane_inl.extend([1] * op.n)
                    else:
                        for h in op.handle:
                            p = values.get(h) if h else None
                            if p is not None and type(p) is not bytes:
                                return False
                            pays.append(p)
                        lane_f2.extend(op.handle)
                        lane_inl.extend([0] * op.n)
                    keys.extend(ks)
                    lane_j.extend(range(j + 1, j + 1 + op.n))
                    lane_e.extend([e] * op.n)
                    lane_slot.extend(op.slot)
                j += op.n
        if not lane_j:
            return True  # read-only flush: nothing to log
        joined = "".join(keys)
        key_arena = joined.encode("utf-8")
        if len(key_arena) != len(joined):
            return False  # non-ascii keys: char lens != byte lens
        n = len(lane_j)
        key_len = np.fromiter(map(len, keys), np.int64, n)
        key_off = np.zeros((n,), np.int64)
        np.cumsum(key_len[:-1], out=key_off[1:])
        pay_len = np.fromiter(
            (-1 if p is None else len(p) for p in pays), np.int64, n)
        if int((key_len + np.maximum(pay_len, 0)).max()) >= 65500:
            # CPython's pickler frames in ~64 KiB units: once a
            # record's body reaches FRAME_SIZE_TARGET it splits
            # frames at opcode boundaries (and writes >= 64 KiB
            # str/bytes out-of-frame entirely).  The kernel emits ONE
            # frame per record body, so oversized records would
            # diverge from the oracle byte-for-byte — route the flush
            # to Python.  65500 = the target minus the record's
            # worst-case non-payload opcode overhead.
            return False
        pay_arena = b"".join(p for p in pays if p is not None)
        pay_off = np.zeros((n,), np.int64)
        np.cumsum(np.maximum(pay_len, 0)[:-1], out=pay_off[1:])
        out = self._native_resolve.wal_encode(
            self.n_ens, np.asarray(lane_j, np.int32),
            np.asarray(lane_e, np.int32),
            np.asarray(lane_slot, np.int32),
            np.asarray(lane_f2, np.int32),
            np.asarray(lane_inl, np.uint8),
            np.zeros((n,), np.uint8), key_off, key_len, key_arena,
            pay_off, pay_len, pay_arena, committed, value, vsn)
        if out is None:
            return False
        arena, idx = out
        idx = idx[idx[:, 1] > 0]  # drop uncommitted lanes
        if rec is not None:
            dt = time.perf_counter() - t0
            rec["resolve_native"] = rec.get("resolve_native",
                                            0.0) + dt
        if len(idx):
            self._wal.log_arena(arena, idx,
                                self._wal_extra_records())
        return True

    def _safe_resolve(self, fut: Future, result: Any) -> None:
        """Resolve a client future, containing waiter exceptions:
        ``Future.resolve`` runs waiters synchronously, and a client
        callback that raises must not abort the resolve loop — that
        would orphan every later op in the batch (and, on the failure
        path, mask the original device error)."""
        try:
            fut.resolve(result)
        except Exception:  # client bug, not ours: trace it with the
            import traceback  # traceback (KeyboardInterrupt/SystemExit
            self._emit("svc_waiter_error",  # propagate)
                       {"error": traceback.format_exc(limit=8)})

    def _fail_entry(self, e: int, op) -> None:
        """Fail one queue entry (scalar op or batch) — launch
        failures and ensemble destruction."""
        if isinstance(op, _PendingBatch):
            self._fail_batch(e, op)
        else:
            self._fail_op(e, op)

    def _fail_batch(self, e: int, op: _PendingBatch) -> None:
        if op.fut.done:
            return
        if op.kind in (eng.OP_PUT, eng.OP_CAS, eng.OP_RMW):
            for i in range(op.n):
                self._unnote_write(e, op.slot[i])
                if op.kind != eng.OP_RMW:
                    # an RMW entry's handle field is its int32
                    # operand, not a payload handle
                    self._release_handle(op.handle[i])
                    if op.handle[i]:
                        self._unnote_handle_write(e, op.slot[i])
                if op.keys is not None:
                    self._queue_recycle(e, (op.keys[i], op.slot[i],
                                            op.gen[i]))
        op.accum.fill(op.fut, op.pos, ["failed"] * op.n,
                      self._safe_resolve)

    def _fail_op(self, e: int, op: _PendingOp) -> None:
        """Resolve one queued op as failed, releasing a put's payload
        and queueing its slot for recycling (shared by the resolve
        loop's uncommitted branch and the launch-failure path)."""
        if op.fut.done:
            return
        if op.kind in (eng.OP_PUT, eng.OP_CAS):
            self._release_handle(op.handle)
            if op.handle:
                self._unnote_handle_write(e, op.slot)
        if op.kind in (eng.OP_PUT, eng.OP_CAS, eng.OP_RMW):
            self._unnote_write(e, op.slot)
            # A failed write that was the slot's last queued write may
            # leave it holding nothing committed (fresh slot, or a
            # tombstone whose delete-side recycle was skipped because
            # this write bumped the generation): queue it for
            # recycling or the slot leaks until the key is deleted.
            # (An RMW's handle field is its operand — nothing to
            # release; the recycle drain's committed-handle check
            # covers the -1 inline sentinel too.)
            if op.key is not None:
                self._queue_recycle(e, (op.key, op.slot, op.gen))
        self._safe_resolve(op.fut, "failed")

    def _resolve_batch(self, e: int, j: int, op: _PendingBatch,
                       planes, ack: bool, ack_reads: bool = True,
                       native_mirrors: bool = False) -> None:
        """Resolve one batch entry from result-plane column slices —
        the vectorized counterpart of the per-op resolve loop.  With
        ``native_mirrors`` the kernel already scattered this flush's
        ``_slot_vsn``/``_inline_value`` slab updates, so the loop
        keeps only the Python-owned bookkeeping (handles, recycles,
        the pending-write index, the storage-class set, the client
        results)."""
        committed, get_ok, found, value, vsn = planes
        n = op.n
        results: List[Any] = []
        append = results.append
        if op.kind in (eng.OP_PUT, eng.OP_CAS):
            comm_l = committed[j:j + n, e].tolist()
            vs_l = vsn[j:j + n, e].tolist()
            slot_l = op.slot
            handle_l = op.handle
            gen_l = op.gen
            keys = op.keys if op.keys is not None else [None] * n
            slot_handle = self.slot_handle[e]
            # direct append binding for the hot loop; one dirty mark
            # covers every recycle this batch queues
            recycle = self._recycle_pending[e].append
            self._recycle_dirty.add(e)
            release = self._release_handle
            inline = self._inline_slots[e]
            inline_row = self._inline_np[e]
            inline_val_np = self._inline_value_np[e]
            inline_val_ok = self._inline_value_ok[e]
            vsn_row = self._slot_vsn_np[e]
            vsn_ok_row = self._slot_vsn_ok[e]
            unnote_w = self._unnote_write
            for comm, s, h, g, key, vs in zip(comm_l, slot_l,
                                              handle_l, gen_l, keys,
                                              vs_l):
                unnote_w(e, s)
                if h:
                    self._unnote_handle_write(e, s)
                if not comm:
                    release(h)
                    if key is not None:
                        recycle((key, s, g))
                    append("failed")
                    continue
                old = slot_handle.pop(s, 0)
                if old != h:
                    release(old)
                if h:
                    slot_handle[s] = h
                inline.discard(s)
                inline_row[s] = False
                if not native_mirrors:
                    inline_val_ok[s] = False
                    vsn_row[s] = vs  # mirror before the ack
                    vsn_ok_row[s] = True
                append(("ok", tuple(vs)) if ack else "failed")
        elif op.kind == eng.OP_RMW:
            comm_l = committed[j:j + n, e].tolist()
            vs_l = vsn[j:j + n, e].tolist()
            val_l = value[j:j + n, e].tolist()
            slot_handle = self.slot_handle[e]
            inline = self._inline_slots[e]
            inline_row = self._inline_np[e]
            inline_val_np = self._inline_value_np[e]
            inline_val_ok = self._inline_value_ok[e]
            vsn_row = self._slot_vsn_np[e]
            vsn_ok_row = self._slot_vsn_ok[e]
            release = self._release_handle
            recycle = self._recycle_pending[e].append
            self._recycle_dirty.add(e)
            unnote_w = self._unnote_write
            keys = op.keys if op.keys is not None else [None] * n
            for comm, s, g, key, vs, v in zip(comm_l, op.slot, op.gen,
                                              keys, vs_l, val_l):
                unnote_w(e, s)
                if not comm:
                    if key is not None:
                        recycle((key, s, g))
                    append("failed")
                    continue
                old = slot_handle.pop(s, 0)
                if old > 0:
                    release(old)
                if v:  # live value; a computed 0 is the tombstone
                    slot_handle[s] = -1
                    if not native_mirrors:  # mirror before the ack
                        inline_val_np[s] = v
                        inline_val_ok[s] = True
                else:
                    if not native_mirrors:
                        inline_val_ok[s] = False
                    if key is not None:  # tombstone: recycle the slot
                        recycle((key, s, g))
                inline.add(s)
                inline_row[s] = True
                if not native_mirrors:
                    vsn_row[s] = vs
                    vsn_ok_row[s] = True
                append(("ok", tuple(vs)) if ack else "failed")
        else:  # OP_GET batch
            ok_l = get_ok[j:j + n, e].tolist()
            found_l = found[j:j + n, e].tolist()
            val_l = value[j:j + n, e].tolist()
            vs_l = (vsn[j:j + n, e].tolist() if vsn is not None
                    else [None] * n)
            values = self.values
            inline = self._inline_slots[e]
            inline_val_np = self._inline_value_np[e]
            inline_val_ok = self._inline_value_ok[e]
            vsn_row = self._slot_vsn_np[e]
            vsn_ok_row = self._slot_vsn_ok[e]
            want_vsn = op.want_vsn
            for ok, fnd, v, vs, s in zip(ok_l, found_l, val_l, vs_l,
                                         op.slot):
                if ok and ack_reads:
                    if fnd and v != 0:
                        if s in inline:
                            out = v
                            if not native_mirrors:  # refresh mirror
                                inline_val_np[s] = v
                                inline_val_ok[s] = True
                        else:
                            out = values.get(v, NOTFOUND)
                    else:
                        out = NOTFOUND
                    if vs is not None and not native_mirrors:
                        vsn_row[s] = vs  # refresh fast mirror
                        vsn_ok_row[s] = True
                    append(("ok", out, tuple(vs)) if want_vsn
                           else ("ok", out))
                else:
                    append("failed")
        op.accum.fill(op.fut, op.pos, results,
                      self._safe_resolve)

    # -- completion-slab resolve (slab enqueue path, ARCH §12) -------------

    def _resolve_taken_slab(self, taken, planes, lanes, ack: bool,
                            ack_reads: bool,
                            native_mirrors: bool) -> int:
        """Resolve every taken entry through the per-flush COMPLETION
        SLAB: each result plane gathers through the flush's op lanes
        ONCE (``[R]`` records, R = taken rounds — one fancy index per
        plane instead of per-op scalar reads or a full ``[K, E]``
        tolist), then the entries walk their row segments with
        vectorized bookkeeping.  Exactly one slab fill (WAKE) per
        flush — ``stats()["completion_slab"]`` counts it, and
        tests/test_native_enqueue.py pins the one-wake-per-flush
        claim.  Scalar ops resolve as thin views over their single
        slab row.  Results and mirror slabs are identical to the
        per-op oracle loops."""
        committed, get_ok, found, value, vsn = planes
        ent_col, ent_row0, ent_len, n_rows, offs = lanes[:5]
        got = lists = None
        bounds = (self._shard_bounds(len(ent_col))
                  if self._native_enqueue is not None else None)
        if bounds is not None:
            # sharded completion-slab gather (ARCHITECTURE §16):
            # chunk [lo, hi) covers global slab rows [offs[lo],
            # offs[hi]) — the native gather AND its bulk tolist run
            # per chunk on the pool (both drop the GIL in C); the
            # main thread concatenates in chunk order, so the lanes
            # and lists are element-identical to the serial gather
            cm_u8, gk_u8, fn_u8 = (_u8view(committed),
                                   _u8view(get_ok), _u8view(found))
            val_c = np.ascontiguousarray(value, np.int32)
            vsn_c = np.ascontiguousarray(vsn, np.int32)
            n_desc = len(ent_col)

            def _gather_chunk(lo, hi):
                s = offs[lo]
                t = offs[hi] if hi < n_desc else n_rows
                g = self._native_enqueue.gather(
                    len(committed), committed.shape[1],
                    ent_col[lo:hi], ent_row0[lo:hi], ent_len[lo:hi],
                    cm_u8, gk_u8, fn_u8, val_c, vsn_c, t - s)
                return (g, [a.tolist() for a in g]) \
                    if g is not None else None

            chunks = self._shard_map(_gather_chunk, bounds)
            if all(c is not None for c in chunks):
                got = tuple(np.concatenate([c[0][i] for c in chunks])
                            for i in range(5))
                lists = [sum((c[1][i] for c in chunks), [])
                         for i in range(5)]
        if got is None and self._native_enqueue is not None:
            got = self._native_enqueue.gather(
                len(committed), committed.shape[1], ent_col,
                ent_row0, ent_len, _u8view(committed),
                _u8view(get_ok), _u8view(found),
                np.ascontiguousarray(value, np.int32),
                np.ascontiguousarray(vsn, np.int32), n_rows)
        if got is None:
            rows, cols = _lane_indices(ent_col, ent_row0, ent_len)
            got = (committed[rows, cols], get_ok[rows, cols],
                   found[rows, cols], value[rows, cols],
                   vsn[rows, cols])
        ok_lane, gok_lane, fnd_lane, val_lane, vsn_lane = got
        # plane→Python conversion happens ONCE per flush per lane
        # (bulk C tolist); every entry below slices plain lists —
        # the per-entry numpy slice + tolist pairs of the oracle
        # loops are gone entirely
        if lists is None:
            lists = [ok_lane.tolist(), gok_lane.tolist(),
                     fnd_lane.tolist(), val_lane.tolist(),
                     vsn_lane.tolist()]
        ok_l, gok_l, fnd_l, val_l, vs_l = lists
        self.completion_wakes += 1
        self.completion_rows += n_rows
        served = 0
        ei = 0
        for e, ops in taken:
            for op in ops:
                off = offs[ei]
                ei += 1
                n = op.n
                end = off + n
                if isinstance(op, _PendingBatch):
                    self._resolve_batch_slab(
                        e, op, ok_l[off:end], gok_l[off:end],
                        fnd_l[off:end], val_l[off:end],
                        vs_l[off:end], ack, ack_reads,
                        native_mirrors,
                        (ok_lane, gok_lane, fnd_lane, val_lane,
                         vsn_lane, off))
                else:
                    self._resolve_scalar_slab(
                        e, op, ok_l[off], gok_l[off], fnd_l[off],
                        val_l[off], tuple(vs_l[off]), ack, ack_reads,
                        native_mirrors)
                served += n
        return served

    def _resolve_batch_slab(self, e: int, op: _PendingBatch, comm_l,
                            gok_l, fnd_l, val_l, vs_l, ack: bool,
                            ack_reads: bool, native_mirrors: bool,
                            np_lanes) -> None:
        """One batch entry from its completion-slab segment — the
        slab-path form of :meth:`_resolve_batch` (identical results
        and byte-identical mirror slabs).  The segments arrive as
        PLAIN LIST slices of the flush's once-converted lanes, so the
        loop body is dict/list work only; ``np_lanes`` is the
        ``(ok, gok, fnd, val, vsn, off)`` numpy lane reference, read
        ONLY on the fallback-resolve mirror path (native mirrors —
        the default — already scattered on the C side).  The
        storage-class set/slab flips run once per entry over the
        committed subset; per-index numpy calls lose to plain loops
        at the tens-of-ops entry sizes this path sees (measured)."""
        n = op.n
        results: List[Any] = []
        append = results.append
        comm_slots: List[int] = []
        if op.kind in (eng.OP_PUT, eng.OP_CAS):
            slot_l = op.slot
            handle_l = op.handle
            gen_l = op.gen
            keys = op.keys if op.keys is not None else [None] * n
            slot_handle = self.slot_handle[e]
            recycle = self._recycle_pending[e].append
            self._recycle_dirty.add(e)
            release = self._release_handle
            pw = self._pending_writes[e]
            qh = self._queued_handle_writes[e]
            for i, comm in enumerate(comm_l):
                h = handle_l[i]
                s = slot_l[i]
                # every op un-notes (committed or not), exactly like
                # the oracle loop — clamped at 0 like _unnote_write
                # (an unpaired un-note must park reads on the device
                # round, never underflow)
                if pw[s] > 0:
                    pw[s] -= 1
                if h and qh[s] > 0:
                    qh[s] -= 1
                if not comm:
                    release(h)
                    if keys[i] is not None:
                        recycle((keys[i], s, gen_l[i]))
                    append("failed")
                    continue
                old = slot_handle.pop(s, 0)
                if old != h:
                    release(old)
                if h:
                    slot_handle[s] = h
                comm_slots.append(s)
                append(("ok", tuple(vs_l[i])) if ack else "failed")
            if comm_slots:
                # committed writes flip their slots to handle class:
                # set + slab adopt the committed subset in bulk; the
                # vsn mirror scatters in ROUND order (duplicate
                # slots: numpy fancy assignment keeps the last write,
                # which is what the sequential loop committed last) —
                # mirror-before-ack holds, the accum fill below is
                # the first client-visible effect
                self._inline_slots[e].difference_update(comm_slots)
                self._inline_np[e, comm_slots] = False
                if not native_mirrors:
                    ok_a, _g, _f, _v, vsn_a, off = np_lanes
                    okm = ok_a[off:off + n]
                    self._inline_value_ok[e, comm_slots] = False
                    self._slot_vsn_np[e, comm_slots] = \
                        vsn_a[off:off + n][okm]
                    self._slot_vsn_ok[e, comm_slots] = True
        elif op.kind == eng.OP_RMW:
            slot_l = op.slot
            gen_l = op.gen
            keys = op.keys if op.keys is not None else [None] * n
            slot_handle = self.slot_handle[e]
            release = self._release_handle
            recycle = self._recycle_pending[e].append
            self._recycle_dirty.add(e)
            pw = self._pending_writes[e]
            for i, comm in enumerate(comm_l):
                s = slot_l[i]
                if pw[s] > 0:  # clamped, like _unnote_write
                    pw[s] -= 1
                if not comm:
                    if keys[i] is not None:
                        recycle((keys[i], s, gen_l[i]))
                    append("failed")
                    continue
                old = slot_handle.pop(s, 0)
                if old > 0:  # superseded host payload (-1 stays put)
                    release(old)
                if val_l[i]:  # live value; a computed 0 = tombstone
                    slot_handle[s] = -1
                else:
                    if keys[i] is not None:
                        recycle((keys[i], s, gen_l[i]))
                comm_slots.append(s)
                append(("ok", tuple(vs_l[i])) if ack else "failed")
            if comm_slots:
                self._inline_slots[e].update(comm_slots)
                self._inline_np[e, comm_slots] = True
                if not native_mirrors:
                    ok_a, _g, _f, val_a, vsn_a, off = np_lanes
                    okm = ok_a[off:off + n]
                    cvals = val_a[off:off + n][okm]
                    cvs = vsn_a[off:off + n][okm]
                    if len(set(comm_slots)) != len(comm_slots):
                        # duplicate slots in one RMW segment: live/
                        # tombstone interleavings are ROUND-ordered —
                        # only the sequential walk preserves which
                        # state the slot ends in
                        for s, v, vv in zip(comm_slots,
                                            cvals.tolist(),
                                            cvs.tolist()):
                            if v:
                                self._inline_value_np[e, s] = v
                                self._inline_value_ok[e, s] = True
                            else:
                                self._inline_value_ok[e, s] = False
                            self._slot_vsn_np[e, s] = vv
                            self._slot_vsn_ok[e, s] = True
                    else:
                        csl = np.asarray(comm_slots, np.int32)
                        live = cvals != 0
                        lsl = csl[live]
                        if lsl.size:
                            self._inline_value_np[e, lsl] = cvals[live]
                            self._inline_value_ok[e, lsl] = True
                        self._inline_value_ok[e, csl[~live]] = False
                        self._slot_vsn_np[e, csl] = cvs
                        self._slot_vsn_ok[e, csl] = True
        else:  # OP_GET segment
            want_vsn = op.want_vsn
            if not ack_reads:
                gok_l = [False] * n
            slot_l = op.slot
            inline = self._inline_slots[e]
            values = self.values
            served_slots: List[int] = []
            for i, okv in enumerate(gok_l):
                if not okv:
                    append("failed")
                    continue
                v = val_l[i]
                if fnd_l[i] and v != 0:
                    out = v if slot_l[i] in inline \
                        else values.get(v, NOTFOUND)
                else:
                    out = NOTFOUND
                served_slots.append(slot_l[i])
                append(("ok", out, tuple(vs_l[i])) if want_vsn
                       else ("ok", out))
            if not native_mirrors and served_slots:
                # served reads refresh the vsn mirror; reads of live
                # inline slots refresh the inline mirror (identical
                # values for a slot read twice in one segment — no
                # write can interleave inside one entry's round
                # range, so scatter order is moot)
                gok_a, fnd_a, val_a, vsn_a, off = (
                    np_lanes[1], np_lanes[2], np_lanes[3],
                    np_lanes[4], np_lanes[5])
                okm = gok_a[off:off + n]
                self._slot_vsn_np[e, served_slots] = \
                    vsn_a[off:off + n][okm]
                self._slot_vsn_ok[e, served_slots] = True
                sl_a = np.asarray(slot_l, np.intp)
                refr = okm & fnd_a[off:off + n] \
                    & (val_a[off:off + n] != 0) \
                    & self._inline_np[e, sl_a]
                if refr.any():
                    rsl = sl_a[refr]
                    self._inline_value_np[e, rsl] = \
                        val_a[off:off + n][refr]
                    self._inline_value_ok[e, rsl] = True
        op.accum.fill(op.fut, op.pos, results, self._safe_resolve)

    def _resolve_scalar_slab(self, e: int, op: _PendingOp, comm: bool,
                             gok: bool, fnd: bool, v: int, vs,
                             ack: bool, ack_reads: bool,
                             native_mirrors: bool) -> None:
        """One scalar op from its completion-slab row — the thin
        view-future resolve: the client's Future resolves from the
        gathered row alone, so a flush with scalar ops never converts
        the full ``[K, E]`` result planes to Python lists.  Logic is
        the per-op oracle loop's, verbatim."""
        slot_handle = self.slot_handle[e]
        if op.kind in (eng.OP_PUT, eng.OP_CAS):
            if comm:
                self._unnote_write(e, op.slot)
                if op.handle:
                    self._unnote_handle_write(e, op.slot)
                old = slot_handle.pop(op.slot, 0)
                if old != op.handle:
                    self._release_handle(old)
                if op.handle:
                    slot_handle[op.slot] = op.handle
                self._inline_slots[e].discard(op.slot)
                self._inline_np[e, op.slot] = False
                if not native_mirrors:
                    self._inline_value_ok[e, op.slot] = False
                    self._slot_vsn_np[e, op.slot] = vs
                    self._slot_vsn_ok[e, op.slot] = True
                self._safe_resolve(op.fut,
                                   ("ok", vs) if ack else "failed")
            else:
                self._fail_op(e, op)
        elif op.kind == eng.OP_RMW:
            if comm:
                self._unnote_write(e, op.slot)
                old = slot_handle.pop(op.slot, 0)
                if old > 0:
                    self._release_handle(old)
                if v:
                    slot_handle[op.slot] = -1
                    if not native_mirrors:
                        self._inline_value_np[e, op.slot] = v
                        self._inline_value_ok[e, op.slot] = True
                else:
                    if not native_mirrors:
                        self._inline_value_ok[e, op.slot] = False
                    if op.key is not None:
                        self._queue_recycle(e, (op.key, op.slot,
                                                op.gen))
                self._inline_slots[e].add(op.slot)
                self._inline_np[e, op.slot] = True
                if not native_mirrors:
                    self._slot_vsn_np[e, op.slot] = vs
                    self._slot_vsn_ok[e, op.slot] = True
                self._safe_resolve(op.fut,
                                   ("ok", vs) if ack else "failed")
            else:
                self._fail_op(e, op)
        else:  # OP_GET
            if gok and ack_reads:
                if fnd and v != 0:
                    if op.slot in self._inline_slots[e]:
                        out = v
                        if not native_mirrors:
                            self._inline_value_np[e, op.slot] = v
                            self._inline_value_ok[e, op.slot] = True
                    else:
                        out = self.values.get(v, NOTFOUND)
                else:
                    out = NOTFOUND
                if not native_mirrors:
                    self._slot_vsn_np[e, op.slot] = vs
                    self._slot_vsn_ok[e, op.slot] = True
                self._safe_resolve(
                    op.fut, ("ok", out, vs) if op.want_vsn
                    else ("ok", out))
            else:
                self._fail_op(e, op)

    def _resolve_flush(self, taken, planes, ack: bool = True,
                       ack_reads: bool = True, op_planes=None,
                       rec=None, fid: int = 0,
                       t_join: float = 0.0, lanes=None) -> int:
        """Resolve every taken op from the result planes.  With
        ``ack=False`` (the WAL write failed) committed writes keep
        their device-side bookkeeping — the commit is real — but
        resolve 'failed': an ack may never outrun the disk.  Reads
        don't need the disk, so they survive ``ack=False``;
        ``ack_reads=False`` fails them too — the replication group
        uses it when the HOST quorum was lost, where serving a read
        would mean a minority/deposed leader answering clients.

        ``op_planes`` is the launch's host (kind, slot) op-plane pair:
        when present and the native resolve kernel is loaded, one C
        pass scatters every committed mirror update
        (``_slot_vsn``/``_inline_value`` slabs, leased-GET refreshes)
        in the loop's exact per-column round order, and the per-op
        loops below skip their mirror writes — byte-identical slabs
        either way.

        ``lanes`` is the flush's pending-slab record from the slab
        enqueue path — ``(ent_col, ent_row0, ent_len, n_rows, offs,
        ent_meta)``: per-entry run descriptors (ensemble column,
        first plane row, run length), the taken round count, each
        entry's first slab row, and the SLO stamp columns (None with
        obs off).  When present (RETPU_NATIVE_ENQUEUE on), resolution
        runs through the per-flush COMPLETION SLAB — every result
        plane gathered through the runs in ONE pass, one wake per
        flush, per-entry vectorized bookkeeping — instead of the
        per-op loops below, with identical results and mirror slabs
        (tests/test_native_enqueue.py sweeps the equivalence)."""
        # per-op SLO settle stamp: the moment this flush's outcome is
        # known to the host.  On a replicated leader this method runs
        # AFTER the host-quorum decision (_settle_batch), so ack
        # stamps land after quorum settle by construction.
        t_settle = time.perf_counter() if self._obs else 0.0
        committed, get_ok, found, value, vsn = planes

        if committed is None:  # k == 0: election-only launch, no ops
            assert not taken, "ops taken but no result planes"
            self._drain_recycles()
            return 0
        native_mirrors = False
        if (self._native_resolve is not None and taken
                and op_planes is not None
                and op_planes[0] is not None
                and op_planes[1] is not None):
            t0 = time.perf_counter()
            n_cols = len(taken)
            cols = np.fromiter((e for e, _ops in taken), np.int32,
                               n_cols)
            kcounts = np.fromiter(
                (sum(op.n for op in ops) for _e, ops in taken),
                np.int32, n_cols)
            bounds = self._shard_bounds(n_cols)
            if bounds is None:
                native_mirrors = self._native_resolve.scatter_mirrors(
                    self.n_ens, self.n_slots, op_planes[0],
                    op_planes[1], committed, get_ok, found, value,
                    vsn, cols, kcounts, ack_reads,
                    (eng.OP_PUT, eng.OP_CAS, eng.OP_GET, eng.OP_RMW),
                    self._slot_vsn_np, self._slot_vsn_ok,
                    self._inline_value_np, self._inline_value_ok,
                    self._inline_np)
            else:
                # sharded mirror scatter (ARCHITECTURE §16): chunks
                # partition the taken COLUMNS, and every ensemble
                # column appears in `taken` at most once, so chunk
                # writes land on disjoint mirror rows; a chunk that
                # falls back just leaves its rows for the Python
                # mirror walk (state-identical either way)
                def _scatter_chunk(lo, hi):
                    return self._native_resolve.scatter_mirrors(
                        self.n_ens, self.n_slots, op_planes[0],
                        op_planes[1], committed, get_ok, found,
                        value, vsn, cols[lo:hi], kcounts[lo:hi],
                        ack_reads,
                        (eng.OP_PUT, eng.OP_CAS, eng.OP_GET,
                         eng.OP_RMW),
                        self._slot_vsn_np, self._slot_vsn_ok,
                        self._inline_value_np, self._inline_value_ok,
                        self._inline_np)
                native_mirrors = all(self._shard_map(_scatter_chunk,
                                                     bounds))
            if native_mirrors and rec is not None:
                dt = time.perf_counter() - t0
                rec["resolve_native"] = rec.get("resolve_native",
                                                0.0) + dt

        if lanes is not None and self._enq_slab and taken \
                and vsn is not None:
            # COMPLETION-SLAB path (ARCHITECTURE §12): the whole
            # flush's results gather through the op lanes — one
            # fancy index per plane, ONE wake — and entries resolve
            # from their slab row segments with vectorized
            # bookkeeping; the per-op loops below stay the oracle.
            served = self._resolve_taken_slab(taken, planes, lanes,
                                              ack, ack_reads,
                                              native_mirrors)
            self.ops_served += served
            if self._obs:
                self._obs_account_taken(taken, committed, t_settle,
                                        rec, fid, t_join,
                                        ent_meta=(lanes[5]
                                                  if len(lanes) > 5
                                                  else None))
            self._drain_recycles()
            return served

        # Per-op resolve loop: convert the result planes to plain
        # Python lists ONCE (C-speed bulk conversion) — per-op numpy
        # scalar indexing costs ~5x more than list indexing at
        # thousands of ops per flush.  Batch-only flushes (the keyed
        # vectorized surface) never touch the full planes per op, so
        # the conversion is LAZY: built only when a scalar op exists.
        committed_l = get_ok_l = found_l = value_l = vsn_l = None
        if any(not isinstance(op, _PendingBatch)
               for _e, ops in taken for op in ops):
            committed_l = committed.tolist()
            get_ok_l = get_ok.tolist()
            found_l = found.tolist()
            value_l = value.tolist()
            vsn_l = vsn.tolist()
        served = 0
        puts = (eng.OP_PUT, eng.OP_CAS)
        for e, ops in taken:
            slot_handle = self.slot_handle[e]
            j = -1
            for op in ops:
                if isinstance(op, _PendingBatch):
                    self._resolve_batch(e, j + 1, op, planes, ack,
                                        ack_reads, native_mirrors)
                    served += op.n
                    j += op.n
                    continue
                j += 1
                served += 1
                if op.kind in puts:
                    if committed_l[j][e]:
                        self._unnote_write(e, op.slot)
                        if op.handle:
                            self._unnote_handle_write(e, op.slot)
                        # Release the payload this write superseded
                        # (rounds resolve in device order, so the last
                        # committed handle per slot survives).
                        old = slot_handle.pop(op.slot, 0)
                        if old != op.handle:
                            self._release_handle(old)
                        if op.handle:
                            slot_handle[op.slot] = op.handle
                        # a committed put/CAS flips a device-native
                        # slot back to handle storage
                        self._inline_slots[e].discard(op.slot)
                        self._inline_np[e, op.slot] = False
                        # mirror-before-ack: a fast read issued after
                        # this future resolves must see the write
                        # (the native pass already scattered it)
                        if not native_mirrors:
                            self._inline_value_ok[e, op.slot] = False
                            self._slot_vsn_np[e, op.slot] = \
                                vsn_l[j][e]
                            self._slot_vsn_ok[e, op.slot] = True
                        self._safe_resolve(
                            op.fut, ("ok", tuple(vsn_l[j][e]))
                            if ack else "failed")
                    else:
                        self._fail_op(e, op)
                elif op.kind == eng.OP_RMW:
                    if committed_l[j][e]:
                        self._unnote_write(e, op.slot)
                        old = slot_handle.pop(op.slot, 0)
                        if old > 0:  # superseded host payload
                            self._release_handle(old)
                        # sentinel: LIVE value committed device-side
                        # (no host payload) — blocks recycling like a
                        # live handle, releases as a no-op.  A
                        # computed 0 is the tombstone: no sentinel,
                        # and the slot recycles like a committed
                        # delete (the host fallback's ksafe_delete
                        # arm recycles; the device arm must match).
                        if value_l[j][e]:
                            slot_handle[op.slot] = -1
                            if not native_mirrors:
                                self._inline_value_np[e, op.slot] = \
                                    value_l[j][e]
                                self._inline_value_ok[e, op.slot] = \
                                    True
                        else:
                            if not native_mirrors:
                                self._inline_value_ok[e, op.slot] = \
                                    False
                            if op.key is not None:
                                self._queue_recycle(
                                    e, (op.key, op.slot, op.gen))
                        self._inline_slots[e].add(op.slot)
                        self._inline_np[e, op.slot] = True
                        if not native_mirrors:
                            self._slot_vsn_np[e, op.slot] = \
                                vsn_l[j][e]
                            self._slot_vsn_ok[e, op.slot] = True
                        self._safe_resolve(
                            op.fut, ("ok", tuple(vsn_l[j][e]))
                            if ack else "failed")
                    else:
                        self._fail_op(e, op)
                else:
                    if get_ok_l[j][e] and ack_reads:
                        v = value_l[j][e]
                        if found_l[j][e] and v != 0:
                            # device-native slots carry the value
                            # itself, not a payload handle
                            if op.slot in self._inline_slots[e]:
                                out = v
                                # refresh the fast path's inline
                                # mirror from the device read
                                if not native_mirrors:
                                    self._inline_value_np[
                                        e, op.slot] = v
                                    self._inline_value_ok[
                                        e, op.slot] = True
                            else:
                                out = self.values.get(v, NOTFOUND)
                        else:
                            out = NOTFOUND
                        # vsn is the object's — a tombstone's real
                        # version rides along with NOTFOUND, so CAS
                        # chains (ksafe_delete → kupdate) work.  The
                        # device read also refreshes the fast path's
                        # vsn mirror (repopulating it after the
                        # post-election invalidation).
                        if not native_mirrors:
                            self._slot_vsn_np[e, op.slot] = \
                                vsn_l[j][e]
                            self._slot_vsn_ok[e, op.slot] = True
                        self._safe_resolve(
                            op.fut, ("ok", out, tuple(vsn_l[j][e]))
                            if op.want_vsn else ("ok", out))
                    else:
                        self._fail_op(e, op)
        self.ops_served += served
        if self._obs and taken:
            self._obs_account_taken(taken, committed, t_settle, rec,
                                    fid, t_join)
        self._drain_recycles()
        return served
