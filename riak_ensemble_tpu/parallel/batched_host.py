"""Host↔engine bridge: many ensembles served through the batched
device engine.

The scalar actor stack (:mod:`riak_ensemble_tpu.peer`) is the protocol
oracle and the fully-general path (dynamic membership, synctree,
gossip).  This module is the scale path the north star describes: a
host *service* that multiplexes thousands of engine-backed ensembles —

- client ops (kget/kput/kdelete) queue per ensemble and flush as one
  ``full_step`` launch per tick: ``[K, E]`` op matrices, one device
  dispatch for every queued op of every ensemble (the batched analog
  of E leader processes × worker pools);
- the host side keeps what consensus doesn't need on-device: the
  key→slot assignment per ensemble, the payload store (device arrays
  carry int32 handles; real bytes live host-side keyed by handle —
  engine.py's object-store contract), per-ensemble leases (monotonic
  clock), and the failure detector (an ``up`` mask per ensemble);
- leaderless or leader-down ensembles get an election folded into the
  SAME launch (``full_step``'s elect inputs) — the thundering-herd
  re-election after failures is one kernel call, not E timers.

Results come back to client futures after each flush (one d2h per
flush, amortized over every op in the batch).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from riak_ensemble_tpu.config import Config
from riak_ensemble_tpu.ops import engine as eng
from riak_ensemble_tpu.runtime import Future, Runtime, Timer
from riak_ensemble_tpu.types import NOTFOUND

_handles = itertools.count(1)


@dataclass
class _PendingOp:
    kind: int
    slot: int
    handle: int
    fut: Future


class BatchedEnsembleService:
    """N engine-backed ensembles behind a put/get API.

    ``n_slots`` bounds live keys per ensemble (slots are recycled when
    keys are deleted).  ``tick`` is the flush cadence: lower = lower
    latency, higher = bigger batches.
    """

    def __init__(self, runtime: Runtime, n_ens: int, n_peers: int,
                 n_slots: int = 128, tick: float = 0.005,
                 max_ops_per_tick: int = 64,
                 config: Optional[Config] = None) -> None:
        import jax.numpy as jnp

        self.runtime = runtime
        self.config = config if config is not None else Config()
        self.n_ens, self.n_peers, self.n_slots = n_ens, n_peers, n_slots
        self.tick = tick
        self.max_k = max_ops_per_tick
        self.state = eng.init_state(n_ens, n_peers, n_slots)
        #: host failure detector input (set_peer_up)
        self.up = np.ones((n_ens, n_peers), dtype=bool)
        #: per-ensemble key→slot and free slots
        self.key_slot: List[Dict[Any, int]] = [dict() for _ in range(n_ens)]
        self.free_slots: List[List[int]] = [
            list(range(n_slots)) for _ in range(n_ens)]
        #: payload store: handle -> value (device carries handles)
        self.values: Dict[int, Any] = {}
        self.queues: List[List[_PendingOp]] = [[] for _ in range(n_ens)]
        #: leader leases, host-side: ensemble -> expiry (runtime.now)
        self.lease_until = np.zeros((n_ens,), dtype=float)
        self.flushes = 0
        self.ops_served = 0
        self._timer: Optional[Timer] = None
        self._jnp = jnp
        self._schedule()

    # -- client API --------------------------------------------------------

    def kput(self, ens: int, key: Any, value: Any) -> Future:
        """Quorum-replicated write; resolves ('ok', handle_vsn) or
        'failed' (no slot / no quorum this flush)."""
        fut = Future()
        slot = self._slot_for(ens, key, allocate=True)
        if slot is None:
            fut.resolve("failed")
            return fut
        handle = next(_handles) & 0x7FFFFFFF
        self.values[handle] = value
        self.queues[ens].append(_PendingOp(eng.OP_PUT, slot, handle, fut))
        return fut

    def kget(self, ens: int, key: Any) -> Future:
        """Linearizable read; resolves ('ok', value|NOTFOUND) or
        'failed'."""
        fut = Future()
        slot = self._slot_for(ens, key, allocate=False)
        if slot is None:
            fut.resolve(("ok", NOTFOUND))
            return fut
        self.queues[ens].append(_PendingOp(eng.OP_GET, slot, 0, fut))
        return fut

    def kdelete(self, ens: int, key: Any) -> Future:
        """Tombstone write (slot recycled once committed)."""
        fut = Future()
        slot = self._slot_for(ens, key, allocate=False)
        if slot is None:
            fut.resolve(("ok", NOTFOUND))
            return fut
        handle = 0  # 0 = tombstone handle
        op = _PendingOp(eng.OP_PUT, slot, handle, fut)
        self.queues[ens].append(op)

        def recycle(result):
            if isinstance(result, tuple) and result[0] == "ok":
                self.key_slot[ens].pop(key, None)
                self.free_slots[ens].append(slot)
        fut.add_waiter(recycle)
        return fut

    def set_peer_up(self, ens: int, peer: int, up: bool) -> None:
        """Failure-detector input (the host's nodedown/suspend signal)."""
        self.up[ens, peer] = up

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    # -- internals ---------------------------------------------------------

    def _slot_for(self, ens: int, key: Any, allocate: bool) -> Optional[int]:
        slot = self.key_slot[ens].get(key)
        if slot is not None or not allocate:
            return slot
        if not self.free_slots[ens]:
            return None
        slot = self.free_slots[ens].pop()
        self.key_slot[ens][key] = slot
        return slot

    def _schedule(self) -> None:
        self._timer = self.runtime.schedule(self.tick, self._on_tick)

    def _on_tick(self) -> None:
        try:
            self.flush()
        finally:
            self._schedule()

    def _election_inputs(self) -> Tuple[np.ndarray, np.ndarray]:
        """Elect wherever there is no leader or the leader is down;
        candidate = lowest-index up member (the randomized-timeout
        winner in the reference; the host picks deterministically)."""
        leader = np.asarray(self.state.leader)
        leader_up = np.zeros((self.n_ens,), dtype=bool)
        has = leader >= 0
        leader_up[has] = self.up[np.nonzero(has)[0], leader[has]]
        member = np.asarray(self.state.view_mask).any(1)
        cand_ok = self.up & member
        any_up = cand_ok.any(1)
        cand = np.where(any_up, cand_ok.argmax(1), -1).astype(np.int32)
        elect = (~has | ~leader_up) & any_up
        return elect, cand

    def flush(self) -> int:
        """One device launch for everything queued; returns ops served."""
        jnp = self._jnp
        k = min(self.max_k, max((len(q) for q in self.queues), default=0))
        elect, cand = self._election_inputs()
        if k == 0 and not elect.any():
            return 0

        kind = np.zeros((k, self.n_ens), dtype=np.int32)
        slot = np.zeros((k, self.n_ens), dtype=np.int32)
        val = np.zeros((k, self.n_ens), dtype=np.int32)
        taken: List[List[_PendingOp]] = []
        now = self.runtime.now
        lease_ok = self.lease_until > now
        for e in range(self.n_ens):
            ops = self.queues[e][:k]
            self.queues[e] = self.queues[e][k:]
            taken.append(ops)
            for j, op in enumerate(ops):
                kind[j, e] = op.kind
                slot[j, e] = op.slot
                val[j, e] = op.handle

        state, won, res = eng.full_step(
            self.state, jnp.asarray(elect), jnp.asarray(cand),
            jnp.asarray(kind), jnp.asarray(slot), jnp.asarray(val),
            jnp.asarray(np.broadcast_to(lease_ok, (max(k, 1),
                                                   self.n_ens))[:k]
                        if k else np.zeros((0, self.n_ens), bool)),
            jnp.asarray(self.up))
        self.state = state

        # one d2h per flush
        won_np = np.asarray(won)
        committed = np.asarray(res.committed) if k else None
        get_ok = np.asarray(res.get_ok) if k else None
        found = np.asarray(res.found) if k else None
        value = np.asarray(res.value) if k else None
        vsn = np.asarray(res.obj_vsn) if k else None

        # a successful election (or any committed activity) renews the
        # lease for this ensemble's leader (leader_tick renewal analog)
        self.lease_until[won_np] = now + self.config.lease()
        served = 0
        for e in range(self.n_ens):
            any_commit = False
            for j, op in enumerate(taken[e]):
                served += 1
                if op.kind == eng.OP_PUT:
                    if committed[j, e]:
                        any_commit = True
                        op.fut.resolve(("ok", (int(vsn[j, e, 0]),
                                               int(vsn[j, e, 1]))))
                    else:
                        self.values.pop(op.handle, None)
                        op.fut.resolve("failed")
                else:
                    if get_ok[j, e]:
                        if found[j, e] and value[j, e] != 0:
                            op.fut.resolve(
                                ("ok", self.values.get(int(value[j, e]),
                                                       NOTFOUND)))
                        else:
                            op.fut.resolve(("ok", NOTFOUND))
                    else:
                        op.fut.resolve("failed")
            if any_commit:
                self.lease_until[e] = now + self.config.lease()
        self.flushes += 1
        self.ops_served += served
        return served
