"""Sharding the consensus engine over a ('ens', 'peer') device mesh.

The reference scales by running ensembles/peers across Erlang nodes
with disterl messaging (SURVEY §2.7).  The TPU-native layout:

- **'ens' axis** — ensembles are embarrassingly parallel (independent
  consensus groups); the E axis shards across devices with no
  cross-device traffic (the DP analog).
- **'peer' axis** — one ensemble's M peer replicas can live on
  different chips; quorum vote counting, proposal-epoch broadcast, and
  newest-object selection become ``psum``/``pmax`` collectives over the
  'peer' mesh axis riding ICI (the TP analog; the msg.erl
  quorum fan-out/collect, riak_ensemble_msg.erl:85-97,319-332, as an
  all-reduce).

Cross-host (DCN) deployment uses the same code: ``jax.make_mesh`` over
multi-host device arrays gives a mesh whose 'ens' dim spans hosts —
ensembles never need DCN collectives, and peer-axis collectives stay
intra-slice by construction (put the 'peer' dim innermost).

``ShardedEngine`` wraps the :mod:`riak_ensemble_tpu.ops.engine` kernels
in ``shard_map`` with the peer axis sharded; inputs/outputs that carry
a peer axis use spec ('ens', 'peer'), per-ensemble vectors use
('ens',) and are replicated along 'peer'.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_04(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_vma)

from riak_ensemble_tpu.ops import engine as eng


def _default_exp(kind, exp_epoch, exp_seq):
    """shard_map takes concrete operands: absent CAS expected-version
    arrays materialize as zeros of the op-matrix shape."""
    if exp_epoch is None:
        exp_epoch = jnp.zeros(kind.shape, kind.dtype)
    if exp_seq is None:
        exp_seq = jnp.zeros(kind.shape, kind.dtype)
    return exp_epoch, exp_seq


def make_mesh(n_ens: int, n_peer: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Mesh of shape (ens=n_ens, peer=n_peer).

    'peer' is the innermost (fastest-varying) mesh dim so peer-axis
    collectives map to nearest-neighbor ICI links.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    assert devs.size >= n_ens * n_peer, \
        f"need {n_ens * n_peer} devices, have {devs.size}"
    grid = devs[: n_ens * n_peer].reshape(n_ens, n_peer)
    return Mesh(grid, ("ens", "peer"))


# PartitionSpecs for each EngineState field ([E,M] / [E] / [E,V,M] /
# [E,M,S] / [E,M,S,LANES] / [E,M,U,LANES]).
_STATE_SPECS = eng.EngineState(
    epoch=P("ens", "peer"),
    fact_seq=P("ens", "peer"),
    leader=P("ens"),
    view_mask=P("ens", None, "peer"),
    view_vsn=P("ens"),
    pend_vsn=P("ens"),
    commit_vsn=P("ens"),
    obj_seq_ctr=P("ens"),
    obj_epoch=P("ens", "peer", None),
    obj_seq=P("ens", "peer", None),
    obj_val=P("ens", "peer", None),
    tree_leaf=P("ens", "peer", None, None),
    tree_node=P("ens", "peer", None, None),
)

# kv_step_scan stacks results along a leading K axis.
_SCAN_RESULT_SPECS = eng.KvResult(
    committed=P(None, "ens"), get_ok=P(None, "ens"), found=P(None, "ens"),
    value=P(None, "ens"), obj_vsn=P(None, "ens", None),
    quorum_ok=P(None, "ens"), tree_corrupt=P(None, "ens", "peer"),
)

# kv_step_scan_wide stacks [G, E, W] (tree_corrupt: [G, E, Ml]).
_WIDE_RESULT_SPECS = eng.KvResult(
    committed=P(None, "ens", None), get_ok=P(None, "ens", None),
    found=P(None, "ens", None), value=P(None, "ens", None),
    obj_vsn=P(None, "ens", None, None), quorum_ok=P(None, "ens", None),
    tree_corrupt=P(None, "ens", "peer"),
)


class ShardedEngine:
    """Engine kernels shard_map'd over a ('ens', 'peer') mesh.

    E must divide by mesh 'ens' size; M by mesh 'peer' size (pad views
    with absent peers if needed — all-zero view columns are inert).
    """

    def __init__(self, mesh: Mesh) -> None:
        self.mesh = mesh
        ax = "peer" if mesh.shape["peer"] > 1 else None

        def smap(fn, in_specs, out_specs):
            return jax.jit(_shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False))

        self._elect = smap(
            lambda st, el, ca, up: eng.elect_step(st, el, ca, up,
                                                  axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens"), P("ens", "peer")),
            (_STATE_SPECS, P("ens")))
        self._kv = smap(
            lambda st, k, sl, v, lz, up, xe, xs: eng.kv_step_scan(
                st, k, sl, v, lz, up, axis_name=ax, exp_epoch=xe,
                exp_seq=xs),
            (_STATE_SPECS, P(None, "ens"), P(None, "ens"), P(None, "ens"),
             P(None, "ens"), P("ens", "peer"), P(None, "ens"),
             P(None, "ens")),
            (_STATE_SPECS, _SCAN_RESULT_SPECS))
        self._full = smap(
            lambda st, el, ca, k, sl, v, lz, up, xe, xs: eng.full_step(
                st, el, ca, k, sl, v, lz, up, axis_name=ax,
                exp_epoch=xe, exp_seq=xs),
            (_STATE_SPECS, P("ens"), P("ens"), P(None, "ens"),
             P(None, "ens"), P(None, "ens"), P(None, "ens"),
             P("ens", "peer"), P(None, "ens"), P(None, "ens")),
            (_STATE_SPECS, P("ens"), _SCAN_RESULT_SPECS))
        self._full_wide = smap(
            lambda st, el, ca, k, sl, v, lz, up, xe, xs:
                eng.full_step_wide(
                    st, el, ca, k, sl, v, lz, up, axis_name=ax,
                    exp_epoch=xe, exp_seq=xs),
            (_STATE_SPECS, P("ens"), P("ens"), P(None, "ens", None),
             P(None, "ens", None), P(None, "ens", None),
             P(None, "ens", None), P("ens", "peer"),
             P(None, "ens", None), P(None, "ens", None)),
            (_STATE_SPECS, P("ens"), _WIDE_RESULT_SPECS))
        self._reconfig = smap(
            lambda st, pr, nv, up: eng.reconfig_step(st, pr, nv, up,
                                                     axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens", "peer"), P("ens", "peer")),
            (_STATE_SPECS, P("ens"), P("ens")))
        self._reconfig_propose = smap(
            lambda st, pr, nv, vsn, up: eng.reconfig_propose(
                st, pr, nv, vsn, up, axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens", "peer"), P("ens"),
             P("ens", "peer")),
            (_STATE_SPECS, P("ens")))
        self._reconfig_transition = smap(
            lambda st, run, up: eng.reconfig_transition(
                st, run, up, axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens", "peer")),
            (_STATE_SPECS, P("ens")))
        self._exchange = smap(
            lambda st, run, up: eng.exchange_step(st, run, up,
                                                  axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens", "peer")),
            (_STATE_SPECS, P("ens", "peer"), P("ens")))
        self._verify = smap(
            lambda st: eng.verify_trees(st, axis_name=ax),
            (_STATE_SPECS,),
            (P("ens", "peer"), P("ens", "peer")))
        self._rebuild = smap(
            eng.rebuild_trees,
            (_STATE_SPECS, P("ens", "peer")),
            _STATE_SPECS)
        self._reset = smap(
            eng.reset_rows,
            (_STATE_SPECS, P("ens"), P("ens", "peer")),
            _STATE_SPECS)

    # -- placement ---------------------------------------------------------

    def shard_state(self, state: eng.EngineState) -> eng.EngineState:
        """Place a host-built state onto the mesh with engine specs."""
        return jax.tree.map(
            lambda x, spec: jax.device_put(x, NamedSharding(self.mesh, spec)),
            state, _STATE_SPECS)

    def init_state(self, n_ensembles: int, n_peers: int, n_slots: int,
                   **kw) -> eng.EngineState:
        assert n_ensembles % self.mesh.shape["ens"] == 0
        assert n_peers % self.mesh.shape["peer"] == 0
        return self.shard_state(
            eng.init_state(n_ensembles, n_peers, n_slots, **kw))

    # -- steps -------------------------------------------------------------

    def elect_step(self, state, elect, cand, up):
        return self._elect(state, elect, cand, up)

    def kv_step_scan(self, state, kind, slot, val, lease_ok, up,
                     exp_epoch=None, exp_seq=None):
        """Ops are [K, E]-shaped (a scan of K rounds), matching
        :func:`riak_ensemble_tpu.ops.engine.kv_step_scan`.  shard_map
        takes concrete operands, so absent CAS versions materialize as
        zeros here."""
        exp_epoch, exp_seq = _default_exp(kind, exp_epoch, exp_seq)
        return self._kv(state, kind, slot, val, lease_ok, up,
                        exp_epoch, exp_seq)

    def full_step(self, state, elect, cand, kind, slot, val, lease_ok,
                  up, exp_epoch=None, exp_seq=None):
        exp_epoch, exp_seq = _default_exp(kind, exp_epoch, exp_seq)
        return self._full(state, elect, cand, kind, slot, val, lease_ok,
                          up, exp_epoch, exp_seq)

    def full_step_wide(self, state, elect, cand, kind, slot, val,
                       lease_ok, up, exp_epoch=None, exp_seq=None):
        """Wide-scheduled flagship step over the mesh: [G, E, W]
        conflict-free planes (:func:`riak_ensemble_tpu.ops.engine.
        kv_step_scan_wide`)."""
        exp_epoch, exp_seq = _default_exp(kind, exp_epoch, exp_seq)
        return self._full_wide(state, elect, cand, kind, slot, val,
                               lease_ok, up, exp_epoch, exp_seq)

    def reconfig_step(self, state, propose, new_view, up):
        """Joint-consensus membership change over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.reconfig_step`)."""
        return self._reconfig(state, propose, new_view, up)

    def reconfig_propose(self, state, propose, new_view, vsn, up):
        """General views-list cons over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.reconfig_propose`)."""
        return self._reconfig_propose(state, propose, new_view, vsn, up)

    def reconfig_transition(self, state, run, up):
        """Views-list collapse over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.reconfig_transition`)."""
        return self._reconfig_transition(state, run, up)

    def exchange_step(self, state, run, up):
        """Anti-entropy sweep over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.exchange_step`)."""
        return self._exchange(state, run, up)

    def verify_trees(self, state):
        """Integrity sweep over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.verify_trees`)."""
        return self._verify(state)

    def rebuild_trees(self, state, mask):
        """Tree rebuild over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.rebuild_trees`)."""
        return self._rebuild(state, mask)

    def reset_rows(self, state, mask, new_view):
        """Ensemble-row recycle over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.reset_rows`)."""
        return self._reset(state, mask, new_view)
