"""Sharding the consensus engine over a ('ens', 'peer') device mesh.

The reference scales by running ensembles/peers across Erlang nodes
with disterl messaging (SURVEY §2.7).  The TPU-native layout:

- **'ens' axis** — ensembles are embarrassingly parallel (independent
  consensus groups); the E axis shards across devices with no
  cross-device traffic (the DP analog).
- **'peer' axis** — one ensemble's M peer replicas can live on
  different chips; quorum vote counting, proposal-epoch broadcast, and
  newest-object selection become ``psum``/``pmax`` collectives over the
  'peer' mesh axis riding ICI (the TP analog; the msg.erl
  quorum fan-out/collect, riak_ensemble_msg.erl:85-97,319-332, as an
  all-reduce).

Cross-host (DCN) deployment uses the same code: ``jax.make_mesh`` over
multi-host device arrays gives a mesh whose 'ens' dim spans hosts —
ensembles never need DCN collectives, and peer-axis collectives stay
intra-slice by construction (put the 'peer' dim innermost).

``ShardedEngine`` wraps the :mod:`riak_ensemble_tpu.ops.engine` kernels
in ``shard_map`` with the peer axis sharded; inputs/outputs that carry
a peer axis use spec ('ens', 'peer'), per-ensemble vectors use
('ens',) and are replicated along 'peer'.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_04(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_vma)

from riak_ensemble_tpu.ops import engine as eng


def _default_exp(kind, exp_epoch, exp_seq):
    """shard_map takes concrete operands: absent CAS expected-version
    arrays materialize as zeros of the op-matrix shape."""
    if exp_epoch is None:
        exp_epoch = jnp.zeros(kind.shape, kind.dtype)
    if exp_seq is None:
        exp_seq = jnp.zeros(kind.shape, kind.dtype)
    return exp_epoch, exp_seq


def make_mesh(n_ens: int, n_peer: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Mesh of shape (ens=n_ens, peer=n_peer).

    'peer' is the innermost (fastest-varying) mesh dim so peer-axis
    collectives map to nearest-neighbor ICI links.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    assert devs.size >= n_ens * n_peer, \
        f"need {n_ens * n_peer} devices, have {devs.size}"
    grid = devs[: n_ens * n_peer].reshape(n_ens, n_peer)
    return Mesh(grid, ("ens", "peer"))


# The canonical sharded-pytree layout lives next to the NamedTuples it
# describes (ops/engine.state_specs and friends) so the single-shard
# and mesh paths can never drift apart — these module aliases keep the
# historical names for existing callers.
_STATE_SPECS = eng.state_specs()
_SCAN_RESULT_SPECS = eng.scan_result_specs()
_WIDE_RESULT_SPECS = eng.wide_result_specs()


def _forward_cache_size(wrapper, jitted) -> None:
    """Expose the jitted program's compile-cache probe on a plain
    wrapper function: ``obs.CompileWatch`` detects compiles via
    ``fn._cache_size()`` and silently passes through callables that
    lack it — a mesh step without this forward would serve compiles
    invisibly (the satellite-1 contract is CompileWatch-assertable
    zero serve-phase compiles on the mesh path)."""
    cs = getattr(jitted, "_cache_size", None)
    if cs is not None:
        wrapper._cache_size = cs


class ShardedEngine:
    """Engine kernels shard_map'd over a ('ens', 'peer') mesh — the
    first-class mesh serving engine.

    E must divide by mesh 'ens' size; M by mesh 'peer' size (pad views
    with absent peers if needed — all-zero view columns are inert).

    The fused serving steps (``full_step``/``full_step_wide`` and
    their ``_donate`` variants) are INSTANCE attributes: plain
    wrappers over the shard_map'd programs that default absent CAS
    planes and forward ``_cache_size`` so ``CompileWatch`` sees mesh
    compiles and ``BatchedEnsembleService._step_fns`` trusts the
    donate pairing (instance-level pair).  There are NO sliced
    variants — a mesh-sharded E axis cannot gather active columns
    across shards without resharding; the mesh service keeps the full
    grid and compacts the packed RESULT per ens-shard instead (see
    ``batched_host``'s shard-wise packer).
    """

    def __init__(self, mesh: Mesh) -> None:
        self.mesh = mesh
        ax = "peer" if mesh.shape["peer"] > 1 else None

        def smap(fn, in_specs, out_specs, donate=False):
            return jax.jit(_shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False),
                donate_argnums=(0,) if donate else ())

        self._elect = smap(
            lambda st, el, ca, up: eng.elect_step(st, el, ca, up,
                                                  axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens"), P("ens", "peer")),
            (_STATE_SPECS, P("ens")))
        self._kv = smap(
            lambda st, k, sl, v, lz, up, xe, xs: eng.kv_step_scan(
                st, k, sl, v, lz, up, axis_name=ax, exp_epoch=xe,
                exp_seq=xs),
            (_STATE_SPECS, P(None, "ens"), P(None, "ens"), P(None, "ens"),
             P(None, "ens"), P("ens", "peer"), P(None, "ens"),
             P(None, "ens")),
            (_STATE_SPECS, _SCAN_RESULT_SPECS))
        _full_in = (_STATE_SPECS, P("ens"), P("ens"), P(None, "ens"),
                    P(None, "ens"), P(None, "ens"), P(None, "ens"),
                    P("ens", "peer"), P(None, "ens"), P(None, "ens"))
        _full_out = (_STATE_SPECS, P("ens"), _SCAN_RESULT_SPECS)

        def _full_body(st, el, ca, k, sl, v, lz, up, xe, xs):
            return eng.full_step(st, el, ca, k, sl, v, lz, up,
                                 axis_name=ax, exp_epoch=xe, exp_seq=xs)

        self._full = smap(_full_body, _full_in, _full_out)
        self._full_donate = smap(_full_body, _full_in, _full_out,
                                 donate=True)
        _wide_in = (_STATE_SPECS, P("ens"), P("ens"),
                    P(None, "ens", None), P(None, "ens", None),
                    P(None, "ens", None), P(None, "ens", None),
                    P("ens", "peer"), P(None, "ens", None),
                    P(None, "ens", None))
        _wide_out = (_STATE_SPECS, P("ens"), _WIDE_RESULT_SPECS)

        def _wide_body(st, el, ca, k, sl, v, lz, up, xe, xs):
            return eng.full_step_wide(st, el, ca, k, sl, v, lz, up,
                                      axis_name=ax, exp_epoch=xe,
                                      exp_seq=xs)

        self._full_wide = smap(_wide_body, _wide_in, _wide_out)
        self._full_wide_donate = smap(_wide_body, _wide_in, _wide_out,
                                      donate=True)
        # instance-attribute serving steps (see class docstring)
        self.full_step = self._make_step(self._full)
        self.full_step_donate = self._make_step(self._full_donate)
        self.full_step_wide = self._make_step(self._full_wide)
        self.full_step_wide_donate = self._make_step(
            self._full_wide_donate)
        self._reconfig = smap(
            lambda st, pr, nv, up: eng.reconfig_step(st, pr, nv, up,
                                                     axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens", "peer"), P("ens", "peer")),
            (_STATE_SPECS, P("ens"), P("ens")))
        self._reconfig_propose = smap(
            lambda st, pr, nv, vsn, up: eng.reconfig_propose(
                st, pr, nv, vsn, up, axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens", "peer"), P("ens"),
             P("ens", "peer")),
            (_STATE_SPECS, P("ens")))
        self._reconfig_transition = smap(
            lambda st, run, up: eng.reconfig_transition(
                st, run, up, axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens", "peer")),
            (_STATE_SPECS, P("ens")))
        self._exchange = smap(
            lambda st, run, up: eng.exchange_step(st, run, up,
                                                  axis_name=ax),
            (_STATE_SPECS, P("ens"), P("ens", "peer")),
            (_STATE_SPECS, P("ens", "peer"), P("ens")))
        self._verify = smap(
            lambda st: eng.verify_trees(st, axis_name=ax),
            (_STATE_SPECS,),
            (P("ens", "peer"), P("ens", "peer")))
        self._rebuild = smap(
            eng.rebuild_trees,
            (_STATE_SPECS, P("ens", "peer")),
            _STATE_SPECS)
        self._reset = smap(
            eng.reset_rows,
            (_STATE_SPECS, P("ens"), P("ens", "peer")),
            _STATE_SPECS)
        # Placement canonicalizer: shard_state routes host-built
        # states through this identity program so they land on the
        # EXACT sharding the step programs emit.  A device_put with
        # the spelled-out specs places equivalently but spells the
        # spec differently (GSPMD canonicalizes size-1 axes and
        # trailing Nones away), and the differing cache key would
        # force a first-flush recompile after warmup.
        self._canon = smap(lambda st: st, (_STATE_SPECS,),
                           _STATE_SPECS)

    def _make_step(self, jitted):
        """Wrap a shard_map'd fused-step program in the serving-step
        call convention (keyword CAS planes, defaulted to zeros) with
        ``_cache_size`` forwarded for CompileWatch."""
        def step(state, elect, cand, kind, slot, val, lease_ok, up,
                 exp_epoch=None, exp_seq=None):
            exp_epoch, exp_seq = _default_exp(kind, exp_epoch, exp_seq)
            return jitted(state, elect, cand, kind, slot, val,
                          lease_ok, up, exp_epoch, exp_seq)
        _forward_cache_size(step, jitted)
        return step

    # -- placement ---------------------------------------------------------

    @property
    def n_ens_shards(self) -> int:
        """Number of shards along the 'ens' mesh axis."""
        return int(self.mesh.shape["ens"])

    @property
    def n_peer_shards(self) -> int:
        """Number of shards along the 'peer' mesh axis."""
        return int(self.mesh.shape["peer"])

    def shard_state(self, state: eng.EngineState) -> eng.EngineState:
        """Place a host-built state onto the mesh with engine specs —
        via the identity program, so the placement's cache key matches
        the step outputs' bit for bit (see ``_canon`` above)."""
        return self._canon(state)

    def init_state(self, n_ensembles: int, n_peers: int, n_slots: int,
                   **kw) -> eng.EngineState:
        assert n_ensembles % self.mesh.shape["ens"] == 0
        assert n_peers % self.mesh.shape["peer"] == 0
        return self.shard_state(
            eng.init_state(n_ensembles, n_peers, n_slots, **kw))

    # -- steps -------------------------------------------------------------

    def elect_step(self, state, elect, cand, up):
        return self._elect(state, elect, cand, up)

    def kv_step_scan(self, state, kind, slot, val, lease_ok, up,
                     exp_epoch=None, exp_seq=None):
        """Ops are [K, E]-shaped (a scan of K rounds), matching
        :func:`riak_ensemble_tpu.ops.engine.kv_step_scan`.  shard_map
        takes concrete operands, so absent CAS versions materialize as
        zeros here."""
        exp_epoch, exp_seq = _default_exp(kind, exp_epoch, exp_seq)
        return self._kv(state, kind, slot, val, lease_ok, up,
                        exp_epoch, exp_seq)

    # full_step / full_step_wide (+ _donate) are instance attributes
    # built in __init__ — see the class docstring.

    def reconfig_step(self, state, propose, new_view, up):
        """Joint-consensus membership change over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.reconfig_step`)."""
        return self._reconfig(state, propose, new_view, up)

    def reconfig_propose(self, state, propose, new_view, vsn, up):
        """General views-list cons over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.reconfig_propose`)."""
        return self._reconfig_propose(state, propose, new_view, vsn, up)

    def reconfig_transition(self, state, run, up):
        """Views-list collapse over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.reconfig_transition`)."""
        return self._reconfig_transition(state, run, up)

    def exchange_step(self, state, run, up):
        """Anti-entropy sweep over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.exchange_step`)."""
        return self._exchange(state, run, up)

    def verify_trees(self, state):
        """Integrity sweep over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.verify_trees`)."""
        return self._verify(state)

    def rebuild_trees(self, state, mask):
        """Tree rebuild over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.rebuild_trees`)."""
        return self._rebuild(state, mask)

    def reset_rows(self, state, mask, new_view):
        """Ensemble-row recycle over the mesh
        (:func:`riak_ensemble_tpu.ops.engine.reset_rows`)."""
        return self._reset(state, mask, new_view)


def mesh_engine(n_devices: Optional[int] = None, n_peer: int = 1,
                devices: Optional[Sequence] = None) -> ShardedEngine:
    """Build a serving :class:`ShardedEngine` over the first
    ``n_devices`` local devices (default: all of them), ``n_peer`` of
    the mesh innermost on the 'peer' axis.

    The svcnode/bench entry point (``--mesh-devices``).  On a CPU box
    the devices come from ``XLA_FLAGS=--xla_force_host_platform_
    device_count=N`` — which must be set BEFORE jax initializes its
    backend, so a too-small device count fails here with the knob
    named rather than deep inside a shard_map.
    """
    devs = list(devices if devices is not None else jax.devices())
    want = len(devs) if n_devices is None else int(n_devices)
    if want > len(devs):
        raise ValueError(
            f"mesh_engine: asked for {want} devices but jax sees only "
            f"{len(devs)} ({devs[0].platform}); on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={want} in the "
            "environment BEFORE the process imports jax")
    if want % n_peer:
        raise ValueError(
            f"mesh_engine: {want} devices do not divide into "
            f"n_peer={n_peer} columns")
    return ShardedEngine(make_mesh(want // n_peer, n_peer,
                                   devices=devs[:want]))
