"""Multi-host bring-up for the sharded engine.

The reference scales across machines with Erlang distribution; the
engine scales across TPU hosts with ``jax.distributed`` + a global
mesh.  The layout contract (see :mod:`riak_ensemble_tpu.parallel.mesh`)
is what keeps traffic on the right fabric:

- the **'ens' axis spans hosts** — ensembles are independent, so the
  data-parallel axis needs no cross-device collectives at all (DCN
  carries only the host-level client/membership traffic, via
  :mod:`riak_ensemble_tpu.netruntime`);
- the **'peer' axis stays intra-slice** ('peer' is the innermost mesh
  dim), so every quorum psum/pmax rides ICI.

Single-process multi-device (including the driver's
``xla_force_host_platform_device_count`` CPU mesh) needs no
initialization — ``global_mesh`` just shapes whatever devices exist.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from riak_ensemble_tpu.parallel.mesh import Mesh, ShardedEngine, make_mesh


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` wrapper.  On TPU pods with
    standard env plumbing (megascale/GKE), call with no args; explicit
    args cover bare-metal DCN clusters.  No-op if already initialized
    or single-process."""
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    except RuntimeError:
        pass  # already initialized


def global_mesh(n_peer: int = 1,
                devices: Optional[Sequence] = None) -> Mesh:
    """Mesh over ALL processes' devices: ens = total_devices / n_peer,
    peer innermost.  Call after :func:`initialize` on every process."""
    devs = list(devices if devices is not None else jax.devices())
    assert len(devs) % n_peer == 0, (len(devs), n_peer)
    return make_mesh(len(devs) // n_peer, n_peer, devices=devs)


def sharded_engine(n_peer: int = 1) -> ShardedEngine:
    """One-call engine over every device of the (multi-host) job."""
    return ShardedEngine(global_mesh(n_peer))
