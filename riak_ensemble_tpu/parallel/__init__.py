"""Device-mesh and cross-host parallelism for the batched engine."""

from riak_ensemble_tpu.parallel.mesh import (  # noqa: F401
    ShardedEngine, make_mesh,
)
# repgroup imports lazily via `from riak_ensemble_tpu.parallel import
# repgroup` (it pulls jax at module import, same as mesh)
