"""Device-mesh parallelism for the batched consensus engine."""

from riak_ensemble_tpu.parallel.mesh import (  # noqa: F401
    ShardedEngine, make_mesh,
)
