"""ctypes bridge to the native single-pass resolve kernel
(``native/resolvekernel.cc``).

The batched service's per-flush resolve half — packed-result unpack,
``_slot_vsn``/``_inline_value`` mirror scatter, WAL record encode and
the changed-slot delta-frame build — is pure Python per flush and
binds the keyed host ceiling before the device does (ROADMAP item 5).
This module exposes the C++ pass that replaces those four traversals
with one, loaded through :mod:`riak_ensemble_tpu.utils.native`'s
builder with the same degradation discipline as the wire codec and
treestore: no toolchain (or ``RETPU_NATIVE_RESOLVE=0``) means the
pure-Python implementations keep running — they remain the oracle,
and every native output is byte-identical to theirs
(tests/test_native_resolve.py fuzzes the equivalence).
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, Optional, Tuple

import numpy as np

from riak_ensemble_tpu.utils import native

__all__ = ["enabled", "get", "NativeResolve"]

_instance: Optional["NativeResolve"] = None
_instance_tried = False


def enabled() -> bool:
    """The ``RETPU_NATIVE_RESOLVE`` knob (default on): ``0`` pins the
    pure-Python resolve path — the fallback arm of the bench A/B and
    the oracle of the equivalence tests."""
    return os.environ.get("RETPU_NATIVE_RESOLVE", "1") != "0"


def get() -> Optional["NativeResolve"]:
    """The loaded kernel wrapper, or None when the knob is off or the
    toolchain can't build it (callers use the Python fallback).  The
    knob is re-read per call so a service constructed under
    ``RETPU_NATIVE_RESOLVE=0`` (the bench's fallback arm) never picks
    the kernel up; the library handle itself is built once."""
    global _instance, _instance_tried
    if not enabled():
        return None
    if not _instance_tried:
        _instance_tried = True
        lib = native.load_resolve()
        if lib is not None:
            _instance = NativeResolve(lib)
    return _instance


def _pt(a: Optional[np.ndarray]):
    return None if a is None else a.ctypes.data_as(ctypes.c_void_p)


_MERGE_LUT: Optional[Tuple[np.ndarray, np.ndarray]] = None


def _merge_lut() -> Tuple[np.ndarray, np.ndarray]:
    """(merge_of[16], negate[16]) LUTs for the native comm fold —
    built from :mod:`riak_ensemble_tpu.funref`'s classification so C
    never hard-codes the RMW fun table (merge-CLASS codes it does pin:
    they are the wire's cell-fun bytes)."""
    global _MERGE_LUT
    if _MERGE_LUT is None:
        from riak_ensemble_tpu import funref
        merge_of = np.full((16,), -1, np.int32)
        for code, mcls in funref.MERGE_OF.items():
            merge_of[code] = mcls
        negate = np.zeros((16,), np.uint8)
        negate[funref.RMW_SUB] = 1
        _MERGE_LUT = (merge_of, negate)
    return _MERGE_LUT


class NativeResolve:
    """Thin, allocation-explicit wrapper over the C ABI.  Every method
    returns numpy arrays shaped exactly like its Python-fallback
    counterpart's output."""

    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib

    # -- 1) packed-result unpack ----------------------------------------

    def unpack(self, flat: np.ndarray, e: int, m: int, k: int,
               want_vsn: bool, active: Optional[np.ndarray],
               a_width: int, sliced: bool):
        """Single-pass :func:`batched_host.unpack_results` replacement
        (full-width scatter included).  Returns the same 8-tuple, or
        None when the payload doesn't match the expected layout (the
        caller falls back to the Python unpack, which raises the
        honest error)."""
        flat = np.ascontiguousarray(flat, np.uint8)
        if active is not None:
            active = np.ascontiguousarray(active, np.int32)
        won = np.zeros((e,), bool)
        quorum = np.zeros((e,), bool)
        corrupt = np.zeros((e, m), bool)
        if k:
            committed = np.zeros((k, e), bool)
            get_ok = np.zeros((k, e), bool)
            found = np.zeros((k, e), bool)
            value = np.zeros((k, e), np.int32)
            vsn = np.zeros((k, e, 2), np.int32) if want_vsn else None
        else:
            # election-only launches carry no client planes; the
            # kernel still unpacks the control planes
            committed = get_ok = found = value = vsn = None
        rc = self._lib.retpu_resolve_unpack(
            _pt(flat), flat.nbytes, e, m, k, int(want_vsn),
            _pt(active), 0 if active is None else len(active),
            a_width, int(bool(sliced)),
            _pt(won), _pt(quorum), _pt(corrupt),
            _pt(committed), _pt(get_ok), _pt(found),
            _pt(value), _pt(vsn))
        if rc != 0:
            return None
        return (won, quorum, corrupt, committed, get_ok, found,
                value, vsn)

    # -- 2) mirror-slab scatter -----------------------------------------

    def scatter_mirrors(self, e_total: int, s_dim: int,
                        kind: np.ndarray, slot: np.ndarray,
                        committed: np.ndarray, get_ok: np.ndarray,
                        found: np.ndarray, value: np.ndarray,
                        vsn: Optional[np.ndarray],
                        cols: np.ndarray, kcounts: np.ndarray,
                        ack_reads: bool,
                        op_codes: Tuple[int, int, int, int],
                        vsn_np: np.ndarray, vsn_ok: np.ndarray,
                        inl_np: np.ndarray, inl_ok: np.ndarray,
                        inline_cls: np.ndarray) -> bool:
        """Scatter a flush's committed mirror updates straight into
        the service's slabs — the per-op dict-write half of the
        resolve loop, in identical per-column round order."""
        op_put, op_cas, op_get, op_rmw = op_codes
        rc = self._lib.retpu_resolve_mirrors(
            e_total, s_dim,
            _pt(np.ascontiguousarray(kind, np.int32)),
            _pt(np.ascontiguousarray(slot, np.int32)),
            _pt(np.ascontiguousarray(committed, np.uint8)),
            _pt(np.ascontiguousarray(get_ok, np.uint8)),
            _pt(np.ascontiguousarray(found, np.uint8)),
            _pt(np.ascontiguousarray(value, np.int32)),
            _pt(None if vsn is None
                else np.ascontiguousarray(vsn, np.int32)),
            _pt(np.ascontiguousarray(cols, np.int32)),
            _pt(np.ascontiguousarray(kcounts, np.int32)),
            len(cols), int(bool(ack_reads)),
            op_put, op_cas, op_get, op_rmw,
            _pt(vsn_np), _pt(vsn_ok), _pt(inl_np), _pt(inl_ok),
            _pt(inline_cls))
        return rc == 0

    # -- 3) WAL arena encode --------------------------------------------

    def wal_encode(self, e_total: int, lane_j: np.ndarray,
                   lane_e: np.ndarray, lane_slot: np.ndarray,
                   lane_f2: np.ndarray, lane_inline: np.ndarray,
                   key_is_bytes: np.ndarray, key_off: np.ndarray,
                   key_len: np.ndarray, key_arena: bytes,
                   pay_off: np.ndarray, pay_len: np.ndarray,
                   pay_arena: bytes, committed: np.ndarray,
                   value: np.ndarray, vsn: np.ndarray
                   ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Pickle the flush's committed keyed WAL records into one
        preallocated byte arena.  Returns ``(arena_view, index)`` —
        ``index`` rows are (key_off, key_len, val_off, val_len) per
        lane, zero-length for uncommitted lanes — or None on a sizing
        bug (caller falls back)."""
        n = len(lane_j)
        karr = np.frombuffer(key_arena, np.uint8)
        parr = np.frombuffer(pay_arena, np.uint8)
        # exact worst case per record pair: two PROTO+FRAME headers
        # (22), key pickle ("kv" + two ints <= 18), value pickle
        # (MARK/ints/bool/tuple overhead <= 30) + key and payload
        # bytes with their own opcode headers (<= 6 each)
        cap = int(76 * n + int(key_len.sum())
                  + int(np.maximum(pay_len, 0).sum()))
        arena = np.empty((max(cap, 1),), np.uint8)
        idx = np.zeros((n, 4), np.int64)
        used = self._lib.retpu_wal_encode(
            n, e_total, _pt(lane_j), _pt(lane_e), _pt(lane_slot),
            _pt(lane_f2), _pt(lane_inline), _pt(key_is_bytes),
            _pt(key_off), _pt(key_len), _pt(karr),
            _pt(pay_off), _pt(pay_len), _pt(parr),
            _pt(np.ascontiguousarray(committed, np.uint8)),
            _pt(np.ascontiguousarray(value, np.int32)),
            _pt(np.ascontiguousarray(vsn, np.int32)),
            _pt(arena), arena.nbytes, _pt(idx))
        if used < 0:
            return None
        return arena[:used], idx

    # -- 4) delta-frame sections ----------------------------------------

    def delta_sections(self, k: int, e_dim: int,
                       committed: np.ndarray, value: np.ndarray,
                       kind: np.ndarray, slot: np.ndarray,
                       opval: np.ndarray, quorum: np.ndarray,
                       op_codes: Tuple[int, int, int], j_dt, s_dt
                       ) -> Optional[Tuple]:
        """The committed-cell sections + section CRC of
        :func:`repgroup.build_delta_entry`, one column-major pass.
        Returns ``(cols, counts, jj, slots, vals, rmw_b, q_b, crc)``
        with the exact dtypes/bytes of the numpy path."""
        op_put, op_cas, op_rmw = op_codes
        ncap = k * e_dim
        cols = np.empty((e_dim,), np.uint16)
        counts = np.empty((e_dim,), np.uint16)
        jj = np.empty((max(ncap, 1),), j_dt)
        slots = np.empty((max(ncap, 1),), s_dt)
        vals = np.empty((max(ncap, 1),), np.int32)
        rmw_b = np.empty(((ncap + 7) // 8 or 1,), np.uint8)
        q_b = np.empty(((e_dim + 7) // 8,), np.uint8)
        meta = np.zeros((2,), np.int64)
        crc = ctypes.c_uint32(0)
        rc = self._lib.retpu_delta_sections(
            k, e_dim,
            _pt(np.ascontiguousarray(committed, np.uint8)),
            _pt(np.ascontiguousarray(value, np.int32)),
            _pt(np.ascontiguousarray(kind, np.int32)),
            _pt(np.ascontiguousarray(slot, np.int32)),
            _pt(np.ascontiguousarray(opval, np.int32)),
            _pt(np.ascontiguousarray(quorum, np.uint8)),
            op_put, op_cas, op_rmw,
            int(np.dtype(j_dt).itemsize), int(np.dtype(s_dt).itemsize),
            _pt(cols), _pt(counts), _pt(jj), _pt(slots), _pt(vals),
            _pt(rmw_b), _pt(q_b), _pt(meta), ctypes.byref(crc))
        if rc != 0:
            return None
        ncells, ncols = int(meta[0]), int(meta[1])
        return (cols[:ncols].copy(), counts[:ncols].copy(),
                jj[:ncells].copy(), slots[:ncells].copy(),
                vals[:ncells].copy(), rmw_b[:(ncells + 7) // 8].copy(),
                q_b.copy(), int(crc.value))

    # -- 5) commutative-lane fold ---------------------------------------

    def comm_fold(self, committed: np.ndarray, exp_e: np.ndarray,
                  slot: np.ndarray, val: np.ndarray,
                  cand: np.ndarray) -> Optional[dict]:
        """The per-candidate-column coalescing fold of
        :func:`repgroup.build_comm_entry` (ARCHITECTURE §18), one C
        pass.  Returns ``{col: (cells, n_ops)}`` where cells =
        ``[(slot, merge_class, folded_operand, last_rank, last_j),
        ...]`` in first-seen slot order and candidate columns
        disqualified by a mixed-class slot are ABSENT — or None when
        the loaded library predates the symbol (the caller runs the
        Python fold, which is also the equivalence oracle)."""
        fn = getattr(self._lib, "retpu_comm_fold", None)
        if fn is None:
            return None
        k, e_dim = committed.shape
        committed_u8 = np.ascontiguousarray(committed, np.uint8)
        ncap = max(int(committed_u8.sum()), 1)
        merge_of, negate = _merge_lut()
        out_cols = np.empty((e_dim,), np.int32)
        out_counts = np.empty((e_dim,), np.int32)
        out_nops = np.empty((e_dim,), np.int32)
        out_slots = np.empty((ncap,), np.int32)
        out_funs = np.empty((ncap,), np.uint8)
        out_ops = np.empty((ncap,), np.int32)
        out_rl = np.empty((ncap,), np.int32)
        out_jl = np.empty((ncap,), np.int32)
        meta = np.zeros((2,), np.int64)
        rc = fn(
            int(k), int(e_dim), _pt(committed_u8),
            _pt(np.ascontiguousarray(exp_e, np.int32)),
            _pt(np.ascontiguousarray(slot, np.int32)),
            _pt(np.ascontiguousarray(val, np.int32)),
            _pt(np.ascontiguousarray(cand, np.uint8)),
            _pt(merge_of), _pt(negate),
            _pt(out_cols), _pt(out_counts), _pt(out_nops),
            _pt(out_slots), _pt(out_funs), _pt(out_ops),
            _pt(out_rl), _pt(out_jl), _pt(meta))
        if rc != 0:
            return None
        out = {}
        pos = 0
        for i in range(int(meta[0])):
            cnt = int(out_counts[i])
            out[int(out_cols[i])] = (
                [(int(out_slots[x]), int(out_funs[x]),
                  int(out_ops[x]), int(out_rl[x]), int(out_jl[x]))
                 for x in range(pos, pos + cnt)],
                int(out_nops[i]))
            pos += cnt
        return out
