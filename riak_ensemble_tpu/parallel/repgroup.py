"""Replica quorum across OS-process failure domains — the scale path's
availability story.

The reference's availability model is M peers on M *machines*: every
commit's quorum crosses node boundaries
(``riak_ensemble_msg.erl:132-142`` sends to remote pids; one process
hierarchy per node, ``doc/Readme.md:49-63``), so a machine dying
neither loses acked data nor stops service.  The batched service held
all M replica "lanes" of an ensemble in ONE process's device arrays —
durable (WAL) but not available across a host death.  This module
closes that gap with a **replication group**:

- **N host processes**, each holding a single-peer engine shard
  (``n_peers=1``) of ALL the group's ensembles plus its own
  :class:`~riak_ensemble_tpu.parallel.wal.ServiceWAL`.  One host is
  the **leader** (the client-facing
  :class:`ReplicatedService`); the rest run :class:`ReplicaServer`.
- Every device launch the leader performs — the whole ``[K, E]`` op
  plane, the election vector, the lease vector — is **shipped to every
  replica host over the restricted wire codec** and applied through
  the same jitted kernels.  Identical inputs over identical state make
  the lanes bit-equal by induction (all-int32 kernels), which is what
  lets ONE batched protocol replace per-op consensus messages: the
  cross-host agreement is per *launch*, amortized over every op in it
  (the msg.erl fan-out/collect as one frame per host per flush).
- **The commit barrier is a host-level quorum of WAL-persisted
  acks**: each replica fsyncs the batch's committed records before
  acking, and the leader resolves client futures as 'ok' only when
  ``1 + acks >= majority(group)`` — otherwise every op in the flush
  resolves 'failed' while the device-side bookkeeping stands (the
  unacked-commit ambiguity the reference also allows under timeout).
- **Epoch/seq fencing** (the vertical-Paxos shape of the reference's
  epoch discipline, peer.erl:877-885): applies carry a group epoch and
  a batch sequence; a replica accepts seq N+1 at its promised epoch
  only.  Leader takeover = promise round to a majority (grants persist
  before they're answered), adopt the newest ``(epoch, seq)`` state
  among the grants, bump the epoch — after which the old leader's
  straggler applies are nacked and it steps down (the sc.erl
  partition premise, test/sc.erl:1012-1036).
- **Catch-up**: a restarted or diverged replica is re-synced with a
  full state snapshot (engine arrays + keyed host mirrors) pushed by
  the leader, then rides the apply stream again.  Divergence is
  *detected*, not assumed: every ack carries a CRC of the result
  planes and a mismatch marks the replica for re-sync (a cross-host
  integrity check the reference's disterl transport never had).

Determinism notes (why lanes stay bit-equal): election and lease
vectors are computed once by the leader and shipped verbatim (a
replica recomputing ``lease_ok`` from its own clock could disagree);
payload handles are allocated by the leader and ride in the frame;
all kernels are int32 (no float nondeterminism).  Physical corruption
on one host is by nature non-deterministic — it surfaces as a CRC
mismatch and heals through re-sync.

- **Dynamic host membership** (round 5): the group's member set is a
  config record ``(cver, hosts, joint)`` riding the SAME (epoch, seq)
  apply stream as data — grow, shrink, or replace hosts at runtime via
  ``update_members([(host, port), ...])``.  Joint consensus at host
  granularity: while ``joint`` is set, every commit (and every
  takeover) needs a majority of BOTH lists (the multi-view AND,
  msg.erl:377-418; update_members/transition,
  riak_ensemble_peer.erl:655-672,751-774); a joining host is never
  counted before its re-sync completes (synced-before-counted), and
  the collapse record only ships once a majority of the NEW set holds
  the full state.  Campaign safety follows Raft's
  latest-config-in-the-log rule: a candidate adopts the newest config
  among its grants/pulled state and re-validates its quorum under it —
  any committed config is held by at least one member of every
  majority the previous config admits.  Every ensemble's member set is
  the full host set.

Wire protocol (length-prefixed frames, :mod:`riak_ensemble_tpu.wire`):

    leader -> replica
      ("hello", ge)                     handshake on (re)connect
      ("promise", ge)                   takeover prepare
      ("pull",)                         fetch full state (new leader)
      ("install", ge, seq, state, cfg)  push full state (re-sync)
      ("abatch", ge, [entry, ...])      coalesced launch batch; one
                                        raw frame, one cumulative ack.
                                        entry is either a changed-slot
                                        DELTA ("d", seq, k, nc, cols,
                                        counts, js, slots, vals,
                                        rmw_bits, quorum_bits, crc,
                                        meta, fid) or a FULL-plane
                                        fallback ("f", seq, k,
                                        want_vsn, elect, lease, kind,
                                        slot, val, exp_e, exp_s, meta,
                                        fid); meta = put-lane (round,
                                        ens, key, handle, payload)
                                        records; fid = the leader's
                                        obs flush_id (trailing term-
                                        header field, 0 when tracing
                                        is off) — replica apply spans
                                        record under it so one id
                                        names the flush end to end
      ("apply", ge, seq, k, want_vsn, elect, lease, kind, slot, val,
       exp_e, exp_s, meta)              legacy single full-plane
                                        launch (still served)
      ("cfg", ge, seq, cver, hosts, joint)  group-config record
      ("promote", peers, tick)          control: become the leader
      ("status",)                       control: role/epoch/seq
    replica -> leader
      ("helloed", promised, applied_ge, applied_seq)
      ("promised", granted, promised, applied_ge, applied_seq, cfg)
      ("state", ge, seq, state, cfg) | ("installed", ge, seq)
      ("applied", ge, seq, crc) | ("nack", why, promised, age, aseq)

Frames are pipelined per link (FIFO window): responses return in send
order over the replica's sequential per-connection loop.

Delta replication transport (round 6): the leader's resolve half knows
exactly which (ensemble, slot) rows a launch committed, so the common
apply frame ships ONLY those rows — the wire cost scales with what
changed, not with the [K, E] grid (the synctree
payload-proportional-to-change economics applied to the apply stream).
A replica applies a delta IN PLACE: scatter the committed cells into
its object planes, advance the per-ensemble seq counters, rebuild the
touched rows' trees — no device re-execution — and the result is
bit-equal to a full-plane re-execution by construction (commit
epochs/seqs are derivable: epoch is the replica's own ballot plane,
seqs are consecutive per column from its own obj_seq_ctr).  Launches
with elections, leader-side corruption/exchange, bulk device-resident
planes, or a delta-ineligible shape fall back to full-plane entries in
the same stream; re-syncs and install barriers ride ahead exactly as
before.  Entries coalesce: all launches settled by one flush (up to
``repl_window``) ship as ONE raw frame per link — one encode, one
scatter-gather write — and the replica applies the batch through one
mirror/WAL pass, answering one cumulative ack.
"""

from __future__ import annotations

import os
import queue
import socket
import struct
import sys
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from riak_ensemble_tpu import faults, funref, obs, wire
from riak_ensemble_tpu.config import Config
from riak_ensemble_tpu.ops import engine as eng
from riak_ensemble_tpu.parallel.batched_host import (
    BatchedEnsembleService, WallRuntime, _PendingBatch,
    warmup_kernels)
from riak_ensemble_tpu.types import NOTFOUND

_HDR = struct.Struct(">I")
#: install frames carry full engine-state snapshots
_MAX_FRAME = 256 << 20


class DeposedError(RuntimeError):
    """This leader's group epoch was superseded — stop serving."""


# -- framing -----------------------------------------------------------------

def send_frame(sock: socket.socket, value: Any) -> None:
    payload = wire.encode(value)
    sock.sendall(_HDR.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket) -> Any:
    head = _recv_exact(sock, _HDR.size)
    (length,) = _HDR.unpack(head)
    if length > _MAX_FRAME:
        raise wire.WireError(f"frame too large: {length}")
    return wire.decode(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


# -- plane / result codecs ---------------------------------------------------

def _pack_bool(v: np.ndarray) -> bytes:
    return np.packbits(np.asarray(v, bool)).tobytes()


def _unpack_bool(b: bytes, n: int) -> np.ndarray:
    return np.unpackbits(np.frombuffer(b, np.uint8), count=n).astype(bool)


def _pack_i32(v: Optional[np.ndarray]) -> Optional[bytes]:
    return None if v is None else np.asarray(v, np.int32).tobytes()


def _unpack_i32(b: Optional[bytes], shape) -> Optional[np.ndarray]:
    if b is None:
        return None
    return np.frombuffer(b, np.int32).reshape(shape).copy()


def result_crc(committed: Optional[np.ndarray],
               vsn: Optional[np.ndarray]) -> int:
    """CRC of the launch's commit/version outcome — the cross-host
    divergence detector carried in every ack."""
    crc = 0
    if committed is not None:
        crc = zlib.crc32(np.packbits(committed).tobytes(), crc)
    if vsn is not None:
        crc = zlib.crc32(np.ascontiguousarray(vsn).tobytes(), crc)
    return crc


# -- state snapshot ----------------------------------------------------------

def dump_state(svc: BatchedEnsembleService) -> Tuple:
    """Full snapshot of one host's lane: every engine array plus the
    keyed host mirrors a promoted leader needs — including the
    dynamic-lifecycle directory (live rows, free pool, tenant names)
    when the group serves dynamic tenants.  Wire-safe (no pickle: the
    group transport keeps the no-code-on-decode trust model of the
    cluster transport)."""
    fields = []
    for name, arr in zip(eng.EngineState._fields, svc.state):
        a = np.asarray(arr)
        fields.append((name, a.dtype.str, list(a.shape), a.tobytes()))
    host = (
        [list(ks.items()) for ks in svc.key_slot],
        [list(sh.items()) for sh in svc.slot_handle],
        list(svc.values.items()),
        int(svc._next_handle),
        _pack_i32(svc.leader_np),
        bool(svc.dynamic),
        _pack_bool(svc._live),
        list(svc._free_rows),
        list(svc._ens_names.items()),
        _pack_bool(svc.member_np.ravel()),
        [sorted(s) for s in svc._inline_slots],
    )
    return (tuple(fields), host)


def install_state(svc: BatchedEnsembleService, dump: Tuple) -> None:
    """Inverse of :func:`dump_state`: make this host's lane bit-equal
    to the dumped one.  Derived host structures (free slots, slot
    generations) are recomputed — they are per-process queue
    bookkeeping, not replicated state."""
    import jax.numpy as jnp

    fields, host = dump
    if bool(host[5]) != svc.dynamic:
        # a mixed group would HALF-sync (directory dropped or stale):
        # fail BEFORE any mutation — a torn half-install would leave
        # snapshot arrays over stale derived mirrors (review r4)
        raise ValueError(
            f"lifecycle-mode mismatch: snapshot dynamic={bool(host[5])}"
            f" vs this lane dynamic={svc.dynamic} — every group host "
            "must run the same --dynamic setting")
    by_name = {name: (dt, shape, raw) for name, dt, shape, raw in fields}
    new = {}
    for name in eng.EngineState._fields:
        dt, shape, raw = by_name[name]
        new[name] = jnp.asarray(
            np.frombuffer(raw, np.dtype(dt)).reshape(shape))
    svc.state = eng.EngineState(**new)
    (key_slot, slot_handle, values, next_handle, leader_b, dynamic,
     live_b, free_rows, ens_names, member_b, *rest) = host
    inline = (rest[0] if rest
              else [[] for _ in range(svc.n_ens)])
    svc._inline_slots = [set(int(s) for s in row) for row in inline]
    svc._inline_np[:] = False
    for row, slots_ in enumerate(svc._inline_slots):
        if slots_:  # storage-class slab rides with the sets
            svc._inline_np[row, list(slots_)] = True
    svc.key_slot = [dict(pairs) for pairs in key_slot]
    svc.slot_handle = [{int(s): int(h) for s, h in pairs}
                       for pairs in slot_handle]
    svc.values = {int(h): v for h, v in values}
    svc._next_handle = int(next_handle)
    svc._free_handles = []
    svc.leader_np = _unpack_i32(leader_b, (svc.n_ens,))
    svc.member_np = _unpack_bool(member_b,
                                 svc.n_ens * svc.n_peers).reshape(
        svc.n_ens, svc.n_peers)
    if bool(dynamic):
        svc.dynamic = True
        svc._live = _unpack_bool(live_b, svc.n_ens)
        svc._free_rows = [int(r) for r in free_rows]
        svc._ens_names = dict(ens_names)
        svc._row_name = {r: n for n, r in svc._ens_names.items()}
    rebuild_derived(svc)


def rebuild_derived(svc: BatchedEnsembleService) -> None:
    """Recompute free-slot lists / slot generations from the keyed
    mirrors (used after install and before a replica checkpoints —
    replicas don't maintain them incrementally).  The read fast
    path's caches reset with them: the snapshot superseded whatever
    versions/inline values were mirrored (they repopulate lazily —
    each miss takes the device round, whose resolve re-mirrors).
    The pending-write index is NOT cleared: it tracks live queue
    entries, which survive an install and still resolve afterward."""
    for e in range(svc.n_ens):
        used = set(svc.key_slot[e].values())
        svc.free_slots[e] = [s for s in range(svc.n_slots)
                             if s not in used]
        svc.slot_gen[e] = {}
        svc._recycle_pending[e] = []
        svc._slot_vsn_ok[e] = False
        svc._inline_value_ok[e] = False


# -- incremental (Merkle) catch-up -------------------------------------------
#
# The full snapshot install ships EVERY engine array + host mirror per
# re-sync — O(state).  A restarted replica usually diverges in a
# handful of slots, and both sides already hold the device Merkle
# trees, so divergence is findable for O(width·height·diffs) traffic
# (synctree.erl:372-417, riak_ensemble_exchange.erl:67-98; the
# cross-process streamed form proven in synctree/remote_sync.py).  The
# catch-up protocol, leader-driven over the FIFO link:
#
#   ("troots",)        -> per-ensemble root hashes [E, LANES] (+ the
#                         replica's frozen (ge, seq) — the diff is only
#                         valid against a replica that is NACKING the
#                         apply stream; any state change voids it)
#   ("tleaves", rows)  -> leaf planes [n, S, LANES] for diverged rows
#   ("tpatch", ge, seq, expect, meta, patches)
#                      -> control-plane vectors (O(E), small) + the
#                         diverged slots' objects/keys/payloads only;
#                         guarded by expect == the replica's (ge, seq)
#                         at probe time — a mismatch nacks and the
#                         leader falls back to the full snapshot.
#
# The probe runs in a thread (never blocking the commit path); the
# patch itself is built in a flush preamble and re-diffs the CURRENT
# leader roots against the cached replica roots, so leader-side writes
# (epoch rewrites on reads included — any device mutation moves a
# root) between probe and patch are covered at row granularity.

#: per-ensemble control-plane vectors shipped whole with every patch
_META_FIELDS = ("epoch", "fact_seq", "leader", "view_mask", "view_vsn",
                "pend_vsn", "commit_vsn", "obj_seq_ctr")


def dump_meta(svc: BatchedEnsembleService) -> Tuple:
    """The per-ensemble control plane WITHOUT the O(keys) payload:
    ballot vectors, membership rows, dynamic directory."""
    vecs = []
    for name in _META_FIELDS:
        a = np.asarray(getattr(svc.state, name))
        vecs.append((name, a.dtype.str, list(a.shape), a.tobytes()))
    host = (_pack_i32(svc.leader_np), bool(svc.dynamic),
            _pack_bool(svc._live), list(svc._free_rows),
            list(svc._ens_names.items()),
            _pack_bool(svc.member_np.ravel()), int(svc._next_handle))
    return (tuple(vecs), host)


def meta_dynamic(meta: Tuple) -> bool:
    """The lifecycle-mode flag carried by a :func:`dump_meta` tuple —
    checkable WITHOUT applying anything (handle_tpatch validates it
    before the first mutation)."""
    return bool(meta[1][1])


def install_meta(svc: BatchedEnsembleService, meta: Tuple) -> None:
    import jax.numpy as jnp

    vecs, host = meta
    (leader_b, dynamic, live_b, free_rows, ens_names, member_b,
     next_handle) = host
    if bool(dynamic) != svc.dynamic:
        # validate BEFORE any mutation (ADVICE r5): assigning the
        # leader's control-plane vectors and THEN failing would leave
        # this lane holding them over its own object planes at its
        # old (ge, seq) — mixed state a campaign could serve from
        raise ValueError("lifecycle-mode mismatch in tree patch")
    new = {name: jnp.asarray(
        np.frombuffer(raw, np.dtype(dt)).reshape(shape))
        for name, dt, shape, raw in vecs}
    svc.state = svc.state._replace(**new)
    svc.leader_np = _unpack_i32(leader_b, (svc.n_ens,))
    svc.member_np = _unpack_bool(
        member_b, svc.n_ens * svc.n_peers).reshape(svc.n_ens,
                                                   svc.n_peers)
    if bool(dynamic):
        svc._live = _unpack_bool(live_b, svc.n_ens)
        svc._free_rows = [int(r) for r in free_rows]
        svc._ens_names = dict(ens_names)
        svc._row_name = {r: n for n, r in svc._ens_names.items()}
    svc._next_handle = max(svc._next_handle, int(next_handle))
    svc._up_dev = None


_DELTA_SCATTER_FN = None
_DELTA_FINISH_FN = None
#: scatter chunk cap — bounds the pow2 program ladder the replica can
#: ever compile for the cell scatter (8..cap); wider cell runs loop in
#: cap-sized chunks.  An uncapped bucket would hit a NEW bucket (and a
#: fresh mid-run XLA compile, hundreds of ms on CPU) the first time a
#: coalesced batch spanned more entries than any before it — measured
#: as 2x ack latency and 4x ack p99 on the bench's pipelined loop.
_DELTA_SCATTER_CAP = 1024


def _delta_fns():
    """The replica's in-place delta apply as TWO compiled programs:
    the three object-plane scatters (one program per pow2 bucket up
    to ``_DELTA_SCATTER_CAP``) and a finish pass (counter swap +
    touched-row tree rebuild, one program).  The eager op-by-op
    version dispatched the whole hash-tree rebuild one primitive at
    a time — ~6x the per-batch replica ack cost."""
    global _DELTA_SCATTER_FN, _DELTA_FINISH_FN
    if _DELTA_SCATTER_FN is None:
        import jax

        def scatter(st, e_j, s_j, eps, sqs, vls):
            return st._replace(
                obj_epoch=st.obj_epoch.at[e_j, 0, s_j].set(
                    eps, mode="drop"),
                obj_seq=st.obj_seq.at[e_j, 0, s_j].set(
                    sqs, mode="drop"),
                obj_val=st.obj_val.at[e_j, 0, s_j].set(
                    vls, mode="drop"))

        def finish(st, ctr, rows):
            return eng.rebuild_trees(
                st._replace(obj_seq_ctr=ctr), rows)

        _DELTA_SCATTER_FN = jax.jit(scatter)
        _DELTA_FINISH_FN = jax.jit(finish)
    return _DELTA_SCATTER_FN, _DELTA_FINISH_FN


def _delta_scatter_cells(svc: BatchedEnsembleService,
                         cells: np.ndarray, ctr_np: np.ndarray,
                         rows: np.ndarray,
                         marks: Optional[Dict[str, float]] = None
                         ) -> None:
    """Land committed cells ``[n, (e, s, epoch, seq, val)]`` in the
    service's object planes through the capped bucket ladder, then
    swap the counters and rebuild the touched rows' trees.  ``marks``
    (obs tracing) gets the blocked scatter/rebuild split in
    seconds."""
    import jax.numpy as jnp

    t0 = time.perf_counter()
    scatter, finish = _delta_fns()
    if svc._obs:
        # compile telemetry (ARCHITECTURE §11): a delta batch landing
        # on an un-warmed scatter bucket pays a mid-ack XLA compile —
        # the watch makes that a counted, named event
        scatter = svc._watched("delta_scatter", scatter)
        finish = svc._watched("delta_finish", finish)
    st = svc.state
    for off in range(0, cells.shape[0], _DELTA_SCATTER_CAP):
        chunk = cells[off:off + _DELTA_SCATTER_CAP]
        b = 8
        while b < chunk.shape[0]:
            b <<= 1
        pad = b - chunk.shape[0]
        if pad:
            # pads aim at slot index S and drop out of range
            chunk = np.concatenate(
                [chunk, np.tile(np.asarray(
                    [[0, svc.n_slots, 0, 0, 0]], np.int32),
                    (pad, 1))])
        st = scatter(st, jnp.asarray(chunk[:, 0]),
                     jnp.asarray(chunk[:, 1]),
                     jnp.asarray(chunk[:, 2]),
                     jnp.asarray(chunk[:, 3]),
                     jnp.asarray(chunk[:, 4]))
    # obs marks are DISPATCH times (no block_until_ready): forcing a
    # device sync here would serialize the replica's scatter/rebuild
    # with its WAL+ack path on every delta run — the async dispatch
    # chain must keep overlapping exactly as without tracing.  The
    # device-side completion cost shows up in whichever later span
    # first consumes the arrays (the same d2h-blind discipline as the
    # leader's 'dispatch' mark).
    if marks is not None:
        t1 = time.perf_counter()
        marks["scatter"] = t1 - t0
    svc.state = finish(st, jnp.asarray(ctr_np), jnp.asarray(rows))
    if marks is not None:
        marks["rebuild"] = time.perf_counter() - t1


_MERGE_GATHER_FN = None


def _merge_fns():
    """The replica's compiled merge-scatter front half: gather each
    merged cell's CURRENT value from this lane's own object plane and
    fold the coalesced operand into it (docs/ARCHITECTURE.md §18).
    The back half — landing the folded values — rides the existing
    delta cell scatter, so a merge run costs ONE extra gather program
    over the plain delta apply."""
    global _MERGE_GATHER_FN
    if _MERGE_GATHER_FN is None:
        import jax

        def gather_merge(st, e_j, s_j, mcls, ops):
            cur = st.obj_val[e_j, 0, s_j]
            return eng.merge_vals(cur, mcls, ops)

        _MERGE_GATHER_FN = jax.jit(gather_merge)
    return _MERGE_GATHER_FN


def _merge_gather_cells(svc: BatchedEnsembleService, e_j: np.ndarray,
                        s_j: np.ndarray, mcls: np.ndarray,
                        ops: np.ndarray) -> np.ndarray:
    """Fold merged operands against the lane's PRE-RUN device values:
    one compiled gather+merge per pow2 bucket (same capped ladder as
    the cell scatter), blocking d2h — the folded values feed the host
    walk's WAL records and mirrors, so this read must complete."""
    import jax.numpy as jnp

    fn = _merge_fns()
    if svc._obs:
        fn = svc._watched("merge_gather", fn)
    out = np.empty(e_j.size, np.int32)
    for off in range(0, e_j.size, _DELTA_SCATTER_CAP):
        n = min(_DELTA_SCATTER_CAP, e_j.size - off)
        b = 8
        while b < n:
            b <<= 1
        sl = slice(off, off + n)

        def pad(a):
            # pads gather in-range cell (0, 0); their folds are
            # discarded below
            if b == n:
                return jnp.asarray(np.ascontiguousarray(a[sl]))
            return jnp.asarray(np.concatenate(
                [a[sl], np.zeros(b - n, a.dtype)]))

        r = fn(svc.state, pad(e_j.astype(np.int32)),
               pad(s_j.astype(np.int32)), pad(mcls.astype(np.int32)),
               pad(ops.astype(np.int32)))
        out[sl] = np.asarray(r)[:n]
    return out


def warm_delta_apply(svc: BatchedEnsembleService) -> None:
    """Pre-compile the delta-apply programs — the WHOLE scatter
    bucket ladder (8..min(cap, E*S): any batch lands on a warmed
    shape) plus the finish pass — so no replica delta batch ever eats
    an XLA compile in its ack latency.  Pure no-op on state: every
    pad aims out of range and the rebuild mask is all-false."""
    import jax.numpy as jnp

    scatter, finish = _delta_fns()
    if svc._obs:
        scatter = svc._watched("delta_scatter", scatter)
        finish = svc._watched("delta_finish", finish)
    top = 8
    while top < min(_DELTA_SCATTER_CAP, svc.n_ens * svc.n_slots):
        top <<= 1
    gather = _merge_fns()
    if svc._obs:
        gather = svc._watched("merge_gather", gather)
    svc._in_warmup = True  # compile events land under phase=warmup
    try:
        st, b = svc.state, 8
        while b <= top:
            e_j = jnp.zeros((b,), jnp.int32)
            s_j = jnp.full((b,), svc.n_slots, jnp.int32)  # oor: drop
            z = jnp.zeros((b,), jnp.int32)
            st = scatter(st, e_j, s_j, z, z, z)
            # merge-gather bucket (§18): reads cell (0, 0), discards
            gather(st, z, z, z, z)
            b <<= 1
        svc.state = finish(
            st, jnp.asarray(np.asarray(st.obj_seq_ctr, np.int32)),
            jnp.zeros((svc.n_ens, svc.n_peers), bool))
    finally:
        svc._in_warmup = False


def tree_roots(svc: BatchedEnsembleService) -> np.ndarray:
    """Per-ensemble root hashes of the single-peer lane: [E, LANES]
    (the root is the LAST entry of the concatenated upper levels)."""
    return np.asarray(svc.state.tree_node[:, 0, -1, :], np.uint32)


def tree_leaves(svc: BatchedEnsembleService,
                rows: Sequence[int]) -> np.ndarray:
    """Leaf planes for the given ensemble rows: [n, S, LANES] — one
    device gather, only the requested rows cross the link."""
    import jax.numpy as jnp

    idx = jnp.asarray(np.asarray(list(rows), np.int32))
    return np.asarray(svc.state.tree_leaf[idx, 0], np.uint32)


class _TreeSync:
    """Leader-side catch-up state for one link (probe thread output +
    the cached replica tree the patch build re-diffs against)."""

    __slots__ = ("result", "expect", "remote_roots", "remote_leaves",
                 "bytes")

    def __init__(self) -> None:
        self.result: Optional[str] = None   # None=running|patch|full
        self.expect = (0, 0)
        self.remote_roots: Optional[np.ndarray] = None
        self.remote_leaves: Dict[int, np.ndarray] = {}
        self.bytes = 0


# -- group metadata persistence ----------------------------------------------

_GRP_KEY = ("grp",)

#: group configuration: (cver, hosts, joint) — cver a monotone config
#: version, hosts the committed member address list (None = legacy
#: implicit mode where group_size alone defines the quorum), joint the
#: incoming member list during a joint-consensus transition (commits
#: then need a majority of BOTH lists — msg.erl:377-418's multi-view
#: AND at host granularity).  Addresses are exact-match identities:
#: every group host must be listed with the same (host, port) string
#: everywhere.
GroupCfg = Tuple[int, Optional[Tuple], Optional[Tuple]]

NO_CFG: GroupCfg = (0, None, None)


def _norm_addrs(hosts) -> Optional[Tuple]:
    if hosts is None:
        return None
    return tuple((str(h), int(p)) for h, p in hosts)


def _norm_cfg(cfg) -> GroupCfg:
    if not cfg:
        return NO_CFG
    cver, hosts, joint = cfg
    return (int(cver), _norm_addrs(hosts), _norm_addrs(joint))


def load_group_meta(svc: BatchedEnsembleService
                    ) -> Tuple[int, int, int, GroupCfg]:
    """(promised_ge, applied_ge, applied_seq, cfg) from the WAL, or
    zeros/NO_CFG.  Pre-round-5 records (no cfg element) read as
    legacy implicit membership."""
    if svc._wal is None:
        return (0, 0, 0, NO_CFG)
    for key, value in svc._wal.records():
        if key == _GRP_KEY:
            cfg = _norm_cfg(value[3]) if len(value) > 3 else NO_CFG
            return (int(value[0]), int(value[1]), int(value[2]), cfg)
    return (0, 0, 0, NO_CFG)


def save_group_meta(svc: BatchedEnsembleService, promised: int,
                    applied_ge: int, applied_seq: int,
                    cfg: GroupCfg = NO_CFG) -> None:
    if svc._wal is not None:
        svc._wal.log([(_GRP_KEY,
                       (promised, applied_ge, applied_seq, cfg))])


# -- apply-frame construction ------------------------------------------------

def _entries_meta(entries, kind: np.ndarray, slot: np.ndarray,
                  values: Dict[int, Any]) -> List[Tuple]:
    """Put/CAS/RMW lane metadata for the replicas' WALs and keyed
    mirrors: (round j, ensemble e, key, handle, payload).  Mirrors
    the iteration order of ``_log_wal`` so rounds line up with the op
    planes.  RMW lanes carry (key, 0, None) — their committed value is
    device-computed, so the replica reads it from its OWN result
    planes (the kind plane says which rounds are RMW)."""
    meta: List[Tuple] = []
    if entries is None:
        return meta
    for e, ops in entries:
        j = -1
        for op in ops:
            if isinstance(op, _PendingBatch):
                if op.kind in (eng.OP_PUT, eng.OP_CAS):
                    for i in range(op.n):
                        h = int(op.handle[i])
                        key = op.keys[i] if op.keys is not None else None
                        meta.append((j + 1 + i, e, key, h,
                                     values.get(h) if h else None))
                elif op.kind == eng.OP_RMW:
                    for i in range(op.n):
                        key = op.keys[i] if op.keys is not None else None
                        meta.append((j + 1 + i, e, key, 0, None))
                j += op.n
                continue
            j += 1
            if op.kind in (eng.OP_PUT, eng.OP_CAS):
                meta.append((j, e, op.key, op.handle,
                             values.get(op.handle) if op.handle
                             else None))
            elif op.kind == eng.OP_RMW:
                meta.append((j, e, op.key, 0, None))
    return meta


def build_apply_frame(ge: int, seq: int, k: int, want_vsn: bool,
                      elect: np.ndarray, lease_ok: np.ndarray,
                      kind: np.ndarray, slot: np.ndarray,
                      val: np.ndarray, exp_e: Optional[np.ndarray],
                      exp_s: Optional[np.ndarray],
                      meta: List[Tuple]) -> Tuple:
    return ("apply", ge, seq, k, want_vsn, _pack_bool(elect),
            _pack_bool(lease_ok), _pack_i32(kind), _pack_i32(slot),
            _pack_i32(val), _pack_i32(exp_e), _pack_i32(exp_s), meta)


def record_digest(items) -> int:
    """Canonical digest for replicated admin records (the
    version-preserving install's allocation): int-coerced tuples
    through the wire codec.  ``repr`` of mixed numpy/int tuples is not
    a stable contract across Python/numpy versions (``np.int32(5)``
    reprs differently between numpy 1.x and 2.x); the wire encoding
    of plain ints is the format both ends already agree on."""
    return zlib.crc32(wire.encode(
        [tuple(int(x) for x in item) for item in items]))


# -- changed-slot delta entries ----------------------------------------------
#
# A delta entry carries, per ensemble column with commits, the
# committed cells in round order: the round index, the written slot
# and the written value.  Everything else a full-plane re-execution
# would have produced is DERIVABLE on the replica from its own
# (bit-equal) state: the commit epoch is its ballot plane's leader
# epoch, the commit seqs are consecutive from its obj_seq_ctr, a
# GET-rewrite's value is the slot's current value (it rides in the
# shipped vals plane anyway — the leader's result planes report it).
# Sections ship as wire.Raw buffers: native byte order (the same
# contract _pack_i32 frames always had), int16 round/slot indices
# (guarded: k and n_slots must fit), int32 elsewhere.

def _idx_dtype(bound: int):
    """Narrowest unsigned dtype holding indices < bound (byte count
    rides the entry so both ends agree)."""
    return np.uint8 if bound <= 256 else np.uint16


def build_delta_entry(seq: int, k: int, committed: Optional[np.ndarray],
                      value: Optional[np.ndarray],
                      kind: np.ndarray, slot: np.ndarray,
                      val: np.ndarray, quorum_ok: np.ndarray,
                      meta: List[Tuple],
                      n_slots: int = 65536,
                      fid: int = 0,
                      native: Any = None) -> Tuple[Tuple, int, int]:
    """Build one delta entry from the leader's resolved planes.

    Returns ``(entry, crc, delta_bytes)`` — the wire entry tuple, the
    CRC over its raw sections (the ack/integrity contract), and the
    section byte count (the shipped-bytes meter).  Index sections use
    the narrowest width that fits (round index by K, slot by S,
    column/count by E/K as uint16) — at a dense write batch the entry
    runs ~6-7 bytes per committed cell against the full planes' 20.
    ``fid`` is the leader's obs flush id, a trailing header field the
    replica tags its apply spans with (cross-process flush tracing);
    it rides outside the section CRC — tracing identity, not
    replicated state.

    ``native`` is the loaded resolve kernel
    (:mod:`riak_ensemble_tpu.parallel.resolve_native`): one C pass
    then emits the committed-cell sections + CRC instead of the
    nonzero/lexsort/unique/packbits numpy pipeline — byte-identical
    output (the tests' contract), same wire entry either way."""
    j_dt = _idx_dtype(max(k, 1))
    s_dt = _idx_dtype(n_slots)
    nat = None
    if (native is not None and committed is not None
            and committed.any()):
        nat = native.delta_sections(
            k, committed.shape[1], committed, value, kind, slot, val,
            np.asarray(quorum_ok, bool),
            (eng.OP_PUT, eng.OP_CAS, eng.OP_RMW), j_dt, s_dt)
    if nat is not None:
        cols, counts, jj, slots, vals, rmw_b, q_b, crc = nat
        nbytes = sum(int(s.nbytes)
                     for s in (cols, counts, jj, slots, vals, rmw_b,
                               q_b))
        entry = ("d", int(seq), int(k), int(jj.size),
                 int(j_dt().nbytes), int(s_dt().nbytes),
                 wire.Raw(cols), wire.Raw(counts), wire.Raw(jj),
                 wire.Raw(slots), wire.Raw(vals), wire.Raw(rmw_b),
                 wire.Raw(q_b), crc, meta, int(fid))
        return entry, crc, nbytes
    if committed is not None and committed.any():
        jj, ee = np.nonzero(committed)
        order = np.lexsort((jj, ee))  # column-major, round order within
        jj = jj[order].astype(j_dt)
        slots = slot[committed][order].astype(s_dt)
        is_put = np.isin(kind[committed][order],
                         (eng.OP_PUT, eng.OP_CAS))
        vals = np.where(is_put, val[committed][order],
                        value[committed][order]).astype(np.int32)
        rmw = (kind[committed][order] == eng.OP_RMW)
        cols, counts = np.unique(ee[order], return_counts=True)
        cols = cols.astype(np.uint16)
        counts = counts.astype(np.uint16)
        rmw_b = np.packbits(rmw)
    else:
        jj = np.zeros((0,), j_dt)
        slots = np.zeros((0,), s_dt)
        vals = np.zeros((0,), np.int32)
        cols = np.zeros((0,), np.uint16)
        counts = np.zeros((0,), np.uint16)
        rmw_b = np.zeros((0,), np.uint8)
    q_b = np.packbits(np.asarray(quorum_ok, bool))
    sections = (cols, counts, jj, slots, vals, rmw_b, q_b)
    crc = 0
    nbytes = 0
    for s in sections:
        b = np.ascontiguousarray(s)
        crc = zlib.crc32(b.tobytes(), crc)
        nbytes += b.nbytes
    entry = ("d", int(seq), int(k), int(jj.size),
             int(j_dt().nbytes), int(s_dt().nbytes),
             wire.Raw(np.ascontiguousarray(cols)),
             wire.Raw(np.ascontiguousarray(counts)),
             wire.Raw(np.ascontiguousarray(jj)),
             wire.Raw(np.ascontiguousarray(slots)),
             wire.Raw(np.ascontiguousarray(vals)),
             wire.Raw(np.ascontiguousarray(rmw_b)),
             wire.Raw(np.ascontiguousarray(q_b)), crc, meta,
             int(fid))
    return entry, crc, nbytes


#: RMW fun code -> "folds into a merge cell" (ordered funs and every
#: non-RMW exp_epoch value read False; the exp_epoch plane only means
#: a fun code on OP_RMW rows, so callers AND with the kind mask)
_RMW_MERGEABLE = np.zeros(16, bool)
for _code in funref.MERGE_OF:
    _RMW_MERGEABLE[_code] = True
del _code


def build_comm_entry(seq: int, k: int, committed: Optional[np.ndarray],
                     value: Optional[np.ndarray],
                     kind: np.ndarray, slot: np.ndarray,
                     val: np.ndarray, exp_e: Optional[np.ndarray],
                     quorum_ok: np.ndarray, meta: List[Tuple],
                     n_slots: int = 65536, fid: int = 0,
                     native: Any = None
                     ) -> Optional[Tuple[Tuple, int, int, int, int]]:
    """Build a commutative-replication entry ("m") when the flush has
    qualifying columns, else None (the caller ships the plain delta —
    which keeps the RETPU_COMM_REPL=0 arm AND non-commutative traffic
    byte-identical by construction; docs/ARCHITECTURE.md §18).

    A column qualifies when EVERY committed cell in it is an OP_RMW
    whose fun is commutative/semilattice AND each of its slots sees a
    single merge class (sub normalizes into add; a max-then-add slot
    stays ordered).  Qualifying columns leave the ordered sections
    entirely and ship as per-(column, slot) COALESCED cells: the
    folded operand, the merge class, the rank of the slot's LAST
    committed op inside the column (its seq offset — version vectors
    land bit-equal to the sequenced apply) and that op's round index
    (the meta join for WAL/mirror keys).  ``m_nops`` per column
    advances the replica's seq counter by the ops the cells absorbed.

    Returns ``(entry, crc, nbytes, n_cells, n_ops)`` — crc is the
    ordered-half CRC chained with the merge-section CRC (the ack
    contract covers both)."""
    if committed is None or exp_e is None or not committed.any():
        return None
    is_rmw = committed & (kind == eng.OP_RMW)
    if not is_rmw.any():
        return None
    mergeable = is_rmw & _RMW_MERGEABLE[np.clip(exp_e, 0, 15)]
    per_col = committed.sum(axis=0)
    cand = (per_col > 0) & (per_col == mergeable.sum(axis=0))
    if not cand.any():
        return None
    m_cols: List[int] = []
    m_counts: List[int] = []
    m_nops: List[int] = []
    m_slots: List[int] = []
    m_funs: List[int] = []
    m_ops: List[int] = []
    m_rl: List[int] = []
    m_jl: List[int] = []
    qual = np.zeros(committed.shape[1], bool)
    n_ops_total = 0
    fold = None
    if native is not None:
        fold = native.comm_fold(committed, exp_e, slot, val, cand)
    for c in np.nonzero(cand)[0].tolist():
        if fold is not None:
            col = fold.get(c)
            if col is None:
                continue
            cells, nops = col
        else:
            rows = np.nonzero(committed[:, c])[0]
            # slot -> [merge class, folded operand, last rank, last j]
            # in first-seen slot order (dicts preserve insertion)
            cells_d: Dict[int, List[int]] = {}
            ok = True
            for rank, j in enumerate(rows.tolist()):
                code = int(exp_e[j, c])
                s = int(slot[j, c])
                v = int(val[j, c])
                mcls = funref.MERGE_OF[code]
                cell = cells_d.get(s)
                if cell is None:
                    cells_d[s] = [mcls, funref.fold_seed(code, v),
                                  rank, j]
                elif cell[0] != mcls:
                    ok = False  # mixed classes on one slot: ordered
                    break
                else:
                    cell[1] = funref.fold_operand(code, cell[1], v)
                    cell[2] = rank
                    cell[3] = j
            if not ok:
                continue
            nops = int(rows.size)
            cells = [(s, cl[0], cl[1], cl[2], cl[3])
                     for s, cl in cells_d.items()]
        qual[c] = True
        m_cols.append(int(c))
        m_counts.append(len(cells))
        m_nops.append(nops)
        n_ops_total += nops
        for s, mcls, acc, rank, j in cells:
            m_slots.append(s)
            m_funs.append(mcls)
            m_ops.append(acc)
            m_rl.append(rank)
            m_jl.append(j)
    if not qual.any():
        return None
    # ordered half: the SAME delta builder over the non-merge columns
    # (native path and byte layout untouched)
    d_entry, d_crc, d_bytes = build_delta_entry(
        seq, k, committed & ~qual[None, :], value, kind, slot, val,
        quorum_ok, meta, n_slots=n_slots, fid=fid, native=native)
    j_dt = _idx_dtype(max(k, 1))
    s_dt = _idx_dtype(n_slots)
    sections = (np.asarray(m_cols, np.uint16),
                np.asarray(m_counts, np.uint16),
                np.asarray(m_nops, np.uint16),
                np.asarray(m_slots, s_dt),
                np.asarray(m_funs, np.uint8),
                np.asarray(m_ops, np.int32),
                np.asarray(m_rl, j_dt),
                np.asarray(m_jl, j_dt))
    mcrc = 0
    mbytes = 0
    for s in sections:
        b = np.ascontiguousarray(s)
        mcrc = zlib.crc32(b.tobytes(), mcrc)
        mbytes += b.nbytes
    entry = (("m",) + d_entry[1:14] + (len(m_slots),)
             + tuple(wire.Raw(np.ascontiguousarray(s))
                     for s in sections)
             + (mcrc, meta, int(fid)))
    return (entry, _crc_chain(d_crc, mcrc), d_bytes + mbytes,
            len(m_slots), n_ops_total)


def build_full_entry(seq: int, k: int, want_vsn: bool,
                     elect: np.ndarray, lease_ok: np.ndarray,
                     kind: np.ndarray, slot: np.ndarray,
                     val: np.ndarray, exp_e: Optional[np.ndarray],
                     exp_s: Optional[np.ndarray],
                     meta: List[Tuple],
                     fid: int = 0) -> Tuple[Tuple, int]:
    """Full-plane fallback entry (re-executed by the replica through
    the plain launch halves — elections, corruption/exchange rounds
    and delta-ineligible shapes).  Planes ride as Raw buffers so even
    the fallback never concatenates them into an intermediate bytes.
    ``fid`` = the leader's obs flush id (see build_delta_entry).
    Returns ``(entry, plane_bytes)``."""

    def raw_i32(p):
        return (None if p is None
                else wire.Raw(np.ascontiguousarray(p, np.int32)))

    eb = np.packbits(np.asarray(elect, bool))
    lb = np.packbits(np.asarray(lease_ok, bool))
    nbytes = (eb.nbytes + lb.nbytes
              + sum(int(np.asarray(p).nbytes) for p in
                    (kind, slot, val) if p is not None)
              + sum(int(np.asarray(p).nbytes) for p in (exp_e, exp_s)
                    if p is not None))
    entry = ("f", int(seq), int(k), bool(want_vsn), wire.Raw(eb),
             wire.Raw(lb), raw_i32(kind), raw_i32(slot), raw_i32(val),
             raw_i32(exp_e), raw_i32(exp_s), meta, int(fid))
    return entry, nbytes


def full_plane_nbytes(k: int, n_ens: int, cas: bool) -> int:
    """What a full-plane entry's sections cost at [K, E]: the
    kind/slot/val planes (+ exp_e/exp_s only when the launch carried
    CAS expectations — matching what :func:`build_full_entry` would
    actually ship) + the elect/lease bit vectors — the denominator of
    the delta-savings meter."""
    planes = 5 if cas else 3
    return planes * k * n_ens * 4 + 2 * ((n_ens + 7) // 8)


def _crc_chain(acc: int, entry_crc: int) -> int:
    """Fold one entry's CRC into a batch's cumulative ack CRC (order-
    sensitive: a reordered or dropped entry cannot collide)."""
    return zlib.crc32(int(entry_crc).to_bytes(8, "big"), acc)


# -- replica-side apply ------------------------------------------------------

class ReplicaCore:
    """One host's lane + group metadata: the apply/install/promise
    logic shared by the standalone :class:`ReplicaServer` process and
    a :class:`ReplicatedService` acting as its own replica zero."""

    def __init__(self, svc: BatchedEnsembleService) -> None:
        self.svc = svc
        (self.promised, self.applied_ge, self.applied_seq,
         self.cfg) = load_group_meta(svc)
        self.last_crc = 0
        #: hook: the owning server mirrors config changes into its
        #: failover peer list (set by ReplicaServer)
        self.on_cfg = None
        #: commutative-lane early ack (docs/ARCHITECTURE.md §18): set
        #: per-frame by the owning server to a send-the-ack callable.
        #: A PURE-merge frame (every entry "m" with zero ordered
        #: cells, no grants) fires it right after its WAL sync —
        #: BEFORE the device scatter is even dispatched — because a
        #: crash between the two replays the run from the WAL's
        #: absolute-value records, the same recovery envelope the
        #: sequenced path already proves at replica_apply_pre_ack.
        self.early_ack = None
        self.early_acks = 0
        #: follower-served leased reads (docs/ARCHITECTURE.md §16):
        #: this lane may answer keyed reads from its delta-maintained
        #: mirrors until ``serve_until`` (monotonic, this host's
        #: clock).  The window derives ONLY from lease grants the
        #: leader ships inside abatch frames — each grant names the
        #: highest ack seq the leader counted inside a quorum-
        #: confirmed settle, and the window anchors at THIS lane's
        #: own send time of that ack (causally before the leader's
        #: receive, so the window always expires inside the leader's
        #: write fence for this address).
        self.serve_until = 0.0
        self.confirmed_seq = 0
        #: (seq, anchor_t, touched-cols-or-None) acks awaiting their
        #: grant; None cols = a full-plane entry (blocks every
        #: ensemble until confirmed)
        self._flw_anchors: "deque[Tuple[int, float, Any]]" = deque()
        self._flw_anchor_top = 0
        self._flw_unconf: set = set()
        self._flw_unconf_all = False
        #: per-frame collector the apply paths feed touched ensemble
        #: columns into (None while the frame carries no grants —
        #: the follower-reads-off arm records nothing)
        self._flw_collect: Optional[set] = None
        self._flw_collect_all = False

    # -- follower-served leased reads (docs/ARCHITECTURE.md §16) --------

    def _flw_drop(self) -> None:
        """Revoke the serve window and every pending anchor — called
        BEFORE this lane grants a higher promise, acks a config
        record, installs a snapshot, or steps into leadership, so no
        follower-served read can outlive the fencing event."""
        self.serve_until = 0.0
        self._flw_anchors.clear()
        self._flw_anchor_top = self.applied_seq
        self._flw_unconf = set()
        self._flw_unconf_all = False

    def _flw_note_ack(self, cols: Any, grants: Any) -> None:
        """Record the ack about to go on the wire as a lease anchor
        (the anchor time is taken BEFORE the send, so it lower-bounds
        the leader's receive time), then consume any grant addressed
        to this lane.  A grant for seq G proves the leader counted
        our FIRST ack for G inside a quorum-confirmed settle within
        that batch's ack deadline, so [anchor(G), anchor(G)+lease) is
        strictly inside the leader's write fence for this address;
        re-acks of an already-anchored seq are ignored (only the
        first instance is provably the one the settle counted)."""
        now = time.monotonic()
        if self.applied_seq > self._flw_anchor_top:
            self._flw_anchors.append((self.applied_seq, now, cols))
            self._flw_anchor_top = self.applied_seq
        me = getattr(self.svc, "self_addr", None)
        g = -1
        if grants and me is not None:
            for h, p, s in grants:
                if (str(h), int(p)) == me:
                    g = int(s)
                    break
        if g >= 0:
            best = None
            while self._flw_anchors and self._flw_anchors[0][0] <= g:
                best = self._flw_anchors.popleft()
            if best is not None:
                self.serve_until = max(
                    self.serve_until,
                    best[1] + self.svc.config.lease())
            self.confirmed_seq = max(self.confirmed_seq, g)
        # visibility gate: ensembles touched by applied-but-not-yet-
        # confirmed entries must not serve — a follower read could
        # otherwise observe a write BEFORE the leader's own settle
        # acks it, and a later leader read might miss it (the
        # time-travel anomaly)
        self._flw_unconf_all = any(a[2] is None
                                   for a in self._flw_anchors)
        cols_u: set = set()
        if not self._flw_unconf_all:
            for a in self._flw_anchors:
                cols_u.update(a[2])
        self._flw_unconf = cols_u

    def _flw_serve_ok(self, ens: int) -> bool:
        """May this follower answer a keyed read of ensemble ``ens``
        from its mirrors right now?  Window valid (with the same
        safety margin the leader-side fast path applies) AND no
        applied-but-unconfirmed entry touches the ensemble."""
        now = time.monotonic()
        if now + self.svc._read_margin >= self.serve_until:
            return False
        if self._flw_unconf_all or ens in self._flw_unconf:
            return False
        return True

    def _obs_role(self) -> str:
        """This lane's span-store role: "replica" plus the lane tag
        (the address peers dial it by) when one exists — in-process
        multi-lane groups share the process-global store, and
        untagged roles would merge three lanes' spans into one
        indistinguishable record."""
        addr = getattr(self.svc, "self_addr", None)
        if addr:
            return f"replica@{addr[0]}:{addr[1]}"
        return "replica"

    def handle_promise(self, ge: int) -> Tuple:
        """Grant iff strictly newer; the grant persists BEFORE it is
        answered (a granted promise that didn't survive a crash would
        let a deposed leader commit after our restart)."""
        meta_lock = getattr(self.svc, "_meta_lock", None)
        import contextlib
        with (meta_lock if meta_lock is not None
              else contextlib.nullcontext()):
            if ge > self.promised:
                # read-lease fence (§16): every follower-served read
                # window this lane holds dies BEFORE the grant goes
                # out — the new leader's first write can't race a
                # stale-lease read
                self._flw_drop()
                self.promised = ge
                save_group_meta(self.svc, self.promised,
                                self.applied_ge, self.applied_seq,
                                self.cfg)
                return ("promised", True, self.promised,
                        self.applied_ge, self.applied_seq, self.cfg)
            return ("promised", False, self.promised, self.applied_ge,
                    self.applied_seq, self.cfg)

    def handle_apply(self, frame: Tuple) -> Tuple:
        """Legacy single full-plane apply (kept on the wire for
        compatibility; the leader now ships ``abatch`` frames)."""
        _, ge, seq = frame[:3]
        bad = self._check_stream(ge, seq)
        if bad is not None:
            return bad
        # legacy frames predate the trailing flush-id field: no
        # leader-side trace to join, record under fid 0 (dropped)
        crc = self._apply_full_entry(
            ge, ("f",) + tuple(frame[2:]) + (0,))
        return ("applied", ge, seq, crc)

    def _check_stream(self, ge: int, seq: int) -> Optional[Tuple]:
        """The (epoch, seq) stream discipline shared by every apply
        shape; None = accept, else the response to send."""
        if ge != self.promised or ge < self.applied_ge:
            return ("nack", "epoch", self.promised, self.applied_ge,
                    self.applied_seq)
        if seq == self.applied_seq and ge == self.applied_ge:
            # retransmit of the batch we just applied (ack was lost)
            return ("applied", ge, seq, self.last_crc)
        if seq != self.applied_seq + 1:
            return ("nack", "seq", self.promised, self.applied_ge,
                    self.applied_seq)
        return None

    def handle_abatch(self, frame: Tuple) -> Tuple:
        """Coalesced launch batch: delta entries apply in place
        through ONE device scatter + mirror/WAL pass per contiguous
        run; full-plane entries re-execute through the plain launch
        halves.  One cumulative ack (the chained per-entry CRCs)
        covers the whole frame."""
        _, ge, entries = frame[:3]
        # optional 4th field (follower-read leader): sorted
        # (host, port, seq) grant triples — absent on the wire when
        # the leader runs with follower reads off, which keeps that
        # arm's frames byte-identical to HEAD
        grants = frame[3] if len(frame) > 3 else None
        self._flw_collect = set() if grants is not None else None
        self._flw_collect_all = False
        if ge != self.promised or ge < self.applied_ge:
            return ("nack", "epoch", self.promised, self.applied_ge,
                    self.applied_seq)
        if not entries:
            if grants is not None:
                self._flw_note_ack(set(), grants)
            return ("applied", ge, self.applied_seq, self.last_crc)
        if ge == self.applied_ge \
                and int(entries[-1][1]) <= self.applied_seq:
            # retransmit of a fully-applied batch (ack was lost);
            # anything partially behind is a protocol break — nack
            # and let the leader re-sync
            if int(entries[-1][1]) == self.applied_seq:
                if grants is not None:
                    # a re-ack never re-anchors (dedup by seq inside
                    # _flw_note_ack) but grants riding the frame
                    # still confirm earlier anchors
                    self._flw_note_ack(set(), grants)
                return ("applied", ge, self.applied_seq, self.last_crc)
            return ("nack", "seq", self.promised, self.applied_ge,
                    self.applied_seq)
        combined = 0
        # §18 early-ack gate: a frame that is ONE pure-merge run (every
        # entry "m" with zero ordered cells) and carries no grants may
        # ack after its WAL sync, before the device scatter — the
        # validation + meta-coverage conditions are re-checked inside
        # the run apply, which owns the durability barrier
        early_ok = (self.early_ack is not None and grants is None
                    and all(e[0] == "m" and int(e[3]) == 0
                            for e in entries))
        i, n = 0, len(entries)
        while i < n:
            ent = entries[i]
            if int(ent[1]) != self.applied_seq + 1:
                if grants is not None:
                    self._flw_drop()
                return ("nack", "seq", self.promised, self.applied_ge,
                        self.applied_seq)
            if ent[0] == "f":
                crc = self._apply_full_entry(ge, ent)
                combined = _crc_chain(combined, crc)
                i += 1
            elif ent[0] in ("d", "m"):
                # group only the CONSECUTIVE-seq prefix: a gap inside
                # the run must stop it so the next top-of-loop check
                # nacks "seq" with the in-order prefix applied
                j, nxt = i, self.applied_seq + 1
                while j < n and entries[j][0] in ("d", "m") \
                        and int(entries[j][1]) == nxt:
                    j += 1
                    nxt += 1
                crcs = self._apply_delta_run(
                    ge, entries[i:j],
                    early=early_ok and i == 0 and j == n)
                if crcs is None:
                    if grants is not None:
                        self._flw_drop()
                    return ("nack", "crc", self.promised,
                            self.applied_ge, self.applied_seq)
                for c in crcs:
                    combined = _crc_chain(combined, c)
                i = j
            else:
                if grants is not None:
                    self._flw_drop()
                return ("nack", "bad-entry", self.promised,
                        self.applied_ge, self.applied_seq)
        if grants is not None:
            # anchor this ack before it hits the wire; a full-plane
            # entry anywhere in the frame gates EVERY ensemble until
            # the leader confirms it (cols=None)
            self._flw_note_ack(
                None if self._flw_collect_all else self._flw_collect,
                grants)
        return ("applied", ge, self.applied_seq, combined)

    def _apply_delta_run(self, ge: int, run: Sequence[Tuple],
                         early: bool = False) -> Optional[List[int]]:
        """Apply consecutive changed-slot delta entries IN PLACE — no
        device re-execution.  Everything the full launch would have
        produced is derived from this lane's own (bit-equal) state:
        commit epochs from its ballot plane, commit seqs consecutive
        from its obj_seq_ctr, the touched rows' trees rebuilt from the
        scattered objects.  The WHOLE run lands through one device
        scatter, one tree rebuild and one WAL sync (the batched apply
        economics).  Returns per-entry CRCs, or None on a section-CRC
        or shape violation (the leader re-syncs).

        "m" entries (§18) additionally carry merge sections: coalesced
        commutative/semilattice cells folded against this lane's OWN
        current value through the compiled merge gather — a cell whose
        slot was already written earlier in the run folds host-side
        from that value instead (the device still holds the pre-run
        plane until the single end-of-run scatter).  ``early=True``
        (a pure-merge frame) reorders the tail to WAL -> ack -> scatter
        and fires ``self.early_ack`` with the frame's cumulative CRC,
        provided every merge cell is covered by a meta row (its final
        value must be in the WAL for the pre-scatter ack to be
        durable)."""
        svc = self.svc
        e_n = svc.n_ens
        epoch_np = np.asarray(svc.state.epoch[:, 0], np.int32)
        ctr_np = np.asarray(svc.state.obj_seq_ctr, np.int32).copy()
        final: Dict[Tuple[int, int], Tuple[int, int, int]] = {}
        touched = np.zeros((e_n,), bool)
        recs: List[Tuple[Any, Any]] = []
        crcs: List[int] = []
        now = svc.runtime.now
        lease_s = svc.config.lease()
        def _buf(x):
            return x.buf if isinstance(x, wire.Raw) else x

        # Validation pass: decode + vet EVERY entry of the run before
        # touching any state.  A mid-run failure after entry-by-entry
        # mutation would leave this lane advertising an applied
        # position (promise grants, campaign ranking) whose effects
        # were never scattered or WAL-logged — a would-be promoter
        # could adopt a state that silently lost acked writes.  All-
        # or-nothing keeps the advertised position truthful.
        t_start = time.perf_counter()
        decoded = []
        meta_covered = True
        for ent in run:
            merge_b = None
            try:
                if ent[0] == "m":
                    (_, seq, _k, nc, jw, sw, cols_b, counts_b, jj_b,
                     slots_b, vals_b, rmw_b, q_b, crc_ship, mc,
                     mcols_b, mcounts_b, mnops_b, mslots_b, mfuns_b,
                     mops_b, mrl_b, mjl_b, mcrc_ship, meta, fid) = ent
                    merge_b = (mcols_b, mcounts_b, mnops_b, mslots_b,
                               mfuns_b, mops_b, mrl_b, mjl_b)
                else:
                    (_, seq, _k, nc, jw, sw, cols_b, counts_b, jj_b,
                     slots_b, vals_b, rmw_b, q_b, crc_ship, meta,
                     fid) = ent
            except ValueError:
                return None
            if int(jw) not in (1, 2) or int(sw) not in (1, 2):
                return None
            j_dt = np.uint8 if int(jw) == 1 else np.uint16
            s_dt = np.uint8 if int(sw) == 1 else np.uint16
            try:
                cols = np.frombuffer(_buf(cols_b), np.uint16)
                counts = np.frombuffer(_buf(counts_b), np.uint16)
                jj = np.frombuffer(_buf(jj_b), j_dt)
                slots = np.frombuffer(_buf(slots_b), s_dt)
                vals = np.frombuffer(_buf(vals_b), np.int32)
                rmwb = np.frombuffer(_buf(rmw_b), np.uint8)
                qb = np.frombuffer(_buf(q_b), np.uint8)
            except ValueError:
                return None
            crc = 0
            for b in (cols, counts, jj, slots, vals, rmwb, qb):
                crc = zlib.crc32(b.tobytes(), crc)
            nc = int(nc)
            if crc != int(crc_ship) or jj.size != nc \
                    or slots.size != nc or vals.size != nc \
                    or cols.size != counts.size \
                    or int(counts.sum()) != nc \
                    or rmwb.size < (nc + 7) // 8 \
                    or qb.size < (e_n + 7) // 8 \
                    or (nc and (int(cols.min()) < 0
                                or int(cols.max()) >= e_n
                                or int(slots.min()) < 0
                                or int(slots.max()) >= svc.n_slots)):
                return None
            # put-lane metadata feeds the mirror/WAL mutation loop
            # below: vet shape and ensemble range up front too
            try:
                meta = [(int(j), int(e), key, handle, payload)
                        for j, e, key, handle, payload in meta]
            except (ValueError, TypeError):
                return None
            if any(e < 0 or e >= e_n for _, e, _k2, _h, _p in meta):
                return None
            merge = None
            ecrc = int(crc_ship)
            if merge_b is not None:
                # merge sections (§18): own CRC, all-or-nothing with
                # the run; the entry's ack CRC chains both halves
                try:
                    mcols = np.frombuffer(_buf(merge_b[0]), np.uint16)
                    mcounts = np.frombuffer(_buf(merge_b[1]),
                                            np.uint16)
                    mnops = np.frombuffer(_buf(merge_b[2]), np.uint16)
                    mslots = np.frombuffer(_buf(merge_b[3]), s_dt)
                    mfuns = np.frombuffer(_buf(merge_b[4]), np.uint8)
                    mops = np.frombuffer(_buf(merge_b[5]), np.int32)
                    mrl = np.frombuffer(_buf(merge_b[6]), j_dt)
                    mjl = np.frombuffer(_buf(merge_b[7]), j_dt)
                except ValueError:
                    return None
                mcrc = 0
                for b in (mcols, mcounts, mnops, mslots, mfuns, mops,
                          mrl, mjl):
                    mcrc = zlib.crc32(b.tobytes(), mcrc)
                mc = int(mc)
                mc64 = mcols.astype(np.int64)
                if (mcrc != int(mcrc_ship) or mc < 1
                        or mslots.size != mc or mfuns.size != mc
                        or mops.size != mc or mrl.size != mc
                        or mjl.size != mc
                        or mcols.size != mcounts.size
                        or mcols.size != mnops.size
                        or int(mcounts.sum()) != mc
                        or int(mcols.max()) >= e_n
                        or int(mslots.max()) >= svc.n_slots
                        or int(mfuns.max()) > funref.MERGE_OR
                        or not bool((mcounts >= 1).all())
                        or not bool((mnops.astype(np.int64)
                                     >= mcounts).all())
                        or int(mjl.max()) >= max(int(_k), 1)
                        or (mcols.size > 1
                            and not bool((np.diff(mc64) > 0).all()))
                        or np.intersect1d(mcols, cols).size
                        or not bool((mrl.astype(np.int64)
                                     < np.repeat(
                                         mnops.astype(np.int64),
                                         mcounts)).all())):
                    return None
                merge = (mcols, mcounts, mnops, mslots, mfuns, mops,
                         mrl, mjl)
                ecrc = _crc_chain(int(crc_ship), mcrc)
                if meta_covered:
                    # early-ack durability precondition: every merge
                    # cell's final value must land in the WAL via its
                    # meta row
                    je = {(j, e) for j, e, _k2, _h, _p in meta}
                    ccol = np.repeat(mcols, mcounts)
                    meta_covered = all(
                        (int(mjl[x]), int(ccol[x])) in je
                        for x in range(mc))
            decoded.append((int(seq), ecrc, cols, counts,
                            jj, slots, vals, rmwb, qb, meta,
                            int(fid), merge))
        t_validated = time.perf_counter()
        # §18 merge resolution: walk the run in order simulating slot
        # state so each merged cell folds against the value the
        # SEQUENCED apply would have seen — the compiled gather+merge
        # covers first-touch cells (device still pre-run), chained
        # cells fold host-side from the walk.
        mvals: List[Optional[np.ndarray]] = [None] * len(decoded)
        if any(d[11] is not None for d in decoded):
            simst: Dict[Tuple[int, int], Tuple] = {}
            chains: List[Tuple[int, int, List[Tuple]]] = []
            for d_i, d in enumerate(decoded):
                (_seq, _ec, cols, counts, jj, slots, vals, rmwb, qb,
                 meta, _fid, merge) = d
                pos = 0
                for c_i, cnt in zip(cols.tolist(), counts.tolist()):
                    for r_i in range(cnt):
                        simst[(c_i, int(slots[pos + r_i]))] = \
                            ("v", int(vals[pos + r_i]))
                    pos += cnt
                if merge is None:
                    continue
                (mcols, mcounts, mnops, mslots, mfuns, mops, mrl,
                 mjl) = merge
                mv = np.zeros(mslots.size, np.int32)
                mvals[d_i] = mv
                ccol = np.repeat(mcols, mcounts).tolist()
                for x in range(mslots.size):
                    c_i = int(ccol[x])
                    s_i = int(mslots[x])
                    mcls = int(mfuns[x])
                    op = int(mops[x])
                    st_ = simst.get((c_i, s_i))
                    if st_ is None:
                        chain: List[Tuple] = [(d_i, x, mcls, op)]
                        chains.append((c_i, s_i, chain))
                        simst[(c_i, s_i)] = ("p", chain)
                    elif st_[0] == "v":
                        v = funref.merge_apply(mcls, st_[1], op)
                        mv[x] = v
                        simst[(c_i, s_i)] = ("v", v)
                    else:
                        st_[1].append((d_i, x, mcls, op))
            if chains:
                head_vals = _merge_gather_cells(
                    svc,
                    np.asarray([c for c, _s, _ch in chains], np.int32),
                    np.asarray([s for _c, s, _ch in chains], np.int32),
                    np.asarray([ch[0][2] for _c, _s, ch in chains],
                               np.int32),
                    np.asarray([ch[0][3] for _c, _s, ch in chains],
                               np.int32))
                for (c_i, s_i, chain), hv in zip(chains,
                                                 head_vals.tolist()):
                    v = int(hv)
                    d_i, x, _mcls, _op = chain[0]
                    mvals[d_i][x] = v
                    for d_i2, x2, mcls2, op2 in chain[1:]:
                        v = funref.merge_apply(mcls2, v, op2)
                        mvals[d_i2][x2] = v

        # Apply pass: nothing below can fail validation — mutations
        # land for the whole run or not at all.
        for d_i, (seq, crc_ship, cols, counts, jj, slots, vals, rmwb,
                  qb, meta, _fid, merge) in enumerate(decoded):
            # committed cells, column-grouped in round order: derive
            # each cell's (epoch, seq) exactly as the kernel assigns
            # them (obj_sequence: consecutive per column)
            cell: Dict[Tuple[int, int],
                       Tuple[int, int, int, bool, int]] = {}
            pos = 0
            for c_i, cnt in zip(cols.tolist(), counts.tolist()):
                ep = int(epoch_np[c_i])
                base = int(ctr_np[c_i])
                for r_i in range(cnt):
                    idx = pos + r_i
                    s_i = int(slots[idx])
                    vl = int(vals[idx])
                    rm = bool(rmwb[idx >> 3] & (0x80 >> (idx & 7)))
                    final[(c_i, s_i)] = (ep, base + r_i + 1, vl)
                    cell[(int(jj[idx]), c_i)] = (ep, base + r_i + 1,
                                                 vl, rm, s_i)
                ctr_np[c_i] = base + cnt
                touched[c_i] = True
                pos += cnt
            if merge is not None:
                # merged columns (§18): each cell lands its FOLDED
                # value at the seq of its slot's last absorbed op
                # (base + rank + 1 — bit-equal version vectors), and
                # the column's counter advances by every op the cells
                # absorbed, exactly as the sequenced kernel would
                (mcols, mcounts, mnops, mslots, mfuns, mops, mrl,
                 mjl) = merge
                mv = mvals[d_i]
                pos = 0
                for c_i, cnt, nops in zip(mcols.tolist(),
                                          mcounts.tolist(),
                                          mnops.tolist()):
                    ep = int(epoch_np[c_i])
                    base = int(ctr_np[c_i])
                    for r_i in range(cnt):
                        idx = pos + r_i
                        s_i = int(mslots[idx])
                        vl = int(mv[idx])
                        sq = base + int(mrl[idx]) + 1
                        final[(c_i, s_i)] = (ep, sq, vl)
                        cell[(int(mjl[idx]), c_i)] = (ep, sq, vl,
                                                      True, s_i)
                    ctr_np[c_i] = base + nops
                    touched[c_i] = True
                    pos += cnt
            # keyed WAL records + host mirrors: the same meta-driven
            # iteration the full-plane path runs
            for j, e, key, handle, payload in meta:
                hit = cell.get((int(j), int(e)))
                if hit is None:
                    continue  # that round didn't commit
                ep, sq, vl, rm, s_i = hit
                if rm:
                    recs.append((("kv", e, s_i),
                                 (key, vl, ep, sq, None, True)))
                    self._mirror_inline(e, key, s_i, vl, ep, sq)
                else:
                    recs.append((("kv", e, s_i),
                                 (key, handle, ep, sq, payload, False)))
                    self._mirror_write(e, key, s_i, handle, payload,
                                       ep, sq)
            # lease renewal from the shipped quorum bits (the full
            # path's quorum_ok renewal, on this lane's own clock)
            renew = _unpack_bool(qb.tobytes(), e_n)
            svc.lease_until[renew] = now + lease_s
            self.applied_ge, self.applied_seq = int(ge), int(seq)
            self.last_crc = int(crc_ship)
            crcs.append(int(crc_ship))
        if self._flw_collect is not None:
            # follower-read visibility gate: these ensembles now hold
            # applied-but-unconfirmed writes
            self._flw_collect.update(np.nonzero(touched)[0].tolist())
        t_applied = time.perf_counter()
        marks: Dict[str, float] = {}

        def _scatter() -> None:
            if final:
                cells = np.asarray(
                    [(e, s, ep, sq, vl)
                     for (e, s), (ep, sq, vl) in final.items()],
                    np.int32)
                rows = np.zeros((e_n, svc.n_peers), bool)
                rows[touched] = True
                _delta_scatter_cells(svc, cells, ctr_np, rows,
                                     marks=marks if svc._obs else None)

        def _wal_sync() -> None:
            if svc._wal is not None:
                svc._wal.log(recs)
                if svc._wal.count >= svc.wal_compact_records:
                    rebuild_derived(svc)
                    svc.save()
                    save_group_meta(svc, self.promised,
                                    self.applied_ge, self.applied_seq,
                                    self.cfg)

        # Durability barrier: one log()/sync covers every entry of the
        # run + the advanced group meta, BEFORE the cumulative ack.
        recs.append((_GRP_KEY, (self.promised, self.applied_ge,
                                self.applied_seq, self.cfg)))
        if (early and self.early_ack is not None and meta_covered
                and svc._wal is not None):
            # §18 early ack: WAL first, ack on the wire, THEN the
            # device scatter dispatch.  A crash between ack and
            # scatter replays every merged final from the WAL's
            # absolute-value records — the same recovery point the
            # sequenced path proves below; the scatter was async-
            # dispatched before the ack anyway (never completion-
            # barriered), so the client-visible guarantee is
            # unchanged, only the wire ack stops waiting for the
            # dispatch.
            t_scattered = time.perf_counter()
            _wal_sync()
            t_wal = time.perf_counter() - t_scattered
            faults.crashpoint("replica_apply_pre_ack")
            combined = 0
            for c in crcs:
                combined = _crc_chain(combined, c)
            self.early_acks += 1
            self.early_ack(("applied", int(ge),
                            int(self.applied_seq), combined))
            _scatter()
        else:
            _scatter()
            t_scattered = time.perf_counter()
            _wal_sync()
            t_wal = time.perf_counter() - t_scattered
            # §15 crash barrier: the run is durable, the ack is not
            # yet on the wire — the classic replica-crash recovery
            # point
            faults.crashpoint("replica_apply_pre_ack")
        if svc._obs:
            # replica half of the cross-process flush trace: every
            # entry's spans record under the LEADER's flush id (the
            # wire's trailing field), so obs.timeline(fid) joins this
            # lane's validate/scatter/rebuild/WAL time with the
            # leader's enqueue/build/ship spans.  Run-shared passes
            # (validate, the one coalesced scatter + WAL sync) are
            # charged to the run and marked with its size.
            n_run = len(decoded)
            # fleet alignment anchor: spans lay out ENDING at this
            # record-time stamp on THIS host's monotonic clock (the
            # clock the leader's per-link offset estimate maps from)
            t_mono = time.monotonic()
            for (seq, _c, _cols, _cnt, _jj, _s, _v, _r, _q, _m,
                 fid, _mg) in decoded:
                obs.SPANS.record(
                    fid, self._obs_role(),
                    [("validate", t_validated - t_start),
                     ("apply", t_applied - t_validated),
                     ("scatter", marks.get("scatter", 0.0)),
                     ("rebuild", marks.get("rebuild", 0.0)),
                     ("wal_sync", t_wal)],
                    seq=seq, run_entries=n_run, kind="delta",
                    t_mono=t_mono)
        return crcs

    def _apply_full_entry(self, ge: int, ent: Tuple) -> int:
        (_, seq, k, want_vsn, elect_b, lease_b, kind_b, slot_b,
         val_b, exp_e_b, exp_s_b, meta, fid) = ent
        # full-plane entries can touch any ensemble — gate every
        # follower read until the leader confirms this frame
        self._flw_collect_all = True
        t_start = time.perf_counter()
        svc = self.svc
        e_n = svc.n_ens
        elect = _unpack_bool(elect_b, e_n)
        lease_ok = _unpack_bool(lease_b, e_n)
        kind = _unpack_i32(kind_b, (k, e_n))
        slot = _unpack_i32(slot_b, (k, e_n))
        val = _unpack_i32(val_b, (k, e_n))
        exp_e = _unpack_i32(exp_e_b, (k, e_n))
        exp_s = _unpack_i32(exp_s_b, (k, e_n))
        cand = np.zeros((e_n,), np.int32)
        # unbound base calls: a ReplicatedService in the replica role
        # must apply through the PLAIN launch halves (its own
        # overrides would try to re-replicate / demand leadership).
        # Active-column compaction composes transparently: the active
        # set is a pure function of the shipped kind plane, so this
        # lane packs/unpacks the SAME [K, A] layout its leader did —
        # and the unpack scatters back to full-width planes, so the
        # apply-stream mirrors, WAL records and the ack CRC below are
        # layout-blind (bit-identical across lanes even if one side
        # disabled compaction via RETPU_COMPACT=0).
        fl = BatchedEnsembleService._launch_enqueue(
            svc, kind, slot, val, k, want_vsn=want_vsn,
            exp_e=exp_e, exp_s=exp_s, elect=elect, cand=cand,
            lease_ok=lease_ok)
        committed, _get_ok, _found, value, vsn = \
            BatchedEnsembleService._launch_resolve(svc, fl)
        crc = result_crc(committed, vsn)
        t_applied = time.perf_counter()

        # Durability barrier: this host's WAL carries every committed
        # record of the batch BEFORE the ack that lets the leader
        # count us toward the commit quorum.  One log() call = one
        # sync for batch + group meta.
        recs: List[Tuple[Any, Any]] = []
        committed_l = committed.tolist() if committed is not None else []
        for j, e, key, handle, payload in meta:
            if not committed_l[j][e]:
                continue
            ve, vs = (int(vsn[j, e, 0]), int(vsn[j, e, 1])) \
                if vsn is not None else (0, 0)
            if int(kind[j, e]) == eng.OP_RMW:
                # device RMW lane: this lane COMPUTED the committed
                # value itself (bit-equal by determinism; the CRC
                # pins it) — log a keyed inline record and mark the
                # slot device-native
                v = int(value[j, e]) if value is not None else 0
                recs.append((("kv", e, int(slot[j, e])),
                             (key, v, ve, vs, None, True)))
                self._mirror_inline(e, key, int(slot[j, e]), v,
                                    ve, vs)
                continue
            recs.append((("kv", e, int(slot[j, e])),
                         (key, handle, ve, vs, payload, False)))
            self._mirror_write(e, key, int(slot[j, e]), handle,
                               payload, ve, vs)
        self.applied_ge, self.applied_seq = int(ge), int(seq)
        self.last_crc = crc
        recs.append((_GRP_KEY, (self.promised, ge, seq, self.cfg)))
        if svc._wal is not None:
            svc._wal.log(recs)
            if svc._wal.count >= svc.wal_compact_records:
                rebuild_derived(svc)
                svc.save()
                # save() rotated to an EMPTY WAL generation: the group
                # meta must survive into it, or a crash before the
                # next apply restarts this host amnesiac about its
                # promise — an old-epoch leader could then count it
                # into a quorum while the new-epoch leader commits
                # elsewhere (review r4: split-brain via compaction).
                save_group_meta(svc, self.promised, ge, seq, self.cfg)
        # §15 crash barrier: batch durable, ack not yet on the wire
        faults.crashpoint("replica_apply_pre_ack")
        if svc._obs:
            # the full-plane fallback's replica trace: one re-executed
            # launch, so "apply" covers the whole device round + local
            # resolve this lane ran under the leader's flush id
            obs.SPANS.record(
                fid, self._obs_role(),
                [("apply", t_applied - t_start),
                 ("wal_sync", time.perf_counter() - t_applied)],
                seq=int(seq), kind="full", t_mono=time.monotonic())
        return crc

    def _mirror_write(self, e: int, key: Any, slot: int, handle: int,
                      payload: Any, ve: int = 0, vs: int = 0) -> None:
        """Keep the keyed host mirrors live on the replica so a
        promoted leader can serve keyed ops — leased fast reads
        included (the vsn mirror rides along) — without a WAL
        rescan."""
        svc = self.svc
        svc._inline_slots[e].discard(slot)
        svc._inline_np[e, slot] = False
        svc._inline_value_ok[e, slot] = False
        svc._slot_vsn_np[e, slot] = (int(ve), int(vs))
        svc._slot_vsn_ok[e, slot] = True
        old = svc.slot_handle[e].pop(slot, 0)
        if old > 0 and old != handle:
            svc.values.pop(old, None)
        if handle:
            svc.values[handle] = payload
            svc.slot_handle[e][slot] = handle
            if key is not None:
                svc.key_slot[e][key] = slot
            if handle >= svc._next_handle:
                svc._next_handle = handle + 1
        else:
            if key is not None:
                svc.key_slot[e].pop(key, None)

    def _mirror_inline(self, e: int, key: Any, slot: int,
                       value: int, ve: int = 0, vs: int = 0) -> None:
        """Keyed mirror of a committed device RMW: the slot is
        device-native (value lives in the engine arrays; the -1
        slot_handle sentinel stands in for a live handle).  A
        computed 0 is the tombstone: the mapping DROPS, exactly like
        the host-delete mirror arm — the leader recycles the slot, so
        a retained replica mapping would alias the key onto whatever
        the recycled slot holds next (cross-key leak on promotion)."""
        svc = self.svc
        old = svc.slot_handle[e].pop(slot, 0)
        if old > 0:
            svc.values.pop(old, None)
        svc._slot_vsn_np[e, slot] = (int(ve), int(vs))
        svc._slot_vsn_ok[e, slot] = True
        if value:
            svc._inline_slots[e].add(slot)
            svc._inline_np[e, slot] = True
            svc._inline_value_np[e, slot] = int(value)
            svc._inline_value_ok[e, slot] = True
            svc.slot_handle[e][slot] = -1
            if key is not None:
                svc.key_slot[e][key] = slot
        else:
            svc._inline_slots[e].discard(slot)
            svc._inline_np[e, slot] = False
            svc._inline_value_ok[e, slot] = False
            if key is not None:
                svc.key_slot[e].pop(key, None)

    def handle_lcl(self, frame: Tuple) -> Tuple:
        """Replicated dynamic-lifecycle op (create/destroy ensemble):
        rides the SAME (epoch, seq) stream as applies — lifecycle
        mutates device rows and the tenant directory, so an
        unreplicated create would diverge the lanes.  Deterministic
        by the same induction: identical directories evolve
        identically, so row assignment and even failure outcomes
        (name taken, no capacity) match bit-for-bit."""
        _, ge, seq, kind, name, view_b = frame
        svc = self.svc
        if ge != self.promised or ge < self.applied_ge:
            return ("nack", "epoch", self.promised, self.applied_ge,
                    self.applied_seq)
        if seq == self.applied_seq and ge == self.applied_ge:
            return ("applied", ge, seq, self.last_crc)
        if seq != self.applied_seq + 1:
            return ("nack", "seq", self.promised, self.applied_ge,
                    self.applied_seq)
        # lifecycle records never carry grants and their mutations are
        # not anchor-gated — the read window dies with them (rare)
        self._flw_drop()
        if kind == "create":
            view = (None if view_b is None
                    else _unpack_bool(view_b, svc.n_peers))
            row = BatchedEnsembleService.create_ensemble(
                svc, name, view)
            crc = row if row is not None else -1
        else:
            ok = BatchedEnsembleService.destroy_ensemble(svc, name)
            crc = 1 if ok else 0
        self.applied_ge, self.applied_seq = ge, seq
        self.last_crc = crc
        save_group_meta(svc, self.promised, ge, seq, self.cfg)
        if svc._wal is not None \
                and svc._wal.count >= svc.wal_compact_records:
            rebuild_derived(svc)
            svc.save()
            save_group_meta(svc, self.promised, ge, seq, self.cfg)
        return ("applied", ge, seq, crc)

    def handle_install(self, frame: Tuple) -> Tuple:
        _, ge, seq, dump = frame[:4]
        if ge < self.promised:
            return ("nack", "epoch", self.promised, self.applied_ge,
                    self.applied_seq)
        # a snapshot install replaces the mirrors wholesale — any
        # outstanding read window is fenced out with it
        self._flw_drop()
        install_state(self.svc, dump)
        if len(frame) > 4:
            # the snapshot's config is part of the state at (ge, seq)
            self.set_cfg(_norm_cfg(frame[4]))
        self.promised = max(self.promised, ge)
        self.applied_ge, self.applied_seq = ge, seq
        self.last_crc = 0
        save_group_meta(self.svc, self.promised, ge, seq, self.cfg)
        if self.svc.data_dir is not None:
            # checkpoint the installed state so our own restart
            # restores it (save() rotates the WAL generation)
            self.svc.save()
            save_group_meta(self.svc, self.promised, ge, seq, self.cfg)
        return ("installed", ge, seq)

    def set_cfg(self, cfg: GroupCfg) -> None:
        self.cfg = cfg
        if self.on_cfg is not None:
            self.on_cfg(cfg)

    def handle_cfg(self, frame: Tuple) -> Tuple:
        """A group-config record riding the apply stream (the
        joint-consensus membership change, update_members
        peer.erl:655-672 / transition:751-774, at HOST granularity):
        same (epoch, seq) discipline as data applies, so every lane
        adopts each config at the same point in the op stream.  The
        ack CRC is the config version."""
        _, ge, seq, cver, hosts, joint = frame
        if ge != self.promised or ge < self.applied_ge:
            return ("nack", "epoch", self.promised, self.applied_ge,
                    self.applied_seq)
        if seq == self.applied_seq and ge == self.applied_ge:
            return ("applied", ge, seq, self.last_crc)
        if seq != self.applied_seq + 1:
            return ("nack", "seq", self.promised, self.applied_ge,
                    self.applied_seq)
        # read-lease fence (§16): no config record acks while this
        # lane could still serve reads under the OLD membership
        self._flw_drop()
        self.set_cfg((int(cver), _norm_addrs(hosts),
                      _norm_addrs(joint)))
        self.applied_ge, self.applied_seq = ge, seq
        self.last_crc = int(cver)
        save_group_meta(self.svc, self.promised, ge, seq, self.cfg)
        return ("applied", ge, seq, int(cver))

    def handle_pull(self) -> Tuple:
        rebuild_derived(self.svc)
        return ("state", self.applied_ge, self.applied_seq,
                dump_state(self.svc), self.cfg)

    def handle_inst(self, frame: Tuple) -> Tuple:
        """Replicated version-preserving install (tenant handoff on a
        repgroup owner): the LEADER's exact allocation
        (key, slot, handle, epoch, seq, payload) and leadership
        decision apply verbatim on this lane — independent
        allocation/leader choice could diverge the lanes.  Same
        (epoch, seq) stream discipline as data applies; the install's
        kv records and the advanced group meta land in ONE durability
        barrier (a crash between them must never advertise a
        regressed position over installed data)."""
        _, ge, seq, ens, lead, applied = frame
        if ge != self.promised or ge < self.applied_ge:
            return ("nack", "epoch", self.promised, self.applied_ge,
                    self.applied_seq)
        if seq == self.applied_seq and ge == self.applied_ge:
            return ("applied", ge, seq, self.last_crc)
        if seq != self.applied_seq + 1:
            return ("nack", "seq", self.promised, self.applied_ge,
                    self.applied_seq)
        applied = [tuple(a) for a in applied]
        crc = record_digest((a[1], a[2], a[3], a[4]) for a in applied)
        # version-preserving installs bypass the anchor gate — no
        # follower read may span one (rare: tenant handoff)
        self._flw_drop()
        self.applied_ge, self.applied_seq = int(ge), int(seq)
        self.last_crc = crc
        BatchedEnsembleService._apply_installed(
            self.svc, int(ens), applied, int(lead),
            extra_records=[(_GRP_KEY, (self.promised, int(ge),
                                       int(seq), self.cfg))])
        if self.svc._wal is not None \
                and self.svc._wal.count >= self.svc.wal_compact_records:
            rebuild_derived(self.svc)
            self.svc.save()
            save_group_meta(self.svc, self.promised, ge, seq, self.cfg)
        return ("applied", ge, seq, crc)

    # -- incremental (Merkle) catch-up ----------------------------------

    def handle_troots(self) -> Tuple:
        if self.svc.n_peers != 1:
            return ("error", "not-a-lane")
        return ("troots", self.applied_ge, self.applied_seq,
                tree_roots(self.svc).tobytes())

    def handle_tleaves(self, frame: Tuple) -> Tuple:
        _, rows = frame
        return ("tleaves", tree_leaves(self.svc,
                                       [int(r) for r in rows]).tobytes())

    def handle_tpatch(self, frame: Tuple) -> Tuple:
        """Targeted catch-up (the tree-exchange economics,
        synctree.erl:372-417 + exchange.erl:67-98): control-plane
        vectors + only the diverged slots' objects.  Valid ONLY
        against the exact frozen state the leader diffed — the
        ``expect`` guard nacks if this lane's (ge, seq) moved (it was
        applying after all, or a campaign intervened), and the leader
        falls back to the full snapshot."""
        import jax.numpy as jnp

        _, ge, seq, expect, meta, patches = frame
        svc = self.svc
        if ge < self.promised:
            return ("nack", "epoch", self.promised, self.applied_ge,
                    self.applied_seq)
        if (self.applied_ge, self.applied_seq) != \
                (int(expect[0]), int(expect[1])):
            return ("nack", "seq", self.promised, self.applied_ge,
                    self.applied_seq)
        if meta_dynamic(meta) != svc.dynamic:
            # reject before the FIRST mutation — see install_meta
            raise ValueError("lifecycle-mode mismatch in tree patch")
        if patches:
            e_j = jnp.asarray(np.asarray([p[0] for p in patches],
                                         np.int32))
            s_j = jnp.asarray(np.asarray([p[1] for p in patches],
                                         np.int32))
            eps = jnp.asarray(np.asarray([p[2] for p in patches],
                                         np.int32))
            sqs = jnp.asarray(np.asarray([p[3] for p in patches],
                                         np.int32))
            vls = jnp.asarray(np.asarray([p[4] for p in patches],
                                         np.int32))
            st = svc.state
            st = st._replace(
                obj_epoch=st.obj_epoch.at[e_j, 0, s_j].set(eps),
                obj_seq=st.obj_seq.at[e_j, 0, s_j].set(sqs),
                obj_val=st.obj_val.at[e_j, 0, s_j].set(vls))
            rows = np.zeros((svc.n_ens, svc.n_peers), bool)
            rows[np.unique([p[0] for p in patches])] = True
            svc.state = eng.rebuild_trees(st, jnp.asarray(rows))
            for e, s, ep, sq, vl, key, handle, payload in patches:
                self._mirror_patch(int(e), int(s), key, int(handle),
                                   payload, int(ep), int(sq), int(vl))
        # control-plane vectors land LAST (ADVICE r5): an exception
        # anywhere above leaves this lane's (ge, seq) markers — and
        # its ballot/view vectors — untouched, so the replica is
        # still a consistently-frozen nacker and the leader's
        # full-install fallback heals it, instead of a lane holding
        # the leader's control plane over its own object planes.
        install_meta(svc, meta)
        rebuild_derived(svc)
        self.promised = max(self.promised, int(ge))
        self.applied_ge, self.applied_seq = int(ge), int(seq)
        self.last_crc = 0
        save_group_meta(svc, self.promised, ge, seq, self.cfg)
        if svc.data_dir is not None:
            # checkpoint, as the full install does: a restart must
            # restore the patched state (save() rotates the WAL)
            svc.save()
            save_group_meta(svc, self.promised, ge, seq, self.cfg)
        return ("installed", ge, seq)

    def _mirror_patch(self, e: int, s: int, key: Any, handle: int,
                      payload: Any, ep: int = 0, sq: int = 0,
                      vl: int = 0) -> None:
        """One patched slot's keyed host mirrors: adopt the leader's
        (key, handle, payload) — key None means the slot is empty on
        the leader, so any local mapping is dropped.  handle -1 is the
        leader's device-native (inline RMW) sentinel: the value rides
        the patched engine arrays, not the payload store (the read
        fast path's inline mirror adopts it from the patch's value
        plane).  The slot's committed (epoch, seq) rides into the vsn
        mirror so a later promotion serves leased kget_vsn from it."""
        svc = self.svc
        old = svc.slot_handle[e].pop(s, 0)
        if old > 0 and old != handle:
            svc.values.pop(old, None)
        stale = [k for k, sl in svc.key_slot[e].items()
                 if sl == s and k != key]
        for k in stale:
            svc.key_slot[e].pop(k, None)
        svc._slot_vsn_np[e, s] = (int(ep), int(sq))
        svc._slot_vsn_ok[e, s] = True
        if handle == -1:
            svc._inline_slots[e].add(s)
            svc._inline_np[e, s] = True
            svc._inline_value_np[e, s] = int(vl)
            svc._inline_value_ok[e, s] = True
            svc.slot_handle[e][s] = -1
            if key is not None:
                svc.key_slot[e][key] = s
            return
        svc._inline_slots[e].discard(s)
        svc._inline_np[e, s] = False
        svc._inline_value_ok[e, s] = False
        if handle:
            svc.values[handle] = payload
            svc.slot_handle[e][s] = handle
            if key is not None:
                svc.key_slot[e][key] = s
            if handle >= svc._next_handle:
                svc._next_handle = handle + 1


# -- leader-side peer link ---------------------------------------------------

class _Encoded:
    """A frame wire-encoded ONCE, shippable to many links (the apply
    fan-out encodes per flush, not per replica)."""

    __slots__ = ("payload",)

    def __init__(self, value: Any) -> None:
        p = wire.encode(value)
        self.payload = _HDR.pack(len(p)) + p


class _EncodedParts:
    """A raw frame encoded ONCE as scatter-gather parts: the length
    header + term section, then the bulk numpy buffers UNCOPIED (the
    arrays stay alive through the part references until every link's
    sender has written them).  One encode per flush, one ``sendmsg``
    per link."""

    __slots__ = ("parts", "nbytes")

    def __init__(self, value: Any) -> None:
        parts = wire.encode_parts(value)
        total = sum(memoryview(p).nbytes for p in parts)
        self.parts = [_HDR.pack(total)] + parts
        self.nbytes = total


def _send_parts(sock: socket.socket, parts) -> None:
    """Scatter-gather send: every part goes to the kernel straight
    from its owning buffer (one syscall in the common case; partial
    sends and IOV_MAX overflow re-slice and continue)."""
    views = [memoryview(p).cast("B") for p in parts]
    while views:
        sent = sock.sendmsg(views[:512])
        while views and sent >= views[0].nbytes:
            sent -= views[0].nbytes
            views.pop(0)
        if views and sent:
            views[0] = views[0][sent:]


class _Ticket:
    __slots__ = ("event", "result", "posted", "fired", "on_done")

    def __init__(self, on_done=None) -> None:
        self.event = threading.Event()
        self.result: Any = None
        #: send time (re-stamped by the sender as the frame goes on
        #: the wire) — lets the receiver's idle-timeout handling tell
        #: a genuinely-overdue response (posted >= IO_TIMEOUT ago)
        #: from a request that arrived DURING the blocked recv
        self.posted = time.monotonic()
        #: completion time, stamped in _fire — the (posted, fired)
        #: pair brackets the remote's handling stamp, which is what
        #: the fleet plane's NTP-midpoint clock-offset estimation
        #: consumes (obs.fleet.ClockOffset; zero until fired)
        self.fired = 0.0
        #: completion hook (the batch settle's shared condition),
        #: attached at creation — BEFORE the frame can complete, so a
        #: wakeup can never be missed
        self.on_done = on_done

    def _fire(self) -> None:
        self.fired = time.monotonic()
        self.event.set()
        cb = self.on_done
        if cb is not None:
            try:
                cb()
            except Exception:
                pass


class _PendingEntry:
    """One resolved-but-unsettled flush riding the replication
    pipeline: its stream seq, its ack-CRC contribution, its wire
    entry, and (once the service's resolve hook claims it) the client
    futures + result planes to resolve when the batch's host-quorum
    outcome is known."""

    __slots__ = ("seq", "crc", "entry", "taken", "planes", "ack",
                 "ack_reads", "shipped_at", "fid", "op_planes",
                 "rec", "t_join", "lanes")

    def __init__(self, seq: int, crc: int, entry: Tuple,
                 shipped_at: float = 0.0, fid: int = 0) -> None:
        self.seq = seq
        self.crc = crc
        self.entry = entry
        #: obs flush id (cross-process tracing): the settle records
        #: the batch's ack span under it
        self.fid = fid
        self.taken: Optional[list] = None
        self.planes: Any = None
        #: host (kind, slot) op planes — the native mirror scatter's
        #: inputs, claimed with taken/planes and replayed at settle
        self.op_planes: Any = None
        #: the flush's flat op lanes (the slab enqueue path's
        #: completion-slab index) — replayed with the planes so the
        #: deferred resolve runs the same one-gather-per-plane path
        #: an unreplicated settle would
        self.lanes: Any = None
        #: the launch's latency record + flush-join time (obs): the
        #: deferred resolve replays them so the per-op SLO fold sees
        #: the true join→quorum-settle window and the slow-op tail
        #: gets its dominating flush mark
        self.rec: Any = None
        self.t_join = 0.0
        self.ack = True
        self.ack_reads = True
        #: runtime.now when the flush was enqueued — the base of any
        #: host-lease grant its settle may issue (the quorum contact
        #: is no fresher than the ship; granting from settle-
        #: processing time would stretch the leased-read window by
        #: the whole settle delay)
        self.shipped_at = shipped_at


class _PendingShip:
    """One shipped batch (coalesced frame) awaiting its cumulative
    acks: member entries in seq order, one ticket per link, and a
    shared condition so the settle wakes on EVERY ack as it lands —
    the quorum decision fires at majority time, not after the slowest
    link (nor after a wait-links-in-list-order slow prefix)."""

    __slots__ = ("entries", "sends", "deadline", "crc", "first_seq",
                 "cond", "ship_t", "shipped_at")

    def __init__(self, entries: List[_PendingEntry],
                 deadline: float) -> None:
        self.entries = entries
        self.first_seq = entries[0].seq
        crc = 0
        for e in entries:
            crc = _crc_chain(crc, e.crc)
        self.crc = crc
        self.sends = []
        self.deadline = deadline
        self.cond = threading.Condition()
        self.ship_t = time.monotonic()
        self.shipped_at = max(e.shipped_at for e in entries)

    def _notify(self) -> None:
        with self.cond:
            self.cond.notify_all()

    def _acked_now(self) -> set:
        """Addresses whose cumulative ack already matches (cheap
        snapshot, re-evaluated per wakeup)."""
        acked = set()
        for link, t in self.sends:
            if not t.event.is_set():
                continue
            r = t.result
            if r is not None and r[0] == "applied" \
                    and int(r[3]) == self.crc and not link.needs_sync:
                acked.add((link.host, link.port))
        return acked

    def wait_quorum(self, quorum_eval) -> None:
        """Block until a majority ack is in, every ticket completed,
        or the deadline passes — whichever is first (ack latency =
        time to majority, not max over links)."""
        with self.cond:
            while True:
                if all(t.event.is_set() for _l, t in self.sends):
                    return
                if quorum_eval(self._acked_now()):
                    return
                remaining = self.deadline - time.monotonic()
                if remaining <= 0:
                    return
                self.cond.wait(remaining)


class PeerLink:
    """Leader's connection to one replica host: a sender thread owning
    a blocking socket plus a receiver thread matching responses to
    tickets **in FIFO order** — the windowed (pipelined) link.  Any
    number of frames may be outstanding; the replica handles one
    connection sequentially (``_serve_repl_conn``), so responses come
    back in send order and a simple deque pairs them.  Automatic
    reconnect with handshake; a connection drop fails every
    outstanding ticket (result None).  A link that has ever
    missed/failed anything is ``needs_sync`` until an install
    succeeds — conservative, because an out-of-date replica acking
    nothing is merely slow, while an out-of-date replica counted into
    a quorum is data loss.

    Round 4 shipped this link as lockstep (one outstanding frame), so
    replication could never overlap across flushes — the leader idled
    a full RTT + replica-apply per flush (VERDICT r4 weak #5).  The
    FIFO window keeps per-link ORDER (the correctness requirement:
    installs queued ahead of applies, applies in seq order) while
    letting flush N+1's ship ride behind flush N's outstanding ack.
    """

    RECONNECT_DELAY = 0.2
    #: one summarized drop/reconnect line per link per interval: an
    #: active nemesis (or a genuinely flapping link) produces drops
    #: at frame rate, and a log line per drop would bury stderr
    LOG_INTERVAL = 5.0

    def __init__(self, host: str, port: int, get_epoch,
                 local_label: str = faults.LOCAL) -> None:
        self.host, self.port = host, port
        #: fault-plane endpoint names: directional rules against this
        #: link address the replica side as "host:port" and the
        #: leader side as ``local_label`` (default ``faults.LOCAL``;
        #: a service with several leaders in ONE process — in-process
        #: nemesis groups — sets a distinct ``fault_label`` per
        #: service so rules can target one leader's links)
        self.label = f"{host}:{port}"
        self.local = str(local_label)
        self._get_epoch = get_epoch
        self.connected = False
        self.needs_sync = True
        #: connection failures observed on this link (stats surface)
        self.drops = 0
        #: successful re-establishments after the first connect
        self.reconnects = 0
        #: frames/responses the fault plane blackholed on this link
        self.injected_drops = 0
        self._ever_connected = False
        self._last_drop_log = 0.0
        self._drops_unlogged = 0
        #: at most one in-flight state snapshot; consumed (not waited
        #: on) by a later flush — installs never block the commit path
        self.install_ticket: Optional[_Ticket] = None
        #: the pipeline seq the install was queued AHEAD of (ADVICE
        #: r5): _settle_batch may consume the ticket only for batches
        #: at-or-after this seq — consuming an install posted by a
        #: LATER flush would clear needs_sync, the current entry's
        #: nack would re-set it, and the NEXT entry's legitimate
        #: matching ack would be discounted (one redundant full
        #: re-sync per occurrence)
        self.install_barrier = 0
        #: per-link clock-offset estimator (the fleet plane): every
        #: obsq sideband round-trip feeds it (posted/remote/fired
        #: stamps), and fleet timelines/dumps read the estimate +
        #: bound to place this replica's spans on the leader's axis
        self.clock = obs.ClockOffset()
        #: in-flight tree-diff catch-up (probe thread output)
        self.sync: Optional["_TreeSync"] = None
        #: one tree-diff attempt per connection: a failed patch falls
        #: back to the full snapshot instead of looping
        self.tried_tree = False
        self.remote_state: Tuple[int, int, int] = (0, 0, 0)
        self._q: "queue.Queue[Optional[Tuple[Tuple, _Ticket]]]" = \
            queue.Queue()
        self._sock: Optional[socket.socket] = None
        self._stop = False
        #: tickets sent and awaiting their (in-order) responses
        self._awaiting: "deque[_Ticket]" = deque()
        self._alock = threading.Lock()
        #: connection generation: a receiver bound to a dead socket
        #: must not fail tickets of the NEXT connection
        self._gen = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def post(self, frame: Tuple, on_done=None) -> _Ticket:
        t = _Ticket(on_done)
        self._q.put((frame, t))
        return t

    @staticmethod
    def wait(ticket: _Ticket, deadline: float) -> Any:
        if ticket.event.wait(max(0.0, deadline - time.monotonic())):
            return ticket.result
        return None

    def close(self) -> None:
        self._stop = True
        self._q.put(None)
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # -- sender -------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop:
            item = self._q.get()
            if item is None:
                continue
            fp = faults.active_plan()
            if fp is not None \
                    and fp.should_swap(self.local, self.label):
                # bounded reorder: hold this frame and send the NEXT
                # queued one first (window of exactly two).  Tickets
                # ride their frames, so FIFO response pairing follows
                # the actual wire order; the replica's seq discipline
                # nacks the early frame and the re-sync path heals —
                # exactly the misordering the nemesis exists to drive.
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    self._send_one(item, fp)  # nothing to swap with
                    continue
                if nxt is None:
                    # close sentinel, not a frame: no swap happened —
                    # requeue it so the loop still terminates
                    self._q.put(None)
                    self._send_one(item, fp)
                    continue
                # only NOW did the wire order actually change
                fp.count_reorder(self.local, self.label)
                self._send_one(nxt, fp)
                self._send_one(item, fp)
                continue
            self._send_one(item, fp)

    def _send_one(self, item, fp) -> None:
        if item is None:
            # close sentinel consumed out of order (reorder stash):
            # put it back for the loop's own None handling
            self._q.put(None)
            return
        frame, ticket = item
        if fp is not None and fp.should_drop(self.local, self.label):
            # injected directional blackhole (leader→replica): the
            # frame never reaches the wire and the ticket never joins
            # _awaiting (pairing stays consistent).  Non-silent plans
            # fire the ticket unresolved NOW — the missed-ack outcome
            # at injection speed; silent plans leave it to the
            # caller's deadline (true blackhole timing).
            self.injected_drops += 1
            if not fp.silent:
                ticket._fire()
            return
        try:
            self._ensure_connected()
            # LOCAL capture: a concurrent receiver-side _drop sets
            # self._sock to None, and an AttributeError escaping
            # this try would kill the sender thread — a silently
            # dead link that never sends, fails, or resyncs again
            sock = self._sock
            if sock is None:
                raise ConnectionError("dropped mid-send")
            if fp is not None:
                # injected one-way request latency: sleeping the
                # sender delays this frame AND serializes behind it —
                # the in-order single-connection wire a slow link is
                d = fp.delay_s(self.local, self.label)
                if d > 0.0:
                    time.sleep(d)
            # append BEFORE send: the response cannot precede the
            # send, so the receiver always finds the ticket queued.
            # Re-stamp posted NOW — the ticket may have dwelled in
            # the sender queue behind a large install/patch
            # upload, and the receiver's overdue check must time
            # the wire wait, not the queue wait (a fresh request
            # read as overdue would drop a healthy link and force
            # the very re-sync the idle-timeout fix removed).
            with self._alock:
                ticket.posted = time.monotonic()
                self._awaiting.append(ticket)
            if isinstance(frame, _Encoded):
                sock.sendall(frame.payload)
            elif isinstance(frame, _EncodedParts):
                _send_parts(sock, frame.parts)
            else:
                send_frame(sock, frame)
        except (OSError, ConnectionError, wire.WireError,
                AttributeError):
            # the ticket may or may not have joined _awaiting;
            # _drop fails everything outstanding either way
            self._drop(fail_also=ticket)

    #: sentinel: the receive timed out before ANY byte arrived
    _IDLE = object()

    @classmethod
    def _recv_frame_or_idle(cls, sock: socket.socket):
        """``recv_frame`` that distinguishes an IDLE timeout (no byte
        of the next frame has arrived — benign on a quiet link) from a
        mid-frame timeout (bytes consumed, stream now desynced — a
        real failure).  Returns ``_IDLE`` for the former."""
        try:
            first = sock.recv(1)
        except socket.timeout:
            return cls._IDLE
        if not first:
            raise ConnectionError("peer closed")
        (length,) = _HDR.unpack(first + _recv_exact(sock,
                                                    _HDR.size - 1))
        if length > _MAX_FRAME:
            raise wire.WireError(f"frame too large: {length}")
        return wire.decode(_recv_exact(sock, length))

    def _recv_loop(self, sock: socket.socket, gen: int) -> None:
        while True:
            try:
                resp = self._recv_frame_or_idle(sock)
            except (OSError, ConnectionError, wire.WireError):
                if gen == self._gen:
                    self._drop()
                return
            if resp is self._IDLE:
                # Idle-socket timeout (IO_TIMEOUT with nothing in
                # flight): on a quiet link — a stepped-down ex-leader,
                # a leader with no client load and no heartbeat — this
                # fires every 120 s, and treating it as a link failure
                # forced a full re-sync reconnect each time (ADVICE
                # r5).  Benign when nothing is OVERDUE: no outstanding
                # response, or the oldest outstanding request was
                # posted DURING this blocked recv (its response hasn't
                # had IO_TIMEOUT to arrive yet — dropping would fail a
                # fresh request against a healthy peer).  A response
                # outstanding for a full IO_TIMEOUT (or a mid-frame
                # timeout) still drops the link — that peer really is
                # wedged; worst-case wedge detection is therefore
                # 2×IO_TIMEOUT.
                with self._alock:
                    oldest = (self._awaiting[0].posted
                              if self._awaiting else None)
                if gen != self._gen:
                    return
                if oldest is None or \
                        time.monotonic() - oldest < self.IO_TIMEOUT:
                    continue
                self._drop()
                return
            fp = faults.active_plan()
            if fp is not None:
                # injected one-way response latency (replica→leader):
                # sleep BEFORE pairing, so the ack lands late exactly
                # like a slow return path (later responses queue
                # behind it — the in-order wire again)
                d = fp.delay_s(self.label, self.local)
                if d > 0.0:
                    time.sleep(d)
            with self._alock:
                # a stale receiver (its connection already dropped and
                # replaced) must not consume the NEW connection's
                # tickets — the same off-by-one desync the reconnect
                # path guards against
                if gen != self._gen:
                    return
                t = self._awaiting.popleft() if self._awaiting else None
            if t is None:
                # a response with no outstanding request: protocol
                # corruption — drop the connection
                self._drop()
                return
            if fp is not None and fp.should_drop(self.label,
                                                 self.local):
                # injected directional blackhole on the RETURN path:
                # the request reached the replica (it may well have
                # applied!) but its ack vanishes — the genuinely
                # ambiguous asymmetry.  The ticket is consumed (the
                # pairing is real) but resolves to None: a missed
                # ack; silent plans don't even fire it.
                self.injected_drops += 1
                if not fp.silent:
                    t._fire()
                continue
            t.result = resp
            t._fire()

    #: per-operation socket timeout: generous enough for an install
    #: (state transfer + replica-side checkpoint), bounded so a
    #: SIGSTOP'd/partitioned peer can't wedge the worker forever
    IO_TIMEOUT = 120.0
    #: connect + HANDSHAKE budget: the whole establishment (TCP
    #: connect, hello, helloed) must finish inside this bound.  The
    #: handshake reply previously ran under IO_TIMEOUT (120 s) — a
    #: half-open peer (SYN accepted, nothing ever answers: a
    #: firewalled port, a SIGSTOP'd accept loop, a one-directional
    #: partition eating the response) wedged the sender thread for
    #: two minutes per attempt
    CONNECT_TIMEOUT = 10.0

    def _ensure_connected(self) -> None:
        if self.connected and self._sock is not None:
            return
        # (no separate injected-partition check here: _send_one — the
        # only caller — already short-circuits the same directional
        # drop rule before any socket work, so a dropped link never
        # reaches the connect path at all)
        # a FRESH connection must start with an EMPTY pairing queue:
        # a ticket whose send slipped in between a receiver-side
        # _drop clearing the deque and the socket actually dying can
        # linger here — if it survived into the new connection, the
        # first response would pop IT and desync every later
        # request/response pair on this link (off-by-one acks →
        # phantom CRC mismatches → a permanently unsyncable replica)
        with self._alock:
            dead = list(self._awaiting)
            self._awaiting.clear()
        for t in dead:
            t._fire()
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.CONNECT_TIMEOUT)
        # handshake runs lockstep on the fresh socket BEFORE the
        # receiver thread attaches (so its response is consumed
        # here), under the CONNECT budget — only an ESTABLISHED link
        # earns the generous IO_TIMEOUT
        self._sock.settimeout(self.CONNECT_TIMEOUT)
        send_frame(self._sock, ("hello", self._get_epoch()))
        resp = recv_frame(self._sock)
        if resp[0] != "helloed":
            raise ConnectionError(f"bad handshake: {resp!r}")
        self._sock.settimeout(self.IO_TIMEOUT)
        self.remote_state = (int(resp[1]), int(resp[2]), int(resp[3]))
        self.connected = True
        if self._ever_connected:
            self.reconnects += 1
        self._ever_connected = True
        # any (re)connect is conservative: re-sync before counting
        self.needs_sync = True
        self.tried_tree = False
        self._gen += 1
        threading.Thread(target=self._recv_loop,
                         args=(self._sock, self._gen),
                         daemon=True).start()

    def _drop(self, fail_also: Optional[_Ticket] = None) -> None:
        if not self._stop:
            # a deliberate close() tears the socket down too — that
            # is not a link FAILURE; only live drops count and log
            self.drops += 1
            self._log_drop()
        self.connected = False
        self.needs_sync = True
        self._gen += 1  # detach any receiver bound to the old socket
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        with self._alock:
            dead = list(self._awaiting)
            self._awaiting.clear()
        for t in dead:
            t._fire()
        if fail_also is not None:
            fail_also._fire()
        if not self._stop:
            time.sleep(self.RECONNECT_DELAY)

    def _log_drop(self) -> None:
        """Rate-limited link-failure logging: at most one SUMMARIZED
        stderr line per link per LOG_INTERVAL, carrying the count of
        drops since the last line — an active nemesis (or a real
        flapping link) failing at frame rate cannot spam stderr,
        while a quiet link's first failure still logs immediately.
        The full history rides ``drops``/``reconnects``/
        ``injected_drops`` in stats()."""
        self._drops_unlogged += 1
        now = time.monotonic()
        if now - self._last_drop_log < self.LOG_INTERVAL:
            return
        n, self._drops_unlogged = self._drops_unlogged, 0
        self._last_drop_log = now
        try:
            print(f"[repgroup] link {self.label}: connection dropped "
                  f"({n} drop(s), {self.injected_drops} injected, "
                  f"{self.reconnects} reconnects since start; "
                  f"retrying)", file=sys.stderr, flush=True)
        except Exception:
            pass  # a broken stderr must never take the link down

    def link_stats(self) -> Dict[str, Any]:
        """Per-link observability row (wire-encodable plain data):
        liveness + failure counters, and — while a fault plan is
        active — the ``injected`` section that lets an operator tell
        a running nemesis from a real outage."""
        out = {
            "host": self.host,
            "port": int(self.port),
            "connected": bool(self.connected),
            "synced": not self.needs_sync,
            "drops": int(self.drops),
            "reconnects": int(self.reconnects),
            "injected_drops": int(self.injected_drops),
        }
        fp = faults.active_plan()
        if fp is not None:
            inj = fp.link_injected(self.local, self.label)
            ret = fp.link_injected(self.label, self.local)
            inj["return_dropping"] = ret.pop("dropping")
            inj["return_rtt_ms"] = ret["rtt_ms"]
            inj["return_drops"] = ret["drops"]
            out["injected"] = inj
        return out


# -- the replicated service (leader role) ------------------------------------

class ReplicatedService(BatchedEnsembleService):
    """A batched service whose commit barrier spans OS-process failure
    domains.

    Construct with ``peers=[(host, port), ...]`` (the OTHER replica
    hosts' replication ports) and call :meth:`takeover` to establish
    leadership before serving.  Without peers it behaves as a plain
    single-lane service (the replica role drives it through
    :class:`ReplicaCore` instead).

    The client surface (kput/kget/... and the vectorized/batch forms)
    is inherited unchanged — replication happens inside ``_launch``,
    and the host-quorum outcome gates future resolution through
    ``_resolve_flush`` exactly like the local WAL barrier does.
    """

    def __init__(self, runtime, n_ens: int, n_peers: int = 1,
                 n_slots: int = 128, group_size: int = 1,
                 peers: Sequence[Tuple[str, int]] = (),
                 ack_timeout: float = 2.0,
                 install_timeout: float = 60.0,
                 repl_window: int = 4,
                 self_addr: Optional[Tuple[str, int]] = None,
                 trust_host_lease: bool = False,
                 fault_label: Optional[str] = None,
                 follower_reads: Optional[bool] = None,
                 **kw) -> None:
        # the (runtime, n_ens, n_peers, n_slots) positional prefix
        # matches the base class so restore() reconstructs us from a
        # persisted shape; the lane is always single-peer (the OTHER
        # peers are the group's hosts)
        assert n_peers == 1, "a replication-group lane has n_peers=1"
        kw.setdefault("tick", None)
        super().__init__(runtime, n_ens, 1, n_slots, **kw)
        assert group_size >= 1
        self.group_size = group_size
        self.ack_timeout = ack_timeout
        self.install_timeout = install_timeout
        #: serializes promise grants against a takeover's commit point
        #: (a promise granted mid-campaign must never be regressed by
        #: the campaign's own meta write)
        self._meta_lock = threading.Lock()
        #: this host's identity in group-config member lists (the
        #: address OTHER hosts dial it by; exact-match comparison).
        #: None = legacy implicit membership: the leader counts itself
        #: toward every quorum and update_members (host form) is
        #: unavailable until an identity exists.
        self.self_addr = (None if self_addr is None
                          else (str(self_addr[0]), int(self_addr[1])))
        self.core = ReplicaCore(self)
        if self.core.cfg[1] is not None:
            # a persisted explicit config wins over the constructor's
            # group_size (the set may have grown/shrunk since)
            self.group_size = len(self.core.cfg[1])
        #: in-progress membership transition (leader-side driver state)
        self._cfg_txn: Optional[Dict[str, Any]] = None
        self._ge = self.core.applied_ge
        self._grp_seq = self.core.applied_seq
        self._deposed = False
        self._is_leader = False
        self._last_quorum_ok = True
        #: lease-protected fast reads on a replication group are
        #: LEADER-ONLY and additionally gated on a HOST-side lease:
        #: renewed only by a settle whose host quorum confirmed this
        #: leader's epoch, zeroed the moment a settle loses the
        #: quorum or a higher promise is observed (a deposed leader
        #: invalidates before its next ack).  OPT-IN
        #: (``trust_host_lease=True``): unlike the device-lane lease,
        #: host promises are not time-fenced — a candidate may be
        #: granted a takeover at any moment, so a superseded-but-live
        #: leader could serve up to ``config.lease()`` of leased
        #: reads before its next settle observes the fencing.  The
        #: default keeps the strict reads-need-the-host-quorum
        #: barrier; opt in when the deployment's promotion discipline
        #: waits out the lease (docs/ARCHITECTURE.md §9).
        self.trust_host_lease = bool(trust_host_lease)
        self._host_lease_until = 0.0
        #: follower-served leased reads (docs/ARCHITECTURE.md §16).
        #: OFF by default — the off arm ships 3-field abatch frames
        #: byte-identical to HEAD.  On: every abatch frame carries the
        #: per-address grant table, settles renew per-replica read
        #: leases, and settle quorums exclude nothing — but a write
        #: cannot ACK while a non-acking replica still holds an
        #: unexpired lease (the write barrier that makes replica
        #: reads linearizable).
        self._follower_reads = (
            bool(follower_reads) if follower_reads is not None
            else os.environ.get("RETPU_FOLLOWER_READS", "0") == "1")
        #: highest ack seq granted per replica address (ships in every
        #: frame) and the leader-side write fence per address:
        #: fence[a] = settle time + lease, an upper bound on the
        #: replica's own window (its anchor predates our settle)
        self._flw_grants: Dict[Tuple[str, int], int] = {}
        self._flw_fence: Dict[Tuple[str, int], float] = {}
        #: fault-plane endpoint name for THIS leader's side of its
        #: links (docs/ARCHITECTURE.md §13).  Default "local"; tests
        #: hosting several leaders in one process pass distinct
        #: labels so directional rules can target one leader's
        #: links.  Constructor-peer links are built right below, so a
        #: non-default label must arrive via this parameter (a bare
        #: attribute assignment only affects links attached LATER —
        #: attach_peers / promote / config growth).
        self.fault_label = (str(fault_label) if fault_label is not None
                            else faults.LOCAL)
        self._links: List[PeerLink] = [
            PeerLink(h, p, lambda: self._ge,
                     local_label=self.fault_label) for h, p in peers]
        #: replication window: resolved-but-unsettled flush entries,
        #: oldest first; at most repl_window deep before the ship path
        #: blocks on the head batch (per-flush quorum barrier stands —
        #: futures resolve only at settlement).  Distinct from the
        #: base service's pipeline_depth (the DEVICE launch pipeline).
        self.repl_window = max(1, int(repl_window))
        # the runtime controller was constructed in the base __init__
        # before this attribute existed: its heal target for the
        # window knob is THIS constructor-configured value
        self._autotune_base_window = self.repl_window
        self._pending_flushes: "deque[_PendingShip]" = deque()
        self._unclaimed: Optional[_PendingEntry] = None
        #: resolved entries awaiting their coalesced ship (all the
        #: entries one flush settles ride ONE frame per link)
        self._ship_buf: List[_PendingEntry] = []
        #: tickets of batches settled at majority whose stragglers'
        #: outcomes (needs_sync, depose nacks) still need bookkeeping
        self._stragglers: List[Tuple[PeerLink, _Ticket, int]] = []
        #: changed-slot delta shipping (RETPU_REPL_DELTA=0 pins the
        #: full-plane frames — the A/B arm and operational escape
        #: hatch); int16 round/slot indices bound the eligible shape
        self._repl_delta = os.environ.get(
            "RETPU_REPL_DELTA", "1") != "0"
        #: reentrancy guard: shipping may drain the launch pipeline,
        #: whose settles call back into _drain_pending
        self._shipping = False
        self._delta_shape_ok = (n_slots <= 32767
                                and self.max_k <= 32767)
        #: replication observability
        self.group_stats = {"applies": 0, "quorum_failures": 0,
                            "resyncs": 0, "depositions": 0,
                            "tree_resyncs": 0, "tree_resync_bytes": 0,
                            "repl_delta_entries": 0,
                            "repl_full_entries": 0,
                            "repl_frames": 0,
                            "repl_bytes_shipped": 0,
                            "repl_bytes_sections": 0,
                            "repl_bytes_full_equiv": 0,
                            "repl_encode_s": 0.0,
                            "repl_build_s": 0.0,
                            "repl_ack_s": 0.0,
                            "repl_acked_batches": 0,
                            "follower_lease_write_blocks": 0,
                            "follower_reads_served": 0,
                            "follower_reads_blocked": 0}
        #: commutative-lane counters (docs/ARCHITECTURE.md §18) —
        #: kept OUT of group_stats so their metric names are the
        #: documented ``retpu_repl_*`` family, not auto-prefixed
        #: ``retpu_group_*`` rows
        self.comm_stats = {"repl_merge_entries": 0,
                           "repl_merge_cells": 0,
                           "repl_merge_ops": 0,
                           "repl_early_acks": 0}
        # group-level metrics join the service's registry (the
        # svcnode `metrics` verb and the docs ratchet see one plane)
        self.obs_registry.collect(self._obs_group_collect)
        #: the standing fleet anomaly watchdog (obs/watchdog.py):
        #: ALWAYS constructed so the retpu_watchdog_*/clock-offset
        #: gauge families register; it TICKS only while armed
        #: (RETPU_WATCHDOG, default on) AND this lane leads with
        #: links — the bench's fleet_obs_overhead off arm flips the
        #: knob and builds a fresh service, like every obs knob
        self.watchdog = obs.AnomalyWatchdog(self)
        self._watchdog_armed = self.watchdog.enabled and self._obs
        #: one-off obsq pulls (fleet verbs + correlated dumps) —
        #: counted apart from the watchdog's STANDING pulls, so a
        #: triggered dump on a RETPU_WATCHDOG=0 service never reads
        #: as the walker having run (source="verb" vs "watchdog")
        self.fleet_verb_pulls = 0
        self.fleet_verb_pull_failures = 0
        self.obs_registry.collect(self.watchdog.collect)

    def _obs_group_collect(self) -> Dict[str, Any]:
        def fam(typ, help, val):
            # the collector-family shape lives in obs.registry.family
            return obs.registry.family(typ, help, {None: val})

        out = {
            "retpu_group_is_leader": fam(
                "gauge", "1 while this lane leads its group",
                int(self.is_leader)),
            "retpu_group_epoch": fam(
                "gauge", "group epoch", self._ge),
            "retpu_group_seq": fam(
                "gauge", "applied stream position", self._grp_seq),
            "retpu_group_peers_connected": fam(
                "gauge", "links currently connected",
                sum(l.connected for l in self._links)),
            "retpu_group_peers_synced": fam(
                "gauge", "links not needing re-sync",
                sum(not l.needs_sync for l in self._links)),
            "retpu_group_pipeline_pending": fam(
                "gauge", "resolved-but-unsettled flush entries",
                self._outstanding()),
        }
        for key, val in self.group_stats.items():
            # every group_stats entry is cumulative-monotone (the
            # *_s entries are summed seconds) — counters all, so
            # PromQL rate()/increase() semantics apply uniformly
            out[f"retpu_group_{key}"] = fam(
                "counter",
                "replication group stat (see stats()['group'])",
                round(val, 6) if isinstance(val, float) else val)
        # commutative replication lane (§18): always registered, so
        # the RETPU_COMM_REPL=0 arm exports the same (zeroed) names
        cs = self.comm_stats
        out["retpu_repl_merge_cells"] = fam(
            "counter", "coalesced merge cells shipped (§18)",
            cs["repl_merge_cells"])
        out["retpu_repl_early_acks"] = fam(
            "counter",
            "pure-commutative entries settled on early acks (§18)",
            cs["repl_early_acks"])
        out["retpu_repl_merge_coalesce_ratio"] = fam(
            "gauge", "committed RMW ops absorbed per merge cell",
            round(cs["repl_merge_ops"]
                  / max(cs["repl_merge_cells"], 1), 6))
        return out

    # -- fleet-scope observability (docs/ARCHITECTURE.md §11) ---------------

    #: bounded budget for a synchronous fleet pull (the verbs and the
    #: correlated-dump hook; the watchdog's standing pulls are
    #: harvest-next-window and never wait at all)
    FLEET_PULL_TIMEOUT = 2.0

    def _obsq_result(self, link: PeerLink, ticket: _Ticket):
        """Unwrap one completed obsq ticket: feeds the link's clock
        estimator from the (posted, remote, fired) stamp triple and
        returns the payload — None for drops/timeouts/non-answers."""
        r = ticket.result
        if (isinstance(r, tuple) and len(r) >= 3
                and r[0] == "obsr"):
            if ticket.fired:
                link.clock.update(ticket.posted, float(r[1]),
                                  ticket.fired)
            return r[2]
        return None

    def _fleet_pull(self, subop: str, *args,
                    deadline_s: Optional[float] = None
                    ) -> Dict[str, Any]:
        """Post one ``obsq`` sideband request to EVERY link and wait
        (bounded) for the answers: ``{host_label: payload-or-None}``.
        The request rides the link's FIFO window behind any
        outstanding applies — ordered like everything else on the
        wire, no second connection, no second trust model."""
        tickets = [(link, link.post(("obsq", subop) + args))
                   for link in self._links]
        deadline = time.monotonic() + (
            self.FLEET_PULL_TIMEOUT if deadline_s is None
            else deadline_s)
        out: Dict[str, Any] = {}
        for link, t in tickets:
            PeerLink.wait(t, deadline)
            payload = self._obsq_result(link, t) \
                if t.event.is_set() else None
            if payload is None:
                self.fleet_verb_pull_failures += 1
            out[link.label] = payload
        self.fleet_verb_pulls += len(tickets)
        return out

    def _clock_section(self) -> Dict[str, Any]:
        return {l.label: l.clock.section() for l in self._links}

    def fleet_metrics(self, fmt: Optional[str] = None):
        """One answer for the whole group: every replica's registry
        pulled over the obsq sideband next to this leader's own.
        ``fmt="prometheus"`` merges the per-host renders into ONE
        scrape document with ``host`` labels (§11) — the federated
        scrape; otherwise a dict of per-host snapshots plus the
        clock-offset estimates the pull refreshed."""
        if not self._links:
            return super().fleet_metrics(fmt)
        label = self._fleet_self_label()
        if fmt == "prometheus":
            sections = {label: self.obs_registry.render_prometheus()}
            sections.update(self._fleet_pull("prometheus"))
            return obs.merge_prometheus(sections)
        hosts = {label: self.obs_registry.snapshot()}
        for h, snap in self._fleet_pull("metrics").items():
            hosts[h] = snap
        return {"schema": "retpu-fleet-metrics-v1", "hosts": hosts,
                "clock": self._clock_section()}

    def fleet_health(self) -> Dict[str, Any]:
        if not self._links:
            return super().fleet_health()
        hosts = {self._fleet_self_label(): self.health()}
        hosts.update(self._fleet_pull("health"))
        return {"schema": "retpu-fleet-health-v1", "hosts": hosts,
                "clock": self._clock_section()}

    def fleet_timeline(self, flush_id: int) -> Dict[str, Any]:
        """The clock-aligned cross-host timeline: this process's
        record for ``flush_id`` merged with every replica's pulled
        record, each role's spans placed on the LEADER's monotonic
        axis through the per-link offset estimates (honest to the
        per-role ``bound_ms``)."""
        if not self._links:
            return super().fleet_timeline(flush_id)
        fid = int(flush_id)
        sides: Dict[str, Any] = {}
        local = obs.SPANS.timeline(fid)
        missing = True
        if local and not local.get("miss"):
            missing = False
            sides.update({r: s for r, s in local.items()
                          if r != "flush_id"})
        for _host, payload in self._fleet_pull(
                "timeline", [fid]).items():
            tl = payload.get(fid) if isinstance(payload, dict) \
                else None
            if not isinstance(tl, dict) or tl.get("miss"):
                continue
            missing = False
            for role, side in tl.items():
                if role != "flush_id" and role not in sides:
                    sides[role] = side
        out = obs.align_timeline(fid, sides, self._clock_section(),
                                 self._fleet_self_label())
        if missing and local and local.get("miss"):
            out["miss"] = local["miss"]
        return out

    def _obs_flush_settled(self, fl) -> None:
        super()._obs_flush_settled(fl)
        # the standing watchdog rides the same settle hook as the
        # controller (observe-only sibling): leader-with-links only —
        # a replica lane has no links to pull and no acks to audit
        if self._watchdog_armed and self._links and self.is_leader:
            self.watchdog.tick(fl.flush_id)

    def _flight_extras(self) -> Dict[str, Any]:
        """Correlated flight dumps (schema v4): on top of the base
        sections, pull every replica's span records for the fids in
        THIS ring (bounded wait — dump writes are already rate-
        limited to one per ``min_dump_interval_s``) so the dump that
        says "this flush was 8× p50" also says which host's
        wal_sync/apply held it, on one aligned clock."""
        out = super()._flight_extras()
        out["watchdog_findings"] = self.watchdog.flight_section()
        if not (self._links and self.is_leader):
            return out
        fids = [int(r["flush_id"]) for r in self.flight.records
                if r.get("flush_id")][-32:]
        if not fids:
            return out
        hosts: Dict[str, Any] = {}
        for host, payload in self._fleet_pull(
                "timeline", fids,
                deadline_s=self.FLEET_PULL_TIMEOUT).items():
            if isinstance(payload, dict):
                hosts[host] = {"spans": {int(f): tl for f, tl
                                         in payload.items()}}
            else:
                hosts[host] = {"unreachable": True}
        out["hosts"] = hosts
        out["clock_offsets"] = self._clock_section()
        return out

    # -- leadership ---------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self._is_leader and not self._deposed

    def _fast_read_ok(self, ens: int, now: float):
        """Group-mode gate over the base lease fast path: replicas
        NEVER serve (leader-only), and a leader serves only inside a
        host-quorum lease — and only when the operator opted into
        trusting it (``trust_host_lease``); the default keeps every
        read behind the host-quorum round."""
        if self._links or self.group_size > 1:
            if not self.is_leader:
                return "not_leader"
            if not self.trust_host_lease:
                return "no_host_lease_trust"
            if self._host_lease_until <= now + self._read_margin:
                return "no_lease"
        return super()._fast_read_ok(ens, now)

    def attach_peers(self, peers: Sequence[Tuple[str, int]]) -> None:
        assert not self._links, "peers already attached"
        self._links = [PeerLink(h, p, lambda: self._ge,
                                local_label=self.fault_label)
                       for h, p in peers]

    def takeover(self, timeout: float = 30.0) -> bool:
        """Establish leadership: promise round to a majority, adopt
        the newest ``(epoch, seq)`` state among the grants, bump the
        group epoch.  Returns True on success; False when no majority
        granted (insufficient reachable replicas — the group cannot
        safely elect, exactly the minority-partition case)."""
        self._drain_launches()  # settle the device launch pipeline
        self._drain_pending(block_all=True)  # settle any prior reign
        deadline = time.monotonic() + timeout
        ge = max(self._ge, self.core.promised) + 1
        while time.monotonic() < deadline:
            # the campaign runs under the candidate's CURRENT config;
            # a grant (or the pulled state) carrying a newer config
            # re-runs the quorum check under THAT config below —
            # because configs ride the seq stream, any committed
            # config is held by at least one member of every majority
            # the old config admits (the Raft joint-consensus overlap
            # argument), so a stale candidate always discovers it
            self._ensure_cfg_links()
            tickets = [(l, l.post(("promise", ge))) for l in self._links]
            grants: List[Tuple[PeerLink, int, int, GroupCfg]] = []
            highest = ge
            for link, t in tickets:
                r = PeerLink.wait(t, min(deadline,
                                         time.monotonic()
                                         + self.ack_timeout))
                if r is None or r[0] != "promised":
                    continue
                granted, promised, age, aseq = r[1:5]
                rcfg = _norm_cfg(r[5]) if len(r) > 5 else NO_CFG
                highest = max(highest, int(promised))
                if granted:
                    grants.append((link, int(age), int(aseq), rcfg))
            # adopt the newest granted config (configs ride the seq
            # stream, so the best-by-(ge, seq) grant carries the max
            # cver among grants); self's own may still be newer
            best_cfg = max([self.core.cfg] + [g[3] for g in grants],
                           key=lambda c: c[0])
            if best_cfg[0] > self.core.cfg[0]:
                self.core.set_cfg(best_cfg)
                self._ensure_cfg_links()
            granted_addrs = {(g[0].host, g[0].port) for g in grants}
            if not self._campaign_quorum(granted_addrs):
                # keep trying until the deadline, always at a FRESH
                # epoch: this round's grants consumed the current one
                # (promises are strictly increasing), so re-proposing
                # it can never succeed (review r4)
                ge = max(highest, ge) + 1
                time.sleep(0.2)
                continue
            # adopt the newest state among the quorum (self included)
            best = max(grants, key=lambda g: (g[1], g[2]), default=None)
            if best is not None and \
                    (best[1], best[2]) > (self.core.applied_ge,
                                          self.core.applied_seq):
                link = best[0]
                t = link.post(("pull",))
                r = PeerLink.wait(t, time.monotonic()
                                  + self.install_timeout)
                if r is None or r[0] != "state":
                    # source died mid-pull; retry with a HIGHER epoch:
                    # the round's grants consumed this one (promises
                    # are strictly increasing), so re-proposing it
                    # could never gather a majority again (review r4)
                    ge += 1
                    continue
                age, aseq, dump = r[1], r[2], r[3]
                if len(r) > 4:
                    # the pulled state's config must be adopted (and
                    # its quorum re-validated) BEFORE the install
                    # mutates this lane — a failed check that had
                    # already installed would leave newer state under
                    # stale (applied_ge, applied_seq) markers, and the
                    # next winning round would reissue old seqs over it
                    pulled_cfg = _norm_cfg(r[4])
                    if pulled_cfg[0] > self.core.cfg[0]:
                        self.core.set_cfg(pulled_cfg)
                        self._ensure_cfg_links()
                        if not self._campaign_quorum(granted_addrs):
                            # re-campaign under the adopted config
                            # (fresh epoch)
                            ge = max(highest, ge) + 1
                            continue
                install_state(self, dump)
                self.core.applied_ge = int(age)
                self.core.applied_seq = int(aseq)
                # the source is NOT stale relative to us
                link.needs_sync = False
                link.remote_state = (ge, int(age), int(aseq))
            # Commit point, atomic vs concurrent promise grants: a
            # higher promise granted mid-campaign (another candidate
            # raced us) means we are already fenced — stand down
            # rather than regress the persisted promise.
            with self._meta_lock:
                if self.core.promised > ge:
                    return False
                self._ge = ge
                self._grp_seq = self.core.applied_seq
                self.core.promised = ge
                save_group_meta(self, ge, self.core.applied_ge,
                                self._grp_seq, self.core.cfg)
                self._deposed = False
                self._is_leader = True
                # a fresh reign starts lease-less: the first quorum-
                # confirmed settle grants the host read lease
                self._host_lease_until = 0.0
                # ...and grant-less: follower read leases issued by
                # the PREVIOUS reign are not ours to renew, and this
                # lane's own replica-role window dies with the reign
                self._flw_grants.clear()
                self._flw_fence.clear()
                self.core._flw_drop()
            # a persisted explicit config defines the quorum size now
            if self.core.cfg[1] is not None:
                self.group_size = len(self.core.cfg[1])
                # an interrupted transition resumes under this leader
                if self.core.cfg[2] is not None:
                    self._cfg_txn = {"new": list(self.core.cfg[2]),
                                     "joint_committed": False}
            # links whose promise reported our adopted (ge, seq) hold
            # bit-equal state (same applied prefix) — no re-sync
            for link, age, aseq, _rcfg in grants:
                if (age, aseq) == (self.core.applied_ge,
                                   self._grp_seq):
                    link.needs_sync = False
            if self._follower_reads:
                # §16 takeover fence: a member that did NOT grant our
                # promise may still hold a read lease from the old
                # reign (granting members dropped theirs inside
                # handle_promise, before the grant persisted).  Any
                # such lease anchors at an ack the OLD leader settled
                # within its batch deadline, so it expires within
                # ack_timeout + lease() of that settle — and no new
                # grant can issue once our majority promised (their
                # epoch nacks break the old leader's settle quorum).
                # Wait it out before this reign's first write acks.
                members = self._member_addrs() or [
                    (l.host, l.port) for l in self._links]
                ungranted = [a for a in members
                             if a != self.self_addr
                             and a not in granted_addrs]
                if ungranted:
                    time.sleep(self.ack_timeout + self.config.lease()
                               + self._read_margin)
            self._emit("grp_takeover", {"epoch": ge,
                                        "seq": self._grp_seq})
            return True
        return False

    # -- group configuration (dynamic host membership) ----------------------

    def _member_addrs(self) -> Optional[List[Tuple[str, int]]]:
        """The current committed member list, synthesized from links
        when running in legacy implicit mode (requires self_addr)."""
        if self.core.cfg[1] is not None:
            return list(self.core.cfg[1])
        if self.self_addr is None:
            return None
        return [self.self_addr] + [(l.host, l.port)
                                   for l in self._links]

    def _ensure_cfg_links(self) -> None:
        """Links must cover every address in the config (hosts and
        joint, minus self)."""
        _cver, hosts, joint = self.core.cfg
        self._ensure_cfg_links_for(
            list(hosts or ()) + [a for a in (joint or ())
                                 if a not in (hosts or ())])

    def _maj(self, members, voted) -> bool:
        votes = sum(1 for m in members
                    if m in voted or m == self.self_addr)
        return votes >= len(members) // 2 + 1

    def _quorum_from(self, acked_addrs) -> bool:
        """Commit quorum under the current config: legacy implicit
        mode counts acks against group_size (self included); explicit
        mode needs a majority of the member list, AND of the joint
        list during a transition (the multi-view AND)."""
        _cver, hosts, joint = self.core.cfg
        if hosts is None:
            return (1 + len(acked_addrs)) >= (self.group_size // 2 + 1)
        ok = self._maj(hosts, acked_addrs)
        if joint is not None:
            ok = ok and self._maj(joint, acked_addrs)
        return ok

    def _campaign_quorum(self, granted_addrs) -> bool:
        """Takeover quorum: same shape as the commit quorum (the
        overlap argument requires grant majorities and commit
        majorities to intersect per member list)."""
        return self._quorum_from(granted_addrs)

    def update_members(self, *args):
        """Membership change.

        **Host form** (replication group): ``update_members(hosts)``
        with ``hosts`` a sequence of ``(host, repl_port)`` addresses —
        the new member set, self_addr included if this leader stays a
        member.  Starts a joint-consensus transition (grow, shrink, or
        replace): the config record rides the apply stream, commits
        require majorities of BOTH old and new sets until the collapse
        record lands, and a joining host is never counted before its
        re-sync completes (the synced-before-counted rule).  The
        transition advances on subsequent flushes/heartbeats
        (:meth:`membership_status`); it is asynchronous, like the
        reference's update_members → leader_tick pipeline
        (peer.erl:655-672, 1199-1214).

        **View form** (single-lane mode only): the base class's
        ``update_members(sel, new_view)`` per-ensemble change.
        """
        if len(args) == 2:
            if self._links or self.group_size > 1:
                raise TypeError(
                    "per-ensemble views don't exist on a replication "
                    "group (the lane is single-peer); pass the new "
                    "host list: update_members([(host, port), ...])")
            return super().update_members(*args)
        (new_hosts,) = args
        new = [(str(h), int(p)) for h, p in new_hosts]
        if not self.is_leader:
            raise DeposedError("not the group leader")
        if self.self_addr is None:
            raise ValueError(
                "membership change needs this leader's identity: "
                "construct with self_addr=(host, port)")
        if self._cfg_txn is not None:
            raise RuntimeError(
                "a membership transition is already in progress")
        if len(new) < 1:
            raise ValueError("the group needs at least one member")
        current = self._member_addrs()
        if set(new) == set(current):
            return
        self._drain_launches()
        self._drain_pending(block_all=True)
        if self._follower_reads:
            # §16 config fence: no member may serve a follower read
            # under the OLD membership once config records start
            # acking.  Stop issuing grants (handle_cfg drops windows
            # on every acking member; _settle_batch won't grant while
            # _cfg_txn is set below) and wait out every outstanding
            # fence — after this, any lease we ever granted has
            # expired on the holder's clock too.
            self._flw_grants.clear()
            now_m = time.monotonic()
            wait = max([t - now_m for t in self._flw_fence.values()],
                       default=0.0)
            if wait > 0:
                time.sleep(wait + self._read_margin)
            self._flw_fence.clear()
        cver = self.core.cfg[0]
        if self.core.cfg[1] is None:
            # first explicit config: pin the CURRENT set at cver+1 so
            # every lane agrees what 'old' means before the joint
            # record references it
            if not self._commit_cfg(cver + 1, current, None):
                raise RuntimeError(
                    "no quorum to pin the current member set")
            cver += 1
        self._ensure_cfg_links_for(new)
        self._cfg_txn = {"new": new, "joint_committed": False}
        self._cfg_txn["joint_committed"] = \
            self._commit_cfg(cver + 1, current, new)
        self._advance_cfg()

    def _ensure_cfg_links_for(self, addrs) -> None:
        have = {(l.host, l.port) for l in self._links}
        for a in addrs:
            if a == self.self_addr or a in have:
                continue
            self._links.append(PeerLink(a[0], a[1],
                                        lambda: self._ge,
                                        local_label=self.fault_label))
            have.add(a)

    def membership_status(self) -> Dict[str, Any]:
        cver, hosts, joint = self.core.cfg
        return {"cver": cver,
                "hosts": None if hosts is None else list(hosts),
                "joint": None if joint is None else list(joint),
                "transition": self._cfg_txn is not None}

    def _replicate_record(self, frame: Tuple, crc: int) -> set:
        """Ship ONE synchronous replicated record (lifecycle, config,
        version-preserving install) and collect its acks: settle the
        pipeline, consume finished catch-up tickets, queue a snapshot
        ahead for any stale link (the write path's preamble
        discipline), post to the synced links, and return the acked
        address set — the caller judges the quorum and advances its
        own local state.  Shared by the three admin record kinds so
        the depose/needs-sync handling cannot drift between them."""
        enc = _Encoded(frame)
        snapshot = None
        for link in self._links:
            inst_t = link.install_ticket
            if inst_t is not None and inst_t.event.is_set():
                r = inst_t.result
                link.install_ticket = None
                if r is not None and r[0] == "installed":
                    link.needs_sync = False
                    link.tried_tree = False
                elif r is not None and r[0] == "nack" \
                        and int(r[2]) > self._ge:
                    self._note_depose(int(r[2]))
            if link.needs_sync and link.connected \
                    and link.install_ticket is None \
                    and link.sync is None:
                if snapshot is None:
                    snapshot = _Encoded(
                        ("install", self._ge, self._grp_seq,
                         dump_state(self), self.core.cfg))
                link.install_ticket = link.post(snapshot)
                # queued ahead of the NEXT stream record; only
                # settles at-or-after it may consume the ticket
                link.install_barrier = self._grp_seq + 1
                self.group_stats["resyncs"] += 1
        sends = [(l, l.post(enc)) for l in self._links
                 if not l.needs_sync]
        acked = set()
        deadline = time.monotonic() + self.ack_timeout
        for link, t in sends:
            r = PeerLink.wait(t, deadline)
            if r is not None and r[0] == "applied" \
                    and int(r[3]) == crc:
                acked.add((link.host, link.port))
            elif r is not None and r[0] == "nack" \
                    and r[1] == "epoch" and int(r[2]) > self._ge:
                self._note_depose(int(r[2]))
                link.needs_sync = True
            else:
                link.needs_sync = True
        self.group_stats["applies"] += 1
        return acked

    def _commit_cfg(self, cver: int, hosts, joint) -> bool:
        """Ship one config record through the apply stream and collect
        its acks synchronously (config changes are rare admin ops).
        The record is adopted locally FIRST — Raft's
        latest-config-in-the-log rule: the leader counts the commit
        under the config being written (for a joint record that is
        maj(old) AND maj(new); for the collapse record maj(new))."""
        self._drain_launches()
        self._drain_pending(block_all=True)
        seq = self._grp_seq + 1
        hosts_t = _norm_addrs(hosts)
        joint_t = _norm_addrs(joint)
        self._grp_seq = seq
        self.core.applied_ge = self._ge
        self.core.applied_seq = seq
        self.core.last_crc = int(cver)
        self.core.set_cfg((int(cver), hosts_t, joint_t))
        save_group_meta(self, self.core.promised, self._ge, seq,
                        self.core.cfg)
        acked = self._replicate_record(
            ("cfg", self._ge, seq, cver, hosts_t, joint_t), int(cver))
        ok = self._quorum_from(acked) and not self._deposed
        if not ok:
            self.group_stats["quorum_failures"] += 1
        self._emit("grp_cfg", {"cver": cver, "committed": ok,
                               "joint": joint_t is not None})
        return ok

    def _advance_cfg(self) -> None:
        """Drive an in-flight membership transition forward (called
        from flush/heartbeat, the leader_tick discipline): re-commit
        the joint record if its quorum was missed, then — once a
        majority of the NEW set is connected and fully synced — ship
        the collapse record, drop links to removed hosts, and adjust
        the quorum size.  A leader transitioning itself out steps
        down after the collapse commits (transition:756-774's
        shutdown-if-not-member)."""
        txn = self._cfg_txn
        if txn is None or not self.is_leader:
            return
        cver, hosts, joint = self.core.cfg
        if joint is None:
            # The collapse record is already adopted locally
            # (_commit_cfg adopts BEFORE counting — its quorum may
            # have been transiently missed, or a resumed transition
            # raced): the finalization must still run, or a leader
            # that transitioned itself out would keep serving and
            # removed links would never prune (review r5).
            self._finish_collapse()
            return
        if not txn["joint_committed"]:
            txn["joint_committed"] = self._commit_cfg(cver, hosts,
                                                      joint)
            if not txn["joint_committed"]:
                return
        # synced-before-counted collapse gate: a majority of the NEW
        # set must hold the full state (connected, not needs_sync)
        synced = {(l.host, l.port) for l in self._links
                  if l.connected and not l.needs_sync}
        if not self._maj(joint, synced):
            return
        if self._commit_cfg(cver + 1, joint, None):
            self._finish_collapse()

    def _finish_collapse(self) -> None:
        """Post-collapse finalization (idempotent): quorum size from
        the committed list, links to removed hosts pruned, and — the
        reference peer's shutdown-if-not-member (transition,
        peer.erl:756-774) — a leader that transitioned itself out
        steps down."""
        new = list(self.core.cfg[1])
        self._cfg_txn = None
        self.group_size = len(new)
        for link in list(self._links):
            if (link.host, link.port) not in new:
                link.close()
                self._links.remove(link)
        self._emit("grp_cfg_collapsed",
                   {"cver": self.core.cfg[0], "hosts": new})
        if self.self_addr is not None \
                and self.self_addr not in new:
            # transitioned out: stop serving (the reference peer
            # shuts down when not a member of the final view)
            self._is_leader = False
            self._deposed = True
            self._emit("grp_step_down", {"reason": "not-member"})

    # -- the replicated launch ----------------------------------------------

    def _launch_enqueue(self, kind, slot, val, k, want_vsn,
                        exp_e=None, exp_s=None, entries=None,
                        elect=None, cand=None, lease_ok=None):
        """Replicated ENQUEUE half: allocate the stream seq, capture
        the exact launch inputs for the ship, and dispatch the local
        launch through the base enqueue half.  The SHIP itself now
        rides the RESOLVE half (the delta transport needs the result
        planes to know what changed); the pipelined commit barrier is
        unchanged — acks are never awaited inline, and a
        ``pipeline_depth`` > 1 still overlaps device rounds with host
        resolve on a replication-group leader."""
        if not self._links and self.group_size == 1:
            return super()._launch_enqueue(kind, slot, val, k, want_vsn,
                                           exp_e, exp_s, entries, elect,
                                           cand, lease_ok)
        if not self.is_leader:
            raise DeposedError(
                "not the group leader (takeover() not run, or this "
                "epoch was superseded)")
        if elect is None:
            elect, cand = self._election_inputs()
        if lease_ok is None:
            lease_ok = self.lease_until > self.runtime.now

        # device-resident planes must be host arrays to ship
        import jax
        if isinstance(kind, jax.Array):
            kind = np.asarray(kind)
            slot = np.asarray(slot)
            val = np.asarray(val)
        seq = self._grp_seq + 1
        meta = _entries_meta(entries, kind, slot, self.values)
        corr0 = self.corruptions
        fl = super()._launch_enqueue(kind, slot, val, k, want_vsn,
                                     exp_e, exp_s, None, elect,
                                     cand, lease_ok)
        # the seq advances at ENQUEUE (later pipelined launches must
        # ship strictly increasing seqs); the core's applied position
        # advances only at resolve, in settle order.  An enqueue
        # failure consumed nothing — the stream has no gap.
        self._grp_seq = seq
        fl.grp_seq = seq
        fl.grp_meta = meta
        fl.grp_corr0 = corr0
        fl.grp_ship = (np.asarray(kind), np.asarray(slot),
                       np.asarray(val),
                       None if exp_e is None else np.asarray(exp_e),
                       None if exp_s is None else np.asarray(exp_s),
                       np.asarray(elect, bool),
                       np.asarray(lease_ok, bool))
        return fl

    def _launch_resolve(self, fl, wait_key="device_d2h"):
        """Replicated RESOLVE half: finish the local launch, build
        this flush's wire entry from the RESULT planes — the common
        changed-slot DELTA (payload proportional to what committed),
        or the full-plane fallback when the launch elected, hit
        corruption (its exchange mutated state beyond the results), or
        the shape is delta-ineligible — and buffer it for the
        coalesced ship.  Acks are NOT awaited here (the pipelined
        commit barrier, VERDICT r4 weak #5): the flush's client
        futures resolve only once its batch's host-quorum outcome is
        known (_settle_batch), while the NEXT flush's build, ship and
        local launch overlap this one's ack wait.  _resolve_flush
        claims this entry and attaches the futures/planes;
        heartbeat()-style direct launches leave taken=None."""
        if getattr(fl, "grp_ship", None) is None:
            # single-lane mode / replica role: the plain resolve
            return super()._launch_resolve(fl, wait_key)
        seq = fl.grp_seq
        try:
            out = super()._launch_resolve(fl, wait_key)
        except BaseException:
            # the local launch rolled back AFTER the stream consumed
            # this seq: nothing was shipped, but later ships arrive
            # with a seq gap — replicas nack and re-sync heals; mark
            # them now so the very next ship queues the install
            for link in self._links:
                link.needs_sync = True
            raise
        committed, _g, _f, value, vsn = out
        t0 = time.perf_counter()
        kind, slot, val, exp_e, exp_s, elect, lease_ok = fl.grp_ship
        meta = fl.grp_meta
        delta_ok = (self._repl_delta and self._delta_shape_ok
                    and self.n_peers == 1
                    and not bool(elect.any())
                    and self.corruptions == fl.grp_corr0)
        if delta_ok:
            comm = None
            if self._comm_repl:
                # §18 commutative fast lane: columns whose committed
                # cells are all mergeable RMWs ship coalesced merge
                # sections; anything else (including the knob-off
                # arm) falls through to the byte-identical plain
                # delta builder
                comm = build_comm_entry(
                    seq, fl.k, committed, value, kind, slot, val,
                    exp_e, fl.quorum_np, meta, n_slots=self.n_slots,
                    fid=fl.flush_id, native=self._native_resolve)
            if comm is not None:
                entry_t, crc, nbytes, n_cells, n_ops = comm
                self.comm_stats["repl_merge_entries"] += 1
                self.comm_stats["repl_merge_cells"] += n_cells
                self.comm_stats["repl_merge_ops"] += n_ops
            else:
                entry_t, crc, nbytes = build_delta_entry(
                    seq, fl.k, committed, value, kind, slot, val,
                    fl.quorum_np, meta, n_slots=self.n_slots,
                    fid=fl.flush_id, native=self._native_resolve)
            self.group_stats["repl_delta_entries"] += 1
        else:
            entry_t, nbytes = build_full_entry(
                seq, fl.k, fl.want_vsn, elect, lease_ok, kind, slot,
                val, exp_e, exp_s, meta, fid=fl.flush_id)
            crc = result_crc(committed, vsn)
            self.group_stats["repl_full_entries"] += 1
        self.group_stats["repl_bytes_sections"] += nbytes
        self.group_stats["repl_bytes_full_equiv"] += \
            full_plane_nbytes(fl.k, self.n_ens, cas=exp_e is not None)
        self.group_stats["repl_build_s"] += time.perf_counter() - t0
        fl.rec["repl_build"] = time.perf_counter() - t0
        self.core.applied_ge = self._ge
        self.core.applied_seq = seq
        self.core.last_crc = crc
        entry = _PendingEntry(seq, crc, entry_t, shipped_at=fl.now,
                              fid=fl.flush_id)
        self._ship_buf.append(entry)
        self._unclaimed = entry
        self.group_stats["applies"] += 1
        # Group meta persists via _wal_extra_records inside the flush's
        # own durability barrier (one sync, and atomically with the kv
        # records — a leader restart must never see data-bearing kv
        # records from a seq its meta doesn't cover, or takeover could
        # adopt an older replica state over its own acked writes).
        # Data-less launches (heartbeats, pure reads) skip it: adopting
        # a state that differs only by empty batches loses nothing.
        return out

    def _ship_now(self) -> None:
        """Coalesce every buffered entry into ONE raw frame and post
        it to every synced link — one encode, one scatter-gather write
        per replica per flush.  A link needing re-sync gets its
        catch-up queued INSTEAD of the batch (the catch-up lands the
        very state the batch produced, so sending both would
        double-apply); the ship never blocks on it — the outcome is
        consumed on a later ship/settle, and at most one install/patch
        is in flight per link (a slow replica must not stall every
        client future for install_timeout, nor accrue redundant
        snapshots — review r4).  Catch-up prefers the tree-diff patch
        (O(diffs)); the full snapshot remains the fallback for heavy
        divergence, non-frozen replicas, and any probe/patch failure.
        Catch-up state is dumped only with the launch pipeline drained
        (an enqueued-unresolved launch's effects are already in the
        device arrays, and a snapshot stamped behind them would make
        the next batch double-apply on the installed replica)."""
        if self._shipping or not self._ship_buf:
            return
        need_catchup = any(
            link.connected and link.install_ticket is None
            and (link.needs_sync or (link.sync is not None
                                     and link.sync.result is not None))
            for link in self._links)
        if need_catchup and self._inflight_launches:
            self._shipping = True
            try:
                self._drain_launches()
            finally:
                self._shipping = False
        entries = self._ship_buf
        self._ship_buf = []
        first_seq = entries[0].seq
        t0 = time.perf_counter()
        if self._follower_reads:
            # §16: piggyback the grant table — each replica reads its
            # own row (the highest of ITS acks counted inside a
            # quorum-confirmed settle).  One shared encoding still
            # serves every link; sorted for deterministic bytes.
            grants = tuple(sorted(
                (h, p, s) for (h, p), s in self._flw_grants.items()))
            enc = _EncodedParts(
                ("abatch", self._ge, [e.entry for e in entries],
                 grants))
        else:
            enc = _EncodedParts(
                ("abatch", self._ge, [e.entry for e in entries]))
        self.group_stats["repl_encode_s"] += time.perf_counter() - t0
        self.group_stats["repl_frames"] += 1
        self.group_stats["repl_bytes_shipped"] += enc.nbytes
        batch = _PendingShip(entries,
                             time.monotonic() + self.ack_timeout)
        snapshot_frame = None
        synced_pos = self.core.applied_seq  # == entries[-1].seq

        def full_install(link) -> None:
            nonlocal snapshot_frame
            if snapshot_frame is None:
                snapshot_frame = _Encoded(
                    ("install", self._ge, synced_pos,
                     dump_state(self), self.core.cfg))
            link.install_ticket = link.post(snapshot_frame)
            link.install_barrier = synced_pos + 1  # next batch counts
            self.group_stats["resyncs"] += 1

        for link in self._links:
            inst_t = link.install_ticket
            if inst_t is not None and inst_t.event.is_set():
                r = inst_t.result
                link.install_ticket = None
                if r is not None and r[0] == "installed":
                    link.needs_sync = False
                    link.tried_tree = False
                elif r is not None and r[0] == "nack" \
                        and int(r[2]) > self._ge:
                    self._note_depose(int(r[2]))
            sync = link.sync
            if sync is not None and sync.result is not None \
                    and link.install_ticket is None \
                    and not self._inflight_launches:
                # (a probe finishing between the drain above and here
                # stays pending one ship: patch/install state must be
                # dumped at the resolved position only)
                link.sync = None
                if sync.result == "patch" and link.connected:
                    patch = self._build_patch(sync)
                    sync.bytes += len(patch.payload)
                    link.install_ticket = link.post(patch)
                    link.install_barrier = synced_pos + 1
                    self.group_stats["tree_resyncs"] += 1
                    self.group_stats["tree_resync_bytes"] += sync.bytes
                elif link.connected:
                    full_install(link)
            elif link.needs_sync and link.connected \
                    and link.install_ticket is None \
                    and link.sync is None \
                    and not self._inflight_launches:
                if self._tree_sync_eligible(link):
                    link.tried_tree = True
                    link.sync = _TreeSync()
                    threading.Thread(target=self._tree_sync_probe,
                                     args=(link, link.sync),
                                     daemon=True).start()
                else:
                    full_install(link)
            # a needs_sync link joins the batch as soon as the batch
            # starts PAST its queued catch-up (install_barrier <=
            # first_seq): the link's FIFO delivers the install/patch
            # first, the replica lands exactly at first_seq - 1, and
            # the batch applies cleanly — its ack becomes countable
            # the moment the settle consumes the install ticket (the
            # ADVICE r5 adjacency, kept under coalescing).  The ship
            # that QUEUED the catch-up must exclude it (that batch's
            # seqs are already inside the snapshot — sending both
            # would read as a diverged retransmit and loop the
            # re-sync); so must ships while a probe is still running
            # (the tree diff needs the replica frozen).
            if not link.needs_sync \
                    or (link.install_ticket is not None
                        and link.install_barrier <= first_seq):
                batch.sends.append(
                    (link, link.post(enc, on_done=batch._notify)))
            elif not link.connected and link.install_ticket is None \
                    and link.sync is None:
                # a dropped link reconnects by CONSUMING a queued
                # frame (the sender thread owns the socket); excluded
                # from the batch, it still needs a nudge or it would
                # never dial back in — the cheap handshake serves
                # (its response is consumed FIFO and ignored)
                link.post(("hello", self._ge))
        self._pending_flushes.append(batch)

    def _settle_execute(self, fl, planes):
        """Bulk execute_async resolves directly to its caller (no
        host-quorum gate, matching the sync ``execute`` contract on a
        replicated leader); the pending entry this launch stashed
        settles with nothing to claim — but it must still settle, or
        a pure execute_async workload would grow _pending_flushes
        unboundedly and defer the ack-side bookkeeping (needs_sync,
        depose detection) indefinitely."""
        self._unclaimed = None
        out = super()._settle_execute(fl, planes)
        self._drain_pending(down_to=self.repl_window)
        return out

    # -- incremental (Merkle) catch-up: leader side -------------------------

    #: skip to the full snapshot when more than this fraction of
    #: ensembles diverged (the patch would approach the snapshot's
    #: size with a chattier protocol)
    TREE_SYNC_MAX_DIFF = 0.5

    def _tree_sync_eligible(self, link: PeerLink) -> bool:
        """Tree-diff catch-up needs a FROZEN replica: one strictly
        behind this leader's applied position, so it nacks the apply
        stream and its state holds still between the probe and the
        patch (the expect guard catches the rest).  One attempt per
        connection; single-peer lanes only."""
        if self.n_peers != 1 or link.tried_tree:
            return False
        _prom, rge, rseq = link.remote_state
        return (rge, rseq) < (self.core.applied_ge,
                              self.core.applied_seq)

    def _tree_sync_probe(self, link: PeerLink,
                         sync: "_TreeSync") -> None:
        """Background diff descent against one frozen replica: roots
        for every ensemble, then leaf planes for the diverged rows
        only (O(width·height·diffs) traffic, synctree.erl:372-417).
        Never blocks the commit path — the flush preamble consumes
        ``sync.result``."""
        dbg = os.environ.get("RETPU_DEBUG_SYNC") == "1"
        try:
            if dbg:
                print(f"[sync] probe start {link.host}:{link.port}",
                      file=sys.stderr, flush=True)
            probe_budget = min(self.install_timeout, 15.0)
            t = link.post(("troots",))
            r = PeerLink.wait(t, time.monotonic() + probe_budget)
            if dbg:
                print(f"[sync] troots -> "
                      f"{None if r is None else r[0]}",
                      file=sys.stderr, flush=True)
            if r is None or r[0] != "troots":
                raise RuntimeError(f"troots: {r!r}")
            sync.expect = (int(r[1]), int(r[2]))
            sync.bytes += len(r[3])
            remote = np.frombuffer(r[3], np.uint32).reshape(
                self.n_ens, -1)
            sync.remote_roots = remote
            local = tree_roots(self)
            diff = np.nonzero((remote != local).any(axis=1))[0]
            if len(diff) > self.n_ens * self.TREE_SYNC_MAX_DIFF:
                sync.result = "full"
                return
            if len(diff):
                t = link.post(("tleaves",
                               [int(e) for e in diff]))
                r = PeerLink.wait(t, time.monotonic() + probe_budget)
                if r is None or r[0] != "tleaves":
                    raise RuntimeError(f"tleaves: {r!r}")
                sync.bytes += len(r[1])
                remote_l = np.frombuffer(r[1], np.uint32).reshape(
                    len(diff), self.n_slots, -1)
                for i, e in enumerate(diff):
                    sync.remote_leaves[int(e)] = remote_l[i]
            sync.result = "patch"
        except Exception:
            sync.result = "full"

    def _build_patch(self, sync: "_TreeSync") -> _Encoded:
        """Build the targeted patch in the flush preamble — atomic
        with the apply stream: it carries state @ self._grp_seq and is
        posted immediately ahead of the seq+1 apply, so a frozen
        replica lands exactly in sync (the same adjacency the full
        install relies on).  The diff re-checks the CURRENT leader
        roots against the replica's cached (frozen) tree, so every
        leader-side mutation since the probe — epoch rewrites on reads
        included — is covered: rows whose cached leaves are stale ship
        whole."""
        import jax.numpy as jnp

        roots_now = tree_roots(self)
        diff_rows = np.nonzero(
            (roots_now != sync.remote_roots).any(axis=1))[0]
        pairs: List[Tuple[int, int]] = []
        if len(diff_rows):
            leaves_now = np.asarray(
                self.state.tree_leaf[
                    jnp.asarray(np.asarray(diff_rows, np.int32)),
                    0], np.uint32)
            for i, e in enumerate(diff_rows):
                cached = sync.remote_leaves.get(int(e))
                if cached is None:
                    slots = range(self.n_slots)
                else:
                    slots = np.nonzero(
                        (leaves_now[i] != cached).any(axis=1))[0]
                pairs += [(int(e), int(s)) for s in slots]
        patches: List[Tuple] = []
        if pairs:
            e_j = jnp.asarray(np.asarray([p[0] for p in pairs],
                                         np.int32))
            s_j = jnp.asarray(np.asarray([p[1] for p in pairs],
                                         np.int32))
            eps = np.asarray(self.state.obj_epoch[e_j, 0, s_j],
                             np.int32)
            sqs = np.asarray(self.state.obj_seq[e_j, 0, s_j],
                             np.int32)
            vls = np.asarray(self.state.obj_val[e_j, 0, s_j],
                             np.int32)
            rev: Dict[int, Dict[int, Any]] = {}
            for (e, s), ep, sq, vl in zip(pairs, eps, sqs, vls):
                r = rev.get(e)
                if r is None:
                    r = rev[e] = {sl: k for k, sl
                                  in self.key_slot[e].items()}
                key = r.get(s)
                handle = self.slot_handle[e].get(s, 0)
                payload = (self.values.get(handle)
                           if handle else None)
                patches.append((e, s, int(ep), int(sq), int(vl),
                                key, int(handle), payload))
        return _Encoded(("tpatch", self._ge, self._grp_seq,
                         sync.expect, dump_meta(self), patches))

    # -- pipelined ack settlement -------------------------------------------

    def _resolve_flush(self, taken, planes, ack: bool = True,
                       ack_reads: bool = True, op_planes=None,
                       rec=None, fid: int = 0,
                       t_join: float = 0.0, lanes=None) -> int:
        """Defer resolution until the flush's host-quorum outcome is
        in (an ack may never outrun the host quorum — READS INCLUDED:
        a minority/deposed leader serving reads would break
        linearizability under partition).  The entry the immediately
        preceding ``_launch`` stashed claims the futures/planes; the
        drain settles entries strictly in flush order, blocking only
        when the pipeline is deeper than ``repl_window``."""
        entry = self._unclaimed
        if entry is None:
            # single-lane mode / replica role: the plain barrier
            return super()._resolve_flush(taken, planes, ack=ack,
                                          ack_reads=ack_reads,
                                          op_planes=op_planes,
                                          rec=rec, fid=fid,
                                          t_join=t_join, lanes=lanes)
        self._unclaimed = None
        entry.taken, entry.planes = taken, planes
        entry.op_planes = op_planes
        entry.lanes = lanes
        entry.rec = rec
        entry.t_join = t_join
        entry.ack, entry.ack_reads = ack, ack_reads
        self._drain_pending(down_to=self.repl_window)
        return 0

    def _outstanding(self) -> int:
        return (sum(len(b.entries) for b in self._pending_flushes)
                + len(self._ship_buf))

    def _drain_pending(self, block_all: bool = False,
                       down_to: Optional[int] = None) -> None:
        """Ship anything buffered, then settle pending batches
        oldest-first.  Non-blocking by default (a batch settles once
        a majority acked, every ticket completed, or its deadline
        passed); ``down_to=N`` blocks only until at most N flush
        entries remain outstanding (the steady-state ship path —
        draining to empty would collapse the very window the pipeline
        provides); ``block_all`` waits every batch out — used before a
        checkpoint/takeover/lifecycle op and by idle flushes so
        flush-until-done callers observe resolved futures."""
        self._reap_stragglers()
        if block_all or down_to is None \
                or self._outstanding() > down_to:
            self._ship_now()
        while self._pending_flushes:
            batch = self._pending_flushes[0]
            done = all(t.event.is_set() for _l, t in batch.sends)
            if not done:
                must_free = (down_to is not None
                             and self._outstanding() > down_to)
                if block_all or must_free:
                    batch.wait_quorum(self._quorum_from)
                elif not self._quorum_from(batch._acked_now()) \
                        and time.monotonic() < batch.deadline:
                    break
            self._pending_flushes.popleft()
            self._settle_batch(batch)

    def _account_ack(self, link: PeerLink, r: Any, crc: int,
                     acked: set) -> None:
        """Bookkeep one link's cumulative-ack outcome."""
        if r is None:
            link.needs_sync = True
        elif r[0] == "applied" and int(r[3]) == crc \
                and not link.needs_sync:
            acked.add((link.host, link.port))
        elif r[0] == "applied":
            # applied but diverged (CRC mismatch): physical
            # corruption or a missed batch — heal via re-sync
            link.needs_sync = True
        elif r[0] == "nack" and r[1] == "epoch":
            # Depose ONLY when the replica promised a genuinely
            # newer epoch.  A LOWER promised (a blank replacement
            # host, or one whose meta was lost) is merely stale —
            # deposing on it would let a dead disk take down a
            # healthy majority leader (review r4).  It re-syncs
            # instead (install raises its promise).
            if int(r[2]) > self._ge:
                self._note_depose(int(r[2]))
            link.needs_sync = True
        else:
            link.needs_sync = True

    def _reap_stragglers(self) -> None:
        """Bookkeep tickets of batches that settled at majority
        before every link answered: a late nack still marks its link
        for re-sync (and a late epoch nack still deposes) — nothing a
        slow socket reports is ever dropped, it just stops holding
        the settled batch's futures hostage."""
        if not self._stragglers:
            return
        still = []
        sink: set = set()
        for link, t, crc in self._stragglers:
            if not t.event.is_set():
                still.append((link, t, crc))
                continue
            self._account_ack(link, t.result, crc, sink)
        self._stragglers = still

    def _settle_batch(self, batch: "_PendingShip") -> None:
        """Count one batch's cumulative acks, decide its host-quorum
        outcome, and resolve every member entry's client futures
        accordingly (the per-flush barrier stands — the batch is the
        unit of ack, the entry stays the unit of resolution)."""
        acked = set()
        for link, apply_t in batch.sends:
            # a catch-up that completed BEFORE this settle makes the
            # link countable for LATER batches — consumable only when
            # it was queued ahead of this batch or earlier
            # (install_barrier <= first_seq): an install posted by a
            # LATER ship must stay pending for the settle that can
            # actually observe its effect (ADVICE r5)
            inst_t = link.install_ticket
            if inst_t is not None and inst_t.event.is_set() \
                    and link.install_barrier <= batch.first_seq:
                ri = inst_t.result
                link.install_ticket = None
                if ri is not None and ri[0] == "installed":
                    link.needs_sync = False
                    link.tried_tree = False
                elif ri is not None and ri[0] == "nack" \
                        and int(ri[2]) > self._ge:
                    self._note_depose(int(ri[2]))
            if not apply_t.event.is_set():
                # quorum settled without this link: its outcome is
                # bookkept when it lands (or its connection drops) —
                # a slow socket must not hold every future to the
                # deadline (max-of-links, not sum-of-slow-prefix)
                self._stragglers.append((link, apply_t, batch.crc))
                continue
            self._account_ack(link, apply_t.result, batch.crc, acked)
        q = self._quorum_from(acked) and not self._deposed
        if self._follower_reads:
            if q:
                # §16 WRITE BARRIER: a write must not ack while a
                # replica that did NOT ack it may still serve reads
                # under an unexpired lease — its mirrors would miss
                # the write and a follower read could return the
                # overwritten value AFTER the client saw the ack.
                # Settles fire at quorum, so a fence holder is often
                # just a straggler whose ack is milliseconds out:
                # WAIT for it (don't fail the batch), bounded by its
                # fence — a holder that cannot confirm (nack, dead
                # socket) stalls this ack at most lease(), the
                # classic price of leased reads.
                addr_t = {(l.host, l.port): t for l, t in batch.sends}
                blocked = False
                while True:
                    now_m = time.monotonic()
                    for a in [a for a, t in self._flw_fence.items()
                              if t <= now_m]:
                        del self._flw_fence[a]
                    missing = [a for a in self._flw_fence
                               if a not in acked]
                    if not missing:
                        break
                    if not blocked:
                        blocked = True
                        self.group_stats[
                            "follower_lease_write_blocks"] += 1
                    a = missing[0]
                    t = addr_t.get(a)
                    budget = max(0.0, self._flw_fence[a] - now_m)
                    if t is not None and not t.event.is_set():
                        if t.event.wait(budget):
                            for l2, t2 in batch.sends:
                                if t2 is t:
                                    self._account_ack(
                                        l2, t2.result, batch.crc,
                                        acked)
                                    break
                        continue
                    # the holder answered without a countable ack (or
                    # never got this batch): its mirrors provably miss
                    # the write — only fence expiry releases the ack
                    time.sleep(budget)
            now_m = time.monotonic()
            if q and now_m <= batch.deadline \
                    and self._cfg_txn is None:
                # grant/renew: each acking replica's lease window
                # anchors at ITS ack-send time, provably before this
                # settle — fence[a] (our clock) always outlasts the
                # replica's own window.  The deadline gate bounds
                # grant issuance to ack_timeout past the ship, which
                # is what lets a takeover wait out
                # ack_timeout + lease() + read_margin.  No grants
                # during a membership transition (handle_cfg drops
                # windows; new ones must wait for the new config).
                g_seq = batch.entries[-1].seq
                lease_s = self.config.lease()
                for a in acked:
                    self._flw_grants[a] = max(
                        self._flw_grants.get(a, 0), g_seq)
                    self._flw_fence[a] = now_m + lease_s
        self._last_quorum_ok = q
        # the HOST lease for leader-local fast reads: only a settle
        # whose host quorum confirmed this epoch renews it, and a
        # lost quorum revokes it BEFORE any of this batch's futures
        # resolve (the mirror updates below run under ack_reads=False
        # then — a minority leader serves nothing).  The grant is
        # based at the batch's newest enqueue time, not settle-
        # processing time (mirroring the device lane's fl.now
        # discipline): the quorum contact the acks prove is no
        # fresher than the ship, and a promoter waiting out lease()
        # counts from the fencing — a settle delayed in the pipeline
        # must not stretch the leased window past what those acks can
        # vouch for.  max() keeps a later-shipped batch's settle from
        # shrinking an earlier grant (settles process in FIFO ship
        # order anyway).
        if q:
            self._host_lease_until = max(
                self._host_lease_until,
                batch.shipped_at + self.config.lease())
            self.group_stats["repl_ack_s"] += \
                time.monotonic() - batch.ship_t
            self.group_stats["repl_acked_batches"] += 1
            for entry in batch.entries:
                # §18: pure-merge entries in a single-run frame are
                # the ones replicas could ack pre-scatter — the
                # quorum-confirmed settle is where that early path
                # becomes client-visible
                if entry.entry[0] == "m" and int(entry.entry[3]) == 0:
                    self.comm_stats["repl_early_acks"] += 1
        else:
            self._host_lease_until = 0.0
            self.group_stats["quorum_failures"] += 1
        if self._obs:
            # leader half of the replication trace: one ack span per
            # member flush (ship → host-quorum decision), joined with
            # the replica apply spans by flush id
            ack_s = time.monotonic() - batch.ship_t
            for entry in batch.entries:
                obs.SPANS.record(entry.fid, "leader",
                                 [("repl_ack", ack_s)],
                                 quorum_ok=q, seq=entry.seq)
        for entry in batch.entries:
            if entry.taken is not None:
                super()._resolve_flush(entry.taken, entry.planes,
                                       ack=entry.ack and q,
                                       ack_reads=entry.ack_reads and q,
                                       op_planes=entry.op_planes,
                                       rec=entry.rec, fid=entry.fid,
                                       t_join=entry.t_join,
                                       lanes=entry.lanes)

    def flush(self) -> int:
        served = super().flush()
        # settle opportunistically under load; fully when idle (no new
        # work to overlap with), so flush-until-done callers and the
        # post-load read-back sweeps observe resolved futures
        self._drain_pending(block_all=not self._active)
        # on a replicated leader, client futures resolve in the
        # settle above (after the host quorum), so a kmodify chain's
        # follow-up CAS lands HERE — give it its launch cycle inside
        # the same flush call (the base flush's chain point saw
        # nothing: resolution was deferred past it)
        served += self._chain_flush()
        if self._cfg_txn is not None:
            self._advance_cfg()
        return served

    def save(self, path: Optional[str] = None) -> None:
        # the snapshot must see fully settled host mirrors (deferred
        # resolutions mutate slot_handle): drain the pipeline first
        if self._links:
            self._in_save = True
            try:
                while self._active:
                    super().flush()
                    self._drain_pending(block_all=True)
                self._drain_launches()
                self._drain_pending(block_all=True)
            finally:
                self._in_save = False
        super().save(path)

    def set_repl_window(self, window: int) -> int:
        """Retune the replication ack window at runtime (the ack-RTT
        actuator's second knob).  Shrinking first settles pending
        ship batches down to the new bound, so the per-flush quorum
        barrier and FIFO settle order are untouched — only how many
        resolved-but-unsettled flushes may coalesce ahead of the head
        batch changes.  Returns the previous window."""
        window = max(1, int(window))
        old = self.repl_window
        if window != old:
            if window < old:
                self._drain_pending(down_to=window)
            self.repl_window = window
            self._emit("svc_autotune",
                       {"knob": "repl_window", "old": old,
                        "new": window})
        return old

    def heartbeat(self) -> bool:
        """Drive replication liveness without client load: an empty
        apply (k=0, no elections) that reconnects and re-syncs lagging
        replicas and re-confirms the host quorum.  Busy leaders get
        this for free from real flushes; idle ones need the beat or a
        restarted replica would stay stale until the next client op.
        Returns the host-quorum outcome (the pipeline fully settled)."""
        # a direct sync launch must not overtake unsettled pipelined
        # launches (settles are strictly FIFO in seq order)
        self._drain_launches()
        z = np.zeros((0, self.n_ens), np.int32)
        elect, cand = self._election_inputs()
        lease_ok = self.lease_until > self.runtime.now
        self._launch(z, z, z, 0, want_vsn=True, exp_e=z, exp_s=z,
                     elect=elect, cand=cand, lease_ok=lease_ok)
        self._unclaimed = None  # nothing to resolve for the beat
        self._drain_pending(block_all=True)
        if self._cfg_txn is not None:
            self._advance_cfg()
        return self._last_quorum_ok

    def _wal_extra_records(self) -> List[Tuple[Any, Any]]:
        return [(_GRP_KEY, (self.core.promised, self._ge,
                            self._grp_seq, self.core.cfg))]

    def _note_depose(self, promised: int) -> None:
        if not self._deposed:
            self.group_stats["depositions"] += 1
            self._emit("grp_deposed", {"superseded_by": promised})
        self._deposed = True
        # a deposed leader invalidates its read lease BEFORE its next
        # ack — no leased read may outlive the observed fencing; the
        # follower-read grant table dies with the reign too (a deposed
        # leader can't settle a quorum, so it could never renew)
        self._host_lease_until = 0.0
        self._flw_grants.clear()
        self._flw_fence.clear()
        self.core.promised = max(self.core.promised, promised)

    def _on_storage_degraded(self) -> None:
        """A leader whose WAL disk died cannot take the durability
        barrier its acks promise — demote it through the existing
        step-down machinery (ARCHITECTURE §15): leadership drops, the
        host lease dies before any further ack, and a peer with a
        working disk can promote itself.  The decision is journaled
        (grp_step_down trace event, group_stats, and the base
        svc_storage_degraded record/health section/gauges)."""
        if self._is_leader:
            if self._storage_degraded is not None:
                self._storage_degraded["mode"] = "step_down"
            self._is_leader = False
            self._deposed = True
            self._host_lease_until = 0.0
            self.group_stats["storage_step_downs"] = \
                self.group_stats.get("storage_step_downs", 0) + 1
            self._emit("grp_step_down", {
                "reason": "wal-storage",
                "errno": (self._storage_degraded or {}).get("errno")})

    # -- replicated dynamic lifecycle ---------------------------------------

    def create_ensemble(self, name, view=None):
        """Dynamic tenant creation with the SAME host-quorum barrier
        as writes: the op rides the group (epoch, seq) stream, every
        lane applies it deterministically (identical directories →
        identical row assignment and failure outcomes), and the row
        is returned only after a host majority acked.  Raises on lost
        quorum — the local create stands (minority residue healed by
        re-sync on heal), but the caller must not act on it."""
        row, _ = self._lifecycle("create", name, view)
        return row

    def destroy_ensemble(self, name):
        _, ok = self._lifecycle("destroy", name, None)
        return ok

    def _lifecycle(self, kind: str, name, view):
        if not self._links and self.group_size == 1:
            if kind == "create":
                return super().create_ensemble(name, view), None
            return None, super().destroy_ensemble(name)
        if not self.is_leader:
            raise DeposedError("not the group leader")
        # lifecycle is synchronous: settle BOTH pipelines (device
        # launches, then replication acks) so the sync flags it reads
        # (and the acks it counts) are current
        self._drain_launches()
        self._drain_pending(block_all=True)
        seq = self._grp_seq + 1
        view_b = None if view is None else _pack_bool(
            np.asarray(view, bool))
        if kind == "create":
            row = super().create_ensemble(name, view)
            ok = None
            crc = row if row is not None else -1
        else:
            row = None
            ok = super().destroy_ensemble(name)
            crc = 1 if ok else 0
        self._grp_seq = seq
        self.core.applied_ge = self._ge
        self.core.applied_seq = seq
        self.core.last_crc = crc
        if self._wal is not None:
            save_group_meta(self, self.core.promised, self._ge, seq,
                            self.core.cfg)
        acked = self._replicate_record(
            ("lcl", self._ge, seq, kind, name, view_b), crc)
        if not self._quorum_from(acked) or self._deposed:
            self.group_stats["quorum_failures"] += 1
            raise RuntimeError(
                f"lifecycle {kind} {name!r}: no host quorum "
                f"({1 + len(acked)}/{self.group_size})")
        return row, ok

    def install_objs(self, ens, items):
        """Version-preserving install with the host-quorum barrier:
        the leader allocates (slots/handles) and decides leadership,
        ships the EXACT allocation through the (epoch, seq) stream
        (handle_inst applies it verbatim — independent allocation
        could diverge free-list orders across lanes), and raises on
        lost quorum like the lifecycle ops."""
        if not self._links and self.group_size == 1:
            return super().install_objs(ens, items)
        if not self.is_leader:
            raise DeposedError("not the group leader")
        self._drain_launches()
        self._drain_pending(block_all=True)
        results, applied = self._allocate_install(int(ens), items)
        if not applied:
            return results
        lead = self._install_lead(int(ens))
        crc = record_digest((a[1], a[2], a[3], a[4]) for a in applied)
        seq = self._grp_seq + 1
        self._grp_seq = seq
        self.core.applied_ge = self._ge
        self.core.applied_seq = seq
        self.core.last_crc = crc
        self._apply_installed(
            int(ens), applied, lead,
            extra_records=[(_GRP_KEY, (self.core.promised, self._ge,
                                       seq, self.core.cfg))])
        acked = self._replicate_record(
            ("inst", self._ge, seq, int(ens), lead,
             [list(a) for a in applied]), crc)
        if not self._quorum_from(acked) or self._deposed:
            self.group_stats["quorum_failures"] += 1
            raise RuntimeError(
                f"install_objs ens {ens}: no host quorum "
                f"({1 + len(acked)}/{self.group_size})")
        return results

    def stats(self) -> Dict[str, Any]:
        s = super().stats()
        s["group"] = {
            "leader": self.is_leader,
            "epoch": self._ge,
            "seq": self._grp_seq,
            "size": self.group_size,
            "peers_connected": sum(l.connected for l in self._links),
            "peers_synced": sum(not l.needs_sync for l in self._links),
            # per-link liveness/failure rows (drop counters + any
            # injected-fault view) — the flapping-link evidence the
            # rate-limited stderr line summarizes
            "links": [l.link_stats() for l in self._links],
            "link_drops": sum(l.drops for l in self._links),
            "link_injected_drops": sum(l.injected_drops
                                       for l in self._links),
            "repl_window": self.repl_window,
            "pipeline_pending": self._outstanding(),
            "repl_delta": self._repl_delta and self._delta_shape_ok,
            "comm_repl": bool(self._comm_repl),
            "trust_host_lease": self.trust_host_lease,
            "host_lease_valid": bool(
                self._host_lease_until
                > self.runtime.now + self._read_margin),
            **self.group_stats,
            **self.comm_stats,
            "repl_merge_coalesce_ratio": round(
                self.comm_stats["repl_merge_ops"]
                / max(self.comm_stats["repl_merge_cells"], 1), 6),
        }
        return s

    def health(self, ens: Optional[int] = None) -> Dict[str, Any]:
        """The ensemble-health verb on a replicated leader carries a
        ``group`` section too (the host-quorum plane a dashboard
        needs next to the device-plane rows): role, group epoch/seq,
        link liveness/sync, pipeline depth outstanding, host-lease
        validity and the quorum-failure/deposition history — all
        host-side bookkeeping, zero device rounds (per-row queries
        pass through unchanged)."""
        out = super().health(ens)
        if ens is not None:
            return out
        out["group"] = {
            "leader": bool(self.is_leader),
            "epoch": int(self._ge),
            "seq": int(self._grp_seq),
            "size": int(self.group_size),
            "peers_connected": sum(l.connected for l in self._links),
            "peers_synced": sum(not l.needs_sync
                                for l in self._links),
            # per-link rows: connection drops + the injected-fault
            # section (satellite: an operator reading health must be
            # able to tell a running nemesis from a real outage)
            "links": [l.link_stats() for l in self._links],
            "pipeline_pending": int(self._outstanding()),
            "host_lease_valid": bool(
                self._host_lease_until
                > self.runtime.now + self._read_margin),
            "quorum_failures": int(
                self.group_stats.get("quorum_failures", 0)),
            "depositions": int(
                self.group_stats.get("depositions", 0)),
        }
        # the fleet watchdog's section (§11): always present on a
        # grouped service — `enabled: false` when disarmed — so a
        # dashboard's queries keep their shape, the controller-
        # section discipline
        out["watchdog"] = self.watchdog.health_section()
        return out

    def stop(self) -> None:
        self._drain_launches()
        self._drain_pending(block_all=True)
        super().stop()
        for link in self._links:
            link.close()




# -- the replica host process ------------------------------------------------

class ReplicaServer:
    """One replica host: a threaded TCP server speaking the group
    protocol (promise/apply/install/pull) plus control commands
    (promote/status), and a client port that answers the svcnode frame
    protocol — ops are rejected with ("error", "not-leader") until
    this host is promoted, after which it serves exactly like a
    leader-born node (the in-place promotion path: its own lane
    already holds the replicated state, so promotion is a promise
    round plus adopting the newest grant, never a cold transfer)."""

    def __init__(self, n_ens: int, group_size: int, n_slots: int,
                 repl_port: int = 0, client_port: int = 0,
                 host: str = "127.0.0.1",
                 data_dir: Optional[str] = None,
                 config: Optional[Config] = None,
                 tick: float = 0.005,
                 ack_timeout: float = 2.0,
                 peers: Sequence[Tuple[str, int]] = (),
                 auto_failover: Optional[float] = None,
                 dynamic: bool = False,
                 advertise: Optional[Tuple[str, int]] = None,
                 trust_host_lease: bool = False,
                 follower_reads: Optional[bool] = None) -> None:
        runtime = WallRuntime()
        if data_dir is not None and (
                os.path.exists(os.path.join(data_dir, "META"))
                or os.path.exists(os.path.join(data_dir, "CURRENT"))):
            dyn_kw = {"dynamic": True} if dynamic else {}
            self.svc = ReplicatedService.restore(
                runtime, data_dir, group_size=group_size,
                data_dir=data_dir, config=config,
                ack_timeout=ack_timeout,
                trust_host_lease=trust_host_lease,
                follower_reads=follower_reads, **dyn_kw)
        else:
            self.svc = ReplicatedService(
                runtime, n_ens, 1, n_slots, group_size=group_size,
                data_dir=data_dir, config=config,
                ack_timeout=ack_timeout, dynamic=dynamic,
                trust_host_lease=trust_host_lease,
                follower_reads=follower_reads)
        self.core = self.svc.core
        warmup_kernels(self.svc)
        warm_delta_apply(self.svc)
        self.tick = tick
        self._lock = threading.RLock()
        self._stop = False
        self._flush_thread: Optional[threading.Thread] = None
        self._repl_srv = _ThreadedAcceptor(
            host, repl_port, self._serve_repl_conn)
        self._client_srv = _ThreadedAcceptor(
            host, client_port, self._serve_client_conn)
        self.repl_port = self._repl_srv.port
        self.client_port = self._client_srv.port
        #: this host's identity in group configs = the address peers
        #: DIAL it by.  Defaults to (bind host, bound repl port) —
        #: correct when every host binds the address others use; a
        #: wildcard/NAT'd bind must pass ``advertise`` (CLI:
        #: --advertise HOST:PORT) or membership comparisons would
        #: treat this node as a non-member of its own group
        self.svc.self_addr = (
            (str(advertise[0]), int(advertise[1]))
            if advertise is not None
            else (str(host), int(self.repl_port)))
        #: member flag: a host a collapse removed must not campaign
        #: (the Raft removed-server disruption rule); manual promote
        #: still works
        self._member = True
        self.core.on_cfg = self._apply_cfg
        if self.core.cfg[1] is not None:
            self._apply_cfg(self.core.cfg)
        #: automatic leader failover (the reference's peers self-elect
        #: on follower timeout, peer.erl's following -> probe ->
        #: election; here the follower signal is leader silence on the
        #: replication port): when ``auto_failover`` seconds pass with
        #: no leader-originated frame AND this host ranks first by
        #: (applied_ge, applied_seq, address) among reachable peers,
        #: it promotes itself — promise-round fencing makes duels
        #: safe, ranking merely avoids most of them.
        self.peer_addrs = [(str(h), int(p)) for h, p in peers]
        self.auto_failover = auto_failover
        self._host = host
        self._last_leader_contact = time.monotonic()
        #: stable random identity for election tie-breaks: the BIND
        #: host can differ from the address peers dial (wildcards,
        #: NAT), so ranking by exchanged ids — not addresses — is the
        #: only comparison both sides compute identically
        import random as _random
        self.node_id = _random.getrandbits(63)
        #: campaign flag: a takeover in progress must not hold the big
        #: lock across its network rounds (two campaigners would
        #: deadlock each other's promise handlers); applies are
        #: busy-nacked instead
        self._campaign = False
        if auto_failover is not None:
            assert self.peer_addrs, \
                "auto failover needs the peer address list"
            threading.Thread(target=self._failover_monitor,
                             daemon=True).start()

    # restore() classmethod inherits BatchedEnsembleService.restore,
    # which forwards **kw to the constructor — group_size rides along.

    @property
    def role(self) -> str:
        return "leader" if self.svc.is_leader else "replica"

    # -- replication port ---------------------------------------------------

    def _serve_repl_conn(self, sock: socket.socket) -> None:
        while not self._stop:
            try:
                frame = recv_frame(sock)
            except (ConnectionError, OSError, wire.WireError):
                return
            #: §18 early-ack outcome for THIS frame (reset every
            #: iteration: a stale entry must never suppress a reply)
            fired: List[bool] = []
            try:
                if frame and frame[0] == "promote":
                    # promotion runs OUTSIDE the big lock: a campaign
                    # holding it across network rounds would block
                    # this node's promise/status handlers — two
                    # campaigning nodes would deadlock each other's
                    # promise grants.  Concurrent applies are fenced
                    # by the campaign flag (busy-nacks) instead.
                    peers = [(str(h), int(p)) for h, p in frame[1]]
                    resp = self._promote(peers)
                elif frame and frame[0] == "obsq":
                    # fleet sideband: answered OUTSIDE the big lock —
                    # the payloads read thread-safe stores (the span
                    # store has its own lock) or monitoring-grade
                    # snapshots, and an obs pull must never queue
                    # behind a slow apply holding the lock (the
                    # response's monotonic stamp feeds the leader's
                    # clock-offset estimate; lock dwell would inflate
                    # the round-trip bound for nothing)
                    resp = self._handle_obsq(frame)
                else:
                    # §18 early ack: arm the core's pre-scatter send
                    # hook for abatch frames (under the big lock, so
                    # at most one frame's hook is live); ``fired``
                    # records the outcome so the reply path below
                    # never double-sends the ack
                    arm = (frame and frame[0] == "abatch"
                           and self.svc._comm_repl
                           and not self._campaign)

                    def _early(resp_t, _s=sock, _f=fired):
                        try:
                            send_frame(_s, resp_t)
                            _f.append(True)
                        except (ConnectionError, OSError):
                            _f.append(False)

                    with self._lock:
                        if arm:
                            self.core.early_ack = _early
                        try:
                            resp = self._handle_repl(frame)
                        finally:
                            self.core.early_ack = None
            except Exception:
                import traceback
                self.svc._emit("grp_replica_error",
                               {"error": traceback.format_exc(limit=8)})
                resp = ("error", "internal")
            if fired:
                if not fired[0]:
                    return  # the early send hit a dead socket
                continue  # ack already on the wire
            try:
                send_frame(sock, resp)
            except (ConnectionError, OSError):
                return

    def _handle_repl(self, frame: Tuple) -> Tuple:
        op = frame[0]
        if op in ("hello", "apply", "abatch", "install", "lcl", "cfg",
                  "tpatch", "inst"):
            # leader-originated traffic: the failover monitor's
            # liveness signal
            self._last_leader_contact = time.monotonic()
        if op == "hello":
            ge = int(frame[1])
            # a newer leader's handshake supersedes this host's own
            # leadership (the fencing a deposed leader observes)
            if ge > self.core.promised:
                self._step_down()
            return ("helloed", self.core.promised, self.core.applied_ge,
                    self.core.applied_seq)
        if op == "promise":
            ge = int(frame[1])
            if ge > self.core.promised:
                self._step_down()
                # granting a vote resets the election timer (the raft
                # discipline): the rival we just granted is about to
                # become leader — don't campaign over it
                self._last_leader_contact = time.monotonic()
            return self.core.handle_promise(ge)
        if op in ("apply", "abatch"):
            if self._campaign:
                # a campaign is installing/pulling state concurrently;
                # the leader treats this like any missed ack (re-sync)
                return ("nack", "busy", self.core.promised,
                        self.core.applied_ge, self.core.applied_seq)
            if self.svc.is_leader:
                # a live apply stream at a newer epoch deposes us;
                # at an older epoch it is nacked by the core
                if int(frame[1]) > self.core.promised:
                    self._step_down()
            if op == "abatch":
                return self.core.handle_abatch(frame)
            return self.core.handle_apply(frame)
        if op == "lcl":
            if self._campaign:
                return ("nack", "busy", self.core.promised,
                        self.core.applied_ge, self.core.applied_seq)
            if self.svc.is_leader and \
                    int(frame[1]) > self.core.promised:
                self._step_down()
            return self.core.handle_lcl(frame)
        if op == "cfg":
            if self._campaign:
                return ("nack", "busy", self.core.promised,
                        self.core.applied_ge, self.core.applied_seq)
            if self.svc.is_leader and \
                    int(frame[1]) > self.core.promised:
                self._step_down()
            return self.core.handle_cfg(frame)
        if op == "inst":
            if self._campaign:
                return ("nack", "busy", self.core.promised,
                        self.core.applied_ge, self.core.applied_seq)
            if self.svc.is_leader and \
                    int(frame[1]) > self.core.promised:
                self._step_down()
            return self.core.handle_inst(frame)
        if op == "install":
            if self._campaign:
                return ("nack", "busy", self.core.promised,
                        self.core.applied_ge, self.core.applied_seq)
            if int(frame[1]) >= self.core.promised:
                self._step_down()
            return self.core.handle_install(frame)
        if op == "pull":
            return self.core.handle_pull()
        if op == "troots":
            return self.core.handle_troots()
        if op == "tleaves":
            return self.core.handle_tleaves(frame)
        if op == "tpatch":
            if self._campaign:
                return ("nack", "busy", self.core.promised,
                        self.core.applied_ge, self.core.applied_seq)
            if int(frame[1]) >= self.core.promised:
                self._step_down()
            return self.core.handle_tpatch(frame)
        if op == "status":
            return ("status", self.role, self.core.promised,
                    self.core.applied_ge, self.core.applied_seq,
                    self.node_id)
        if op == "links":
            return ("links", [
                (l.host, l.port, bool(l.connected),
                 bool(l.needs_sync), bool(l.tried_tree),
                 None if l.sync is None else (l.sync.result or "…"),
                 l.install_ticket is not None,
                 list(l.remote_state))
                for l in self.svc._links])
        return ("error", "unknown-op")

    def _handle_obsq(self, frame: Tuple) -> Tuple:
        """The fleet sideband (docs/ARCHITECTURE.md §11): one
        ``("obsq", kind, ...)`` request per pull, answered
        ``("obsr", t_mono, payload)`` — the monotonic stamp is this
        HOST's clock while handling, the middle leg of the leader's
        NTP-midpoint offset estimate.  Every payload is
        read-only/monitoring-grade: registry snapshot or Prometheus
        text, the health section, or span-store records by flush id
        (structured misses included — "hasn't arrived yet" vs
        "rolled off"; a replica's per-fid evidence lives in its span
        store, since delta applies never ride its own launch path or
        flight ring)."""
        kind = frame[1]
        svc = self.svc
        try:
            if kind == "metrics":
                payload: Any = svc.obs_registry.snapshot()
            elif kind == "prometheus":
                payload = svc.obs_registry.render_prometheus()
            elif kind == "health":
                payload = svc.health()
            elif kind == "timeline":
                fids = [int(f) for f in frame[2]]
                payload = {f: obs.SPANS.timeline(f) for f in fids}
            else:
                return ("error", "unknown-op")
        except Exception:
            return ("error", "internal")
        return ("obsr", time.monotonic(), payload)

    def _apply_cfg(self, cfg) -> None:
        """Mirror a committed group config into this server's
        failover machinery: the peer address list tracks the member
        set (hosts + any joint incoming), the quorum size tracks the
        committed list, and a host the config no longer includes stops
        campaigning (a removed server disrupting elections is the
        classic reconfiguration hazard)."""
        _cver, hosts, joint = cfg
        if hosts is None:
            return
        members = list(hosts) + [a for a in (joint or ())
                                 if a not in hosts]
        me = self.svc.self_addr
        self.peer_addrs = [(str(h), int(p)) for h, p in members
                           if (str(h), int(p)) != me]
        self.svc.group_size = len(hosts)
        self._member = me in members
        if not self._member:
            self.svc._emit("grp_removed_from_group",
                           {"cver": _cver})

    def _step_down(self) -> None:
        if self.svc._is_leader:
            self.svc._is_leader = False
            self.svc._deposed = True
            # any replica-role read window predating our reign is
            # meaningless now (and a fresh one needs fresh grants)
            self.core._flw_drop()
            self.svc._emit("grp_step_down", {})

    def _promote(self, peers: List[Tuple[str, int]]) -> Tuple:
        if self._campaign:
            return ("error", "busy")
        self._campaign = True
        try:
            if not self.svc._links:
                self.svc.attach_peers(peers)
            rebuild_derived(self.svc)
            ok = self.svc.takeover()
        finally:
            self._campaign = False
        if not ok:
            return ("error", "no-majority")
        # Post-takeover heal: drive heartbeats until the reachable
        # replicas re-sync (bounded) — a fresh leader whose peers need
        # catch-up would otherwise fail its first client flushes on a
        # host quorum its installs are about to restore.  Bounded and
        # best-effort: a majority that never syncs surfaces as failed
        # client ops, exactly as before.
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            try:
                with self._lock:
                    self.svc.heartbeat()
                    g_synced = sum(not l.needs_sync
                                   for l in self.svc._links
                                   if l.connected)
                    need = self.svc.group_size // 2
                    if g_synced >= min(
                            need,
                            sum(l.connected
                                for l in self.svc._links)):
                        break
            except DeposedError:
                break
            time.sleep(0.1)
        if self._flush_thread is None:
            self._flush_thread = threading.Thread(
                target=self._flush_loop, daemon=True)
            self._flush_thread.start()
        return ("ok", self.svc._ge)

    # -- automatic leader failover ------------------------------------------

    def _failover_monitor(self) -> None:
        import random

        poll = max(0.2, self.auto_failover / 4.0)
        while not self._stop:
            time.sleep(poll)
            try:
                self._failover_check(poll, random)
            except Exception:
                # the monitor must outlive any single bad pass — a
                # dead monitor thread silently disables failover for
                # this node forever (review r4)
                import traceback
                self.svc._emit("grp_failover_error",
                               {"error": traceback.format_exc(
                                   limit=8)})
                self._last_leader_contact = time.monotonic()

    def _failover_check(self, poll: float, random) -> None:
        if self.svc.is_leader or not getattr(self, "_member", True):
            return
        if time.monotonic() - self._last_leader_contact \
                < self.auto_failover:
            return
        if not self._ranks_first():
            # a better-positioned candidate exists; give it a
            # cycle (its promotion will contact us)
            self._last_leader_contact = time.monotonic() \
                - self.auto_failover * 0.5
            return
        time.sleep(random.uniform(0.0, poll))  # duel jitter
        # re-check AFTER the jitter: a rival may have won meanwhile
        # (its promise/hello updated our contact clock) — usurping it
        # at a higher epoch would ping-pong leadership (review r4)
        if self.svc.is_leader or self._stop or \
                time.monotonic() - self._last_leader_contact \
                < self.auto_failover:
            return
        self.svc._emit("grp_auto_failover_attempt",
                       {"ge": self.core.promised})
        r = self._promote(self.peer_addrs)
        if r[0] != "ok":
            # no majority reachable (we may be the minority side
            # of a partition): back off a full window
            self._last_leader_contact = time.monotonic()

    def _ranks_first(self) -> bool:
        """True when this host holds the newest (applied_ge,
        applied_seq) among REACHABLE peers — ties broken by the
        EXCHANGED node ids (bind addresses aren't comparable: the
        name peers dial can differ from --host) — so at most one
        candidate per connected component normally attempts the
        promise round."""
        me = (self.core.applied_ge, self.core.applied_seq,
              self.node_id)
        for host, port in self.peer_addrs:
            try:
                with socket.create_connection((host, port),
                                              timeout=2.0) as s:
                    s.settimeout(5.0)
                    send_frame(s, ("status",))
                    r = recv_frame(s)
            except (OSError, ConnectionError, wire.WireError):
                continue
            if r[0] != "status":
                continue
            if r[1] == "leader":
                # a live leader exists; we were just out of touch
                self._last_leader_contact = time.monotonic()
                return False
            other = (int(r[3]), int(r[4]),
                     int(r[5]) if len(r) > 5 else 0)
            if other > me:
                return False
        return True

    HEARTBEAT_EVERY = 1.0

    def _flush_loop(self) -> None:
        last_beat = time.monotonic()
        while not self._stop:
            time.sleep(self.tick)
            if not self.svc.is_leader:
                continue
            try:
                with self._lock:
                    if self.svc._active or self.svc._pending_flushes \
                            or self.svc._ship_buf \
                            or self.svc._election_inputs()[0].any():
                        self.svc.flush()
                        last_beat = time.monotonic()
                    elif time.monotonic() - last_beat \
                            > self.HEARTBEAT_EVERY:
                        # idle: keep replica liveness/re-sync moving
                        self.svc.heartbeat()
                        last_beat = time.monotonic()
            except DeposedError:
                continue
            except Exception:
                import traceback
                self.svc._emit("grp_flush_error",
                               {"error": traceback.format_exc(limit=8)})

    # -- client port (svcnode frame protocol) -------------------------------

    def _serve_client_conn(self, sock: socket.socket) -> None:
        wlock = threading.Lock()

        def send(req_id, result) -> None:
            try:
                payload = wire.encode((req_id, result))
            except wire.WireError:
                payload = wire.encode((req_id, "failed"))
            with wlock:
                try:
                    sock.sendall(_HDR.pack(len(payload)) + payload)
                except (ConnectionError, OSError):
                    pass

        while not self._stop:
            try:
                msg = recv_frame(sock)
                req_id, op = msg[0], msg[1]
                args = tuple(msg[2:])
            except (ConnectionError, OSError, wire.WireError,
                    IndexError, TypeError):
                return
            if op == "stats":
                with self._lock:
                    send(req_id, self.svc.stats())
                continue
            if not self.svc.is_leader:
                if op in ("kget", "kget_vsn", "kget_many",
                          "kget_slab"):
                    # §16 follower-served leased reads: answer from
                    # this replica's delta-maintained mirrors when an
                    # unexpired leader-granted lease covers the
                    # ensemble; any miss falls back to not-leader
                    # (the client re-routes to the leader, exactly
                    # the pre-lease behavior)
                    try:
                        with self._lock:
                            r = self._follower_read(op, args)
                    except Exception:
                        r = None
                    if r is not None:
                        send(req_id, r)
                        continue
                send(req_id, ("error", "not-leader"))
                continue
            if op == "update_group_members":
                try:
                    with self._lock:
                        self.svc.update_members(
                            [(str(h), int(pt)) for h, pt in args[0]])
                        resp = ("ok", self.svc.membership_status())
                except DeposedError:
                    resp = ("error", "not-leader")
                except Exception as exc:
                    resp = ("error", f"failed: {exc}")
                send(req_id, resp)
                continue
            if op == "membership":
                with self._lock:
                    send(req_id, ("ok",
                                  self.svc.membership_status()))
                continue
            if op == "fleet":
                # fleet verbs (leader-routed like ops: only the
                # leader holds links to pull) — svcnode's frame
                # grammar, so GroupClient/ServiceClient speak it
                # against a promoted replica too.  NO big lock: the
                # pull blocks on replica round-trips, and holding the
                # lock would stall the flush loop behind it.
                try:
                    sub = args[0] if args else "health"
                    if sub == "metrics":
                        fmt = args[1] if len(args) > 1 else None
                        resp = self.svc.fleet_metrics(
                            "prometheus" if fmt == "prometheus"
                            else None)
                    elif sub == "health":
                        resp = self.svc.fleet_health()
                    elif sub == "timeline":
                        resp = self.svc.fleet_timeline(int(args[1]))
                    else:
                        resp = ("error", "bad-request")
                except Exception:
                    resp = ("error", "failed")
                send(req_id, resp)
                continue
            if op in ("create_ensemble", "destroy_ensemble",
                      "resolve_ensemble"):
                # synchronous replicated lifecycle (quorum-barriered)
                try:
                    with self._lock:
                        if op == "create_ensemble":
                            view = args[1] if len(args) > 1 else None
                            if args[0] in self.svc._ens_names:
                                # duplicate != capacity: an
                                # orchestrator reacting to
                                # "no-capacity" must not act on a
                                # false premise (review r4)
                                resp = ("error", "exists")
                            else:
                                row = self.svc.create_ensemble(
                                    args[0], view)
                                resp = (("ok", row)
                                        if row is not None
                                        else ("error", "no-capacity"))
                        elif op == "destroy_ensemble":
                            resp = (("ok",)
                                    if self.svc.destroy_ensemble(
                                        args[0])
                                    else ("error", "unknown"))
                        else:
                            row = self.svc.resolve_ensemble(args[0])
                            resp = (("ok", row) if row is not None
                                    else ("error", "unknown"))
                except DeposedError:
                    # deposed between the role check and the lock: the
                    # op was never dispatched — clients re-route
                    resp = ("error", "not-leader")
                except Exception:
                    resp = ("error", "failed")
                send(req_id, resp)
                continue
            try:
                with self._lock:
                    fut = self._dispatch(op, args)
            except Exception:
                send(req_id, ("error", "bad-request"))
                continue
            if fut is None:
                send(req_id, ("error", "unknown-op"))
                continue
            fut.add_waiter(
                lambda result, rid=req_id: send(rid, result))

    def _follower_read(self, op: str, args: tuple):
        """Serve one read verb off this REPLICA's host mirrors under
        the leader-granted lease (docs/ARCHITECTURE.md §16), or None
        when anything disqualifies it: lease lapsed/margin-expired,
        the ensemble carries applied-but-unconfirmed writes, or any
        requested key's mirror state is incomplete.  All-or-nothing
        per request — a partially-mirror-served batch would interleave
        two consistency regimes inside one reply."""
        svc = self.svc
        if not args:
            return None
        ens = args[0]
        if type(ens) is not int or not 0 <= ens < svc.n_ens:
            return None
        if not self.core._flw_serve_ok(ens):
            svc.group_stats["follower_reads_blocked"] += 1
            return None
        want_vsn = op == "kget_vsn"
        if op in ("kget", "kget_vsn"):
            keys = [args[1]]
        elif op == "kget_many":
            keys = list(args[1])
            want_vsn = bool(args[2]) if len(args) > 2 else False
        else:  # kget_slab
            from riak_ensemble_tpu.svcnode import _slab_keys
            keys = _slab_keys(args[1], args[2])
            want_vsn = bool(args[3]) if len(args) > 3 else False
        nf = (("ok", NOTFOUND, (0, 0)) if want_vsn
              else ("ok", NOTFOUND))
        ks = svc.key_slot[ens]
        out = []
        for key in keys:
            slot = ks.get(key)
            if slot is None:
                out.append(nf)
                continue
            reason, r = svc._fast_read_result(ens, slot, want_vsn)
            if reason is not None:
                svc.group_stats["follower_reads_blocked"] += 1
                return None
            out.append(r)
        svc.group_stats["follower_reads_served"] += len(out)
        return out if op in ("kget_many", "kget_slab") else out[0]

    def _dispatch(self, op: str, args: tuple):
        svc = self.svc
        if args:
            ens = args[0]
            if type(ens) is not int or not 0 <= ens < svc.n_ens:
                raise ValueError(f"bad ensemble index {ens!r}")
        if op in ("kput_slab", "kget_slab"):
            # the proxy tier forwards whole op slabs here once this
            # host is promoted: same arena decode as svcnode's front
            # door (lazy import dodges the module cycle)
            from riak_ensemble_tpu.svcnode import (_slab_keys,
                                                   _slab_vals)
            if op == "kput_slab":
                return svc.kput_many(ens, _slab_keys(args[1], args[2]),
                                     _slab_vals(args[3], args[4]))
            return svc.kget_many(
                ens, _slab_keys(args[1], args[2]),
                want_vsn=bool(args[3]) if len(args) > 3 else False)
        fns = {"kput": svc.kput, "kget": svc.kget,
               "kget_vsn": svc.kget_vsn, "kupdate": svc.kupdate,
               "kput_once": svc.kput_once, "kmodify": svc.kmodify,
               "kdelete": svc.kdelete,
               "ksafe_delete": svc.ksafe_delete,
               "kput_many": svc.kput_many, "kget_many": svc.kget_many,
               "kupdate_many": svc.kupdate_many,
               "kdelete_many": svc.kdelete_many}
        fn = fns.get(op)
        return None if fn is None else fn(*args)

    def stop(self) -> None:
        self._stop = True
        self._repl_srv.close()
        self._client_srv.close()
        self.svc.stop()


class _ThreadedAcceptor:
    """Minimal threaded TCP acceptor: one handler thread per
    connection (the group has a handful of peers, not thousands)."""

    def __init__(self, host: str, port: int, handler) -> None:
        self._handler = handler
        self._sock = socket.create_server((host, port))
        self.port = self._sock.getsockname()[1]
        self._closed = False
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # daemon handler threads need no tracking: they die with
            # their connection (and the process)
            threading.Thread(target=self._run_conn, args=(conn,),
                             daemon=True).start()

    def _run_conn(self, conn: socket.socket) -> None:
        try:
            self._handler(conn)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class GroupClient:
    """Leader-discovering client for a replication group: give it the
    hosts' CLIENT ports and it finds (and sticks to) the leader,
    re-discovering on connection loss or ``("error", "not-leader")``
    rejections — the role the reference's leader-routing client plays
    (riak_ensemble_client via the router's leader cache).

    Retry discipline mirrors the wire client's ambiguity rules:
    a **not-leader rejection is safely retried** (the op was never
    dispatched into a flush), while a ``DISCONNECTED`` mid-op result
    stays ambiguous and surfaces to the caller — auto-retrying a
    write whose first attempt may have committed would double-apply.
    """

    #: per-host TCP connect budget during discovery: a blackholed
    #: machine (the very failure this client routes around) must cost
    #: seconds, not the OS SYN-retry timeout
    CONNECT_TIMEOUT = 5.0

    def __init__(self, hosts, op_timeout: float = 30.0,
                 discover_timeout: float = 60.0) -> None:
        import asyncio

        from riak_ensemble_tpu import svcnode

        self._svcnode = svcnode
        self.hosts = [(str(h), int(p)) for h, p in hosts]
        self.op_timeout = op_timeout
        self.discover_timeout = discover_timeout
        self._client = None
        self._leader_addr = None
        #: serializes discovery so concurrent ops on a fresh client
        #: can't each open (and leak) their own connection.  Ops
        #: themselves still pipeline on the shared connection; a
        #: leader change mid-overlap may turn a sibling op's result
        #: ambiguous (DISCONNECTED) — within the documented contract.
        self._dlock = asyncio.Lock()

    async def _discover(self, budget: Optional[float] = None):
        import asyncio

        deadline = time.monotonic() + (self.discover_timeout
                                       if budget is None else budget)
        async with self._dlock:
            if self._client is not None:  # a sibling already found it
                return self._client
            while time.monotonic() < deadline:
                for addr in self.hosts:
                    c = self._svcnode.ServiceClient(*addr)
                    try:
                        await asyncio.wait_for(c.connect(),
                                               self.CONNECT_TIMEOUT)
                        st = await c.call("stats", timeout=10.0)
                    except (OSError, ConnectionError,
                            asyncio.TimeoutError):
                        await c.close()
                        continue
                    if isinstance(st, dict) \
                            and st.get("group", {}).get("leader"):
                        self._client, self._leader_addr = c, addr
                        return c
                    await c.close()
                await asyncio.sleep(1.0)
        raise TimeoutError(
            f"no leader found among {self.hosts} within the budget")

    async def call(self, op: str, *args, retryable: bool = False):
        """One op against the current leader, re-discovering and
        retrying ONLY on safe-to-retry outcomes: not-leader
        rejections (never dispatched) always retry; 'failed' retries
        only for ``retryable`` ops (reads — side-effect-free, and a
        fresh leader legitimately answers 'failed' while re-syncing
        its quorum) and only within one op_timeout — a permanently
        dead ensemble also answers 'failed', and that must surface,
        not spin; ambiguous losses surface as DISCONNECTED.  The
        whole call is bounded by ~discover_timeout: nested discovery
        consumes the call's remaining budget, never a fresh one."""
        import asyncio

        deadline = time.monotonic() + self.discover_timeout
        failed_deadline = None
        while True:
            c = self._client
            if c is None:
                c = await self._discover(
                    max(1.0, deadline - time.monotonic()))
            try:
                r = await c.call(op, *args, timeout=self.op_timeout)
            except asyncio.TimeoutError:
                r = self._svcnode.ServiceClient.DISCONNECTED
            if r == ("error", "not-leader"):
                await self._drop(c)
                if time.monotonic() < deadline:
                    continue
            if retryable and r == "failed":
                now = time.monotonic()
                if failed_deadline is None:
                    failed_deadline = min(deadline,
                                          now + self.op_timeout)
                if now < failed_deadline:
                    await asyncio.sleep(0.5)
                    continue
            if r == self._svcnode.ServiceClient.DISCONNECTED:
                # ambiguous: hand it to the caller, but drop the
                # connection so the NEXT op re-discovers
                await self._drop(c)
            return r

    async def _drop(self, failed=None) -> None:
        """Compare-and-drop: only tear down the shared connection if
        it is STILL the one that failed — a stale result from an old
        connection must not close a freshly discovered healthy leader
        out from under sibling ops."""
        if failed is not None and self._client is not failed:
            await failed.close()
            return
        if self._client is not None:
            await self._client.close()
        self._client = None
        self._leader_addr = None

    async def close(self) -> None:
        await self._drop()

    # the common keyed surface
    async def kput(self, ens, key, value):
        return await self.call("kput", ens, key, value)

    async def kget(self, ens, key):
        return await self.call("kget", ens, key, retryable=True)

    async def kget_vsn(self, ens, key):
        return await self.call("kget_vsn", ens, key, retryable=True)

    async def kupdate(self, ens, key, vsn, value):
        return await self.call("kupdate", ens, key, tuple(vsn), value)

    async def kdelete(self, ens, key):
        return await self.call("kdelete", ens, key)

    async def kmodify(self, ens, key, fnref, default):
        return await self.call("kmodify", ens, key, tuple(fnref),
                               default)

    # group administration (leader-routed like any op)
    async def update_group_members(self, hosts):
        return await self.call(
            "update_group_members",
            tuple((str(h), int(p)) for h, p in hosts))

    async def membership(self):
        return await self.call("membership", retryable=True)


# -- CLI ---------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="replication-group replica host")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--repl-port", type=int, default=0)
    ap.add_argument("--client-port", type=int, default=0)
    ap.add_argument("--n-ens", type=int, default=64)
    ap.add_argument("--group-size", type=int, default=3)
    ap.add_argument("--n-slots", type=int, default=32)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--fast", action="store_true")
    ap.add_argument("--peer", action="append", default=[],
                    metavar="HOST:PORT",
                    help="another replica host's replication port "
                         "(repeat per peer; required for "
                         "--auto-failover)")
    ap.add_argument("--dynamic", action="store_true",
                    help="dynamic tenant lifecycle (replicated "
                         "create/destroy over the group)")
    ap.add_argument("--advertise", default=None, metavar="HOST:PORT",
                    help="this host's identity in group-config member "
                         "lists (defaults to bind host + repl port; "
                         "required when binding wildcard/NAT'd "
                         "addresses)")
    ap.add_argument("--auto-failover", type=float, default=None,
                    metavar="SECONDS",
                    help="self-promote when no leader traffic for "
                         "this long and this host ranks first among "
                         "reachable peers")
    ap.add_argument("--trust-host-lease", action="store_true",
                    help="serve lease-protected fast reads when this "
                         "host leads (opt-in: trusts the host-quorum "
                         "lease between settles — see "
                         "docs/ARCHITECTURE.md §9)")
    ap.add_argument("--follower-reads", action="store_true",
                    help="serve kget* from this REPLICA's mirrors "
                         "under leader-granted epoch-fenced read "
                         "leases, and (as leader) grant them "
                         "(docs/ARCHITECTURE.md §16; also "
                         "RETPU_FOLLOWER_READS=1)")
    args = ap.parse_args(argv)

    from riak_ensemble_tpu.config import fast_test_config

    peers = []
    for spec in args.peer:
        h, p = spec.rsplit(":", 1)
        peers.append((h, int(p)))
    adv = None
    if args.advertise:
        h, p = args.advertise.rsplit(":", 1)
        adv = (h, int(p))
    srv = ReplicaServer(
        args.n_ens, args.group_size, args.n_slots,
        repl_port=args.repl_port, client_port=args.client_port,
        host=args.host, data_dir=args.data_dir,
        config=fast_test_config() if args.fast else None,
        peers=peers, auto_failover=args.auto_failover,
        dynamic=args.dynamic, advertise=adv,
        trust_host_lease=args.trust_host_lease,
        follower_reads=args.follower_reads or None)
    print(f"repgroup replica repl={srv.repl_port} "
          f"client={srv.client_port}", flush=True)
    fp = faults.active_plan()
    if fp is not None:
        # a replica host started under fault-injection knobs is part
        # of a nemesis — say so once, loudly, so its injected fsync
        # delays / drops are never read as a real incident
        print(f"repgroup replica: FAULT INJECTION ACTIVE "
              f"{fp.describe()!r}", file=sys.stderr, flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
