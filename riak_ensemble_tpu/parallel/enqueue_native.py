"""ctypes bridge to the native enqueue-pack kernel
(``native/enqueuekernel.cc`` — the sibling of ``resolvekernel.cc``,
compiled into the same ``_retpu_resolve.so``).

PR 7 moved the per-flush RESOLVE half to C++ and the latency breakdown
promptly showed the remaining host cost on the ENQUEUE half
(``queue_wait`` + per-op future fan-out — ROADMAP item 4).  The
service now carries each flush's pending ops as flat int32 LANES and
this module exposes the C++ pass that scatters all five ``[K, E]`` op
planes from those lanes in one traversal.

Knob discipline mirrors :mod:`.resolve_native` exactly:

- ``RETPU_NATIVE_ENQUEUE=0`` opts the service out of the whole
  slab-resident enqueue path — per-entry plane pack and per-op future
  fan-out run as before (the oracle arm of
  ``tests/test_native_enqueue.py`` and the bench's
  ``enqueue_native_speedup`` A/B).
- Knob on but no toolchain / stale .so: the slab path still runs, with
  the plane pack through numpy fancy indexing (the ``enqueue_fallback``
  arm) — graceful degradation, never a crash.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from riak_ensemble_tpu.utils import native

__all__ = ["enabled", "get", "NativeEnqueue"]

_instance: Optional["NativeEnqueue"] = None
_instance_tried = False


def enabled() -> bool:
    """The ``RETPU_NATIVE_ENQUEUE`` knob (default on): ``0`` pins the
    historical per-entry pack + per-op future fan-out — the oracle arm
    of the equivalence tests and the bench A/B."""
    return os.environ.get("RETPU_NATIVE_ENQUEUE", "1") != "0"


def get() -> Optional["NativeEnqueue"]:
    """The loaded kernel wrapper, or None when the knob is off, the
    toolchain can't build the .so, or the .so predates the enqueue
    symbols (callers then use the numpy lane pack).  Re-reads the knob
    per call (a service constructed under ``RETPU_NATIVE_ENQUEUE=0``
    never picks the kernel up); the library handle builds once."""
    global _instance, _instance_tried
    if not enabled():
        return None
    if not _instance_tried:
        _instance_tried = True
        lib = native.load_resolve()
        if lib is not None and hasattr(lib, "retpu_enqueue_pack") \
                and hasattr(lib, "retpu_enqueue_gather") \
                and lib.retpu_enqueue_version() >= 2:
            _instance = NativeEnqueue(lib)
    return _instance


def _pt(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


class NativeEnqueue:
    """Thin wrapper over the C ABI; outputs are written in place and
    are bit-identical to the numpy fallback's.  Both passes walk the
    pending slab's RUN DESCRIPTORS — per taken entry its ensemble
    column, first plane row, run length and uniform op kind — so the
    Python→C conversion cost scales with entries, not ops."""

    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib

    def pack(self, k: int, e: int, ent_col: np.ndarray,
             ent_row0: np.ndarray, ent_len: np.ndarray,
             ent_kind: np.ndarray, slot: np.ndarray, val: np.ndarray,
             expe: np.ndarray, exps: np.ndarray,
             kind_p: np.ndarray, slot_p: np.ndarray,
             val_p: np.ndarray, expe_p: np.ndarray,
             exps_p: np.ndarray) -> bool:
        """Scatter the pending slab into the five zero-initialized
        ``[K, E]`` int32 planes in one C traversal.  False on an
        out-of-grid run (the caller re-packs through the numpy path,
        which raises the honest IndexError)."""
        rc = self._lib.retpu_enqueue_pack(
            len(ent_col), k, e, _pt(ent_col), _pt(ent_row0),
            _pt(ent_len), _pt(ent_kind), _pt(slot), _pt(val),
            _pt(expe), _pt(exps), _pt(kind_p), _pt(slot_p),
            _pt(val_p), _pt(expe_p), _pt(exps_p))
        return rc == 0

    def gather(self, k: int, e: int, ent_col: np.ndarray,
               ent_row0: np.ndarray, ent_len: np.ndarray,
               committed: np.ndarray, get_ok: np.ndarray,
               found: np.ndarray, value: np.ndarray,
               vsn: np.ndarray, n_rows: int):
        """Result planes → completion slab: ``[R]`` records in taken
        order (ok, get_ok, found, value, vsn[R, 2]), one C traversal.
        None on a layout surprise (the caller falls back to the numpy
        gather)."""
        out_ok = np.empty((n_rows,), np.uint8)
        out_gok = np.empty((n_rows,), np.uint8)
        out_fnd = np.empty((n_rows,), np.uint8)
        out_val = np.empty((n_rows,), np.int32)
        out_vsn = np.empty((n_rows, 2), np.int32)
        rc = self._lib.retpu_enqueue_gather(
            len(ent_col), k, e, _pt(ent_col), _pt(ent_row0),
            _pt(ent_len), _pt(committed), _pt(get_ok), _pt(found),
            _pt(value), _pt(vsn), _pt(out_ok), _pt(out_gok),
            _pt(out_fnd), _pt(out_val), _pt(out_vsn))
        if rc != 0:
            return None
        return (out_ok.view(bool), out_gok.view(bool),
                out_fnd.view(bool), out_val, out_vsn)
