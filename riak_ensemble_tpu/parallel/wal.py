"""Write-ahead durability for the batched service's acked writes.

The reference never acks a write that is not on disk: the basic
backend saves synchronously on every put
(``riak_ensemble_basic_backend.erl:120-125``, ``save_data:181-187``)
and facts coalesce to disk within 50 ms
(``riak_ensemble_storage.erl:86-103``).  The batched service acks from
device+host memory, so between explicit checkpoints it needs exactly
this: a log of committed client writes that is forced to disk BEFORE
the client futures resolve, and replayed over the latest checkpoint on
restart.

The store is *latest-record-per-(ensemble, slot)* — not a strictly
ordered log — because that is all recovery needs: the newest committed
(epoch, seq, payload) per slot, plus committed membership rows.
Commutative-lane merge cells (docs/ARCHITECTURE.md §18) need no new
record kind for the same reason: the apply writes the slot's ABSOLUTE
post-merge value (never an operand delta) at the section's high-water
(epoch, seq), so latest-per-key replay reconstructs exactly the state
a sequenced apply would have logged.  The
C++ treestore (``native/treestore.cc``: CRC-framed append log +
in-memory ordered index + snapshot compaction) provides those
semantics natively and is used when the toolchain is available;
:class:`PyLogStore` is the byte-compatible-enough pure-Python fallback
(same interface, its own CRC-framed append log).

WAL generations pair with checkpoint generations: checkpoint ``n``
subsumes every record in ``wal.<n-...>``, so each :meth:`rotate`
starts a fresh ``wal.<n>`` directory and deletes the old ones — there
is no in-place truncate to get wrong.
"""

from __future__ import annotations

import errno as _errno
import os
import pickle
import struct
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from riak_ensemble_tpu import faults
from riak_ensemble_tpu.save import fsync_dir

#: sync modes: "fsync" forces records to stable storage before the ack
#: (power-loss safe — the basic_backend put contract); "buffer" writes
#: through the OS page cache without fsync (process-crash safe; an OS
#: crash can lose the tail — the coalesced-facts RPO rationale,
#: storage.erl:21-39).
SYNC_MODES = ("fsync", "buffer")


class PyLogStore:
    """Pure-Python latest-per-key store over a CRC-framed append log.

    Interface-compatible subset of
    :class:`riak_ensemble_tpu.synctree.native_store.NativeBackend`:
    ``store/delete/fetch/keys/count/sync/close``.  Torn or corrupt
    tail records are dropped at replay (the crash happened mid-append;
    everything acked before it had already been synced).
    """

    _MAGIC = b"RWAL"

    def __init__(self, path: str) -> None:
        self.path = path
        self._map: Dict[bytes, bytes] = {}
        #: corruption evidence counters (stats(): a detected-but-
        #: handled bad disk must be observable, never silent)
        self.quarantines = 0
        self.truncations = 0
        self.truncated_bytes = 0
        #: CRC-failed frames that were re-read (a retry that passes
        #: is a healed transient read error, not a torn tail)
        self.read_retries = 0
        #: failed appends whose partial frame was truncated back to
        #: the frame boundary (review r15: a surviving writer must
        #: repair the tail, or later fsync-acked appends land after
        #: the tear and are destroyed at the next replay)
        self.append_repairs = 0
        #: a failed append whose REPAIR also failed leaves the tail
        #: unknown — every further append must fail fast rather than
        #: write records replay may never reach
        self._tail_unknown = False
        good = self._replay()
        if good is not None:
            # Truncate the torn/corrupt tail BEFORE appending: records
            # appended after garbage would be unreachable at every
            # future replay — acked writes silently lost on the second
            # crash (the replay correctly stops at the tear, so the
            # bytes past `good` were never acked data we could keep).
            self.truncations += 1
            self.truncated_bytes += max(
                0, os.path.getsize(self.path) - good)
            with open(self.path, "r+b") as f:
                f.truncate(good)
        # checked AFTER replay: a quarantine moved the old log aside,
        # so the append handle below creates a genuinely new file
        existed = os.path.exists(path)
        self._f = open(path, "ab")
        if not existed:
            # a crash may keep the rename/creat un-durable without a
            # directory fsync (ext4/xfs); a lost wal FILE would read
            # as "no records" — silent loss of every fsync-acked write
            fsync_dir(os.path.dirname(path) or ".")

    def _quarantine(self) -> None:
        """Move the unreplayable log aside for forensics WITHOUT
        clobbering earlier evidence: monotonic ``.corrupt.<n>``
        suffixes (a second corruption used to overwrite the first)."""
        n = 0
        while os.path.exists(f"{self.path}.corrupt.{n}"):
            n += 1
        os.replace(self.path, f"{self.path}.corrupt.{n}")
        fsync_dir(os.path.dirname(self.path) or ".")
        self.quarantines += 1

    def _replay(self) -> Optional[int]:
        """Rebuild the map from the log.  Returns the byte offset of
        the first bad record (caller truncates there), or None when
        the whole file parsed clean."""
        try:
            f = open(self.path, "rb")
        except FileNotFoundError:
            return None
        with f:
            head4 = f.read(4)
            if head4 == b"":
                return None
            if head4 != self._MAGIC:
                # Foreign/corrupt prefix: nothing here is replayable,
                # and appending after it would hide every future
                # record too.  Preserve the bytes for forensics and
                # start a fresh log.
                f.close()
                self._quarantine()
                return None
            off = 4
            while True:
                head = f.read(8)
                if len(head) < 8:
                    return off if head else None
                crc, ln = struct.unpack(">II", head)
                body = faults.read_filter("wal", f.read(ln))
                if len(body) == ln and zlib.crc32(body) != crc:
                    # CRC mismatch on a FULL frame: re-read the frame
                    # FROM DISK once before believing it — a
                    # transient bad read (bus/memory, or the injected
                    # bit flip) heals on a real re-read, true on-disk
                    # damage does not.  Without this, a transient
                    # flip would be "repaired" by truncating HEALTHY
                    # fsync-acked frames behind it (review r15).
                    self.read_retries += 1
                    f.seek(off + 8)
                    body = faults.read_filter("wal", f.read(ln))
                if len(body) < ln or zlib.crc32(body) != crc or ln < 5:
                    return off  # torn/corrupt tail
                op = body[0]
                klen = struct.unpack(">I", body[1:5])[0]
                if 5 + klen > ln:
                    return off
                key = body[5:5 + klen]
                if op == 1:
                    self._map[key] = body[5 + klen:]
                elif op == 2:
                    self._map.pop(key, None)
                else:
                    return off
                off += 8 + ln

    def _append(self, op: int, key: bytes, val: bytes) -> None:
        if self._tail_unknown:
            raise OSError(
                _errno.EIO,
                "WAL tail unknown after an unrepaired failed append; "
                "refusing to write records replay may never reach")
        faults.storage_raise("wal", "write")
        if self._f.tell() == 0:
            self._f.write(self._MAGIC)
        body = bytes([op]) + struct.pack(">I", len(key)) + key + val
        frame = struct.pack(">II", zlib.crc32(body), len(body)) + body
        start = self._f.tell()
        cut = faults.torn_limit("wal")
        if cut is not None:
            # torn write: the prefix reaches the disk and the writer
            # SEES the failure — so it must repair the frame
            # boundary before any later append, or those later
            # (fsync-acked!) records land after the tear and the
            # next replay's truncate-at-tear destroys them (review
            # r15).  Crash-mid-write tears — where no repair can run
            # — are the crash-point and replay-fuzz tests' domain.
            self._f.write(frame[:min(cut, max(0, len(frame) - 1))])
            self._f.flush()
            self._repair_tail(start)
            raise OSError(_errno.EIO,
                          f"injected torn WAL write at byte {cut}")
        try:
            self._f.write(frame)
        except OSError:
            self._repair_tail(start)
            raise

    def _repair_tail(self, start: int) -> None:
        """Truncate a partial frame back to its start; a repair that
        itself fails poisons the store (fail-fast appends)."""
        try:
            self._f.truncate(start)
            # truncate() does NOT move the buffered stream position,
            # and O_APPEND writes ignore it — but tell() would keep
            # reporting the pre-repair offset, so the NEXT failed
            # append would repair at a stale `start`, zero-padding a
            # hole that destroys later fsync-acked records at replay
            # (review r15, reproduced).  Re-anchor at the real EOF.
            self._f.seek(0, os.SEEK_END)
            self.append_repairs += 1
        except OSError:
            self._tail_unknown = True

    def store(self, key: Any, value: Any) -> None:
        k, v = pickle.dumps(key, protocol=4), pickle.dumps(value,
                                                           protocol=4)
        self._map[k] = v
        self._append(1, k, v)

    def store_raw(self, k: bytes, v: bytes) -> None:
        """Append a pre-pickled record verbatim (the native resolve
        kernel's arena path) — byte-identical log framing to
        :meth:`store` of the decoded terms."""
        self._map[k] = v
        self._append(1, k, v)

    def delete(self, key: Any) -> None:
        k = pickle.dumps(key, protocol=4)
        self._map.pop(k, None)
        self._append(2, k, b"")

    def fetch(self, key: Any, default: Any = None) -> Any:
        v = self._map.get(pickle.dumps(key, protocol=4))
        return default if v is None else pickle.loads(v)

    def keys(self) -> Iterable[Any]:
        return [pickle.loads(k) for k in self._map]

    def count(self) -> int:
        return len(self._map)

    def sync(self) -> None:
        self._f.flush()
        faults.storage_raise("wal", "fsync")
        os.fsync(self._f.fileno())

    def flush(self) -> None:
        """Push buffered records to the OS page cache (no fsync) —
        the process-crash durability floor of buffer mode."""
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None


def _open_store(path: str):
    """Native treestore when buildable, Python log otherwise.  Either
    way the store consults the ``wal`` storage-fault class (the
    native backend defaults to ``tree`` for synctree use)."""
    from riak_ensemble_tpu.synctree import native_store

    if native_store.available():
        st = native_store.NativeBackend(path)
        st.fault_class = "wal"
        return st
    return PyLogStore(path)


class ServiceWAL:
    """One WAL generation: committed write records under ``dir_path``.

    Record keys/values (pickled by the store layer):

    - ``("kv", ens, slot)`` → ``(key_obj, handle, epoch, seq, payload,
      inline)`` — a committed client write.  ``payload`` is the host
      payload-store bytes behind ``handle`` (None for tombstones);
      ``inline=True`` marks bulk-array writes whose int32 value IS the
      payload (no handle indirection).
    - ``("mem", ens)`` → ``list[bool]`` — a committed membership row.
    """

    def __init__(self, dir_path: str, sync_mode: str = "fsync") -> None:
        assert sync_mode in SYNC_MODES, sync_mode
        os.makedirs(dir_path, exist_ok=True)
        # a freshly-created generation directory must itself survive a
        # crash: fsync the parent so ``wal.<n>`` is reachable after
        # power loss (rename/mkdir alone is not durable on ext4/xfs)
        fsync_dir(os.path.dirname(dir_path) or ".")
        self.dir_path = dir_path
        self.sync_mode = sync_mode
        self._store = _open_store(os.path.join(dir_path, "wal"))
        #: fault-injection seam (docs/ARCHITECTURE.md §13): called
        #: immediately BEFORE every durability barrier this WAL
        #: forces (the fsync the ack waits on), so an injected fsync
        #: delay lands exactly where a slow disk would.  Defaults to
        #: the process-global fault plane's sleep (a no-op without an
        #: active ``RETPU_FAULT_FSYNC_MS``/programmatic plan);
        #: assign a callable for a WAL-local override.
        self.sync_hook: Callable[[], None] = faults.fsync_sleep
        # The underlying stores are not thread-safe; a replica host's
        # promise grants (connection threads) and its apply/campaign
        # writes (other threads) share one WAL.
        import threading
        self._lock = threading.Lock()

    def log(self, records: List[Tuple[Any, Any]]) -> None:
        """Append a batch and make it durable per the sync mode.  MUST
        complete before the writes it covers are acked."""
        with self._lock:
            faults.crashpoint("wal_append")
            for key, value in records:
                self._store.store(key, value)
            if self.sync_mode == "fsync":
                faults.crashpoint("wal_fsync_pre")
                self.sync_hook()
                self._store.sync()
                faults.crashpoint("wal_fsync_post")
            else:
                # buffer mode promises PROCESS-crash safety: the
                # records must at least reach the kernel before the
                # ack — a userspace io buffer dies with the process.
                self._flush_store()

    def _flush_store(self) -> None:
        flush = getattr(self._store, "flush", None)
        if flush is not None:
            flush()
        else:  # pragma: no cover - older store without flush-only
            self._store.sync()

    def log_arena(self, arena, index, extra_records=()) -> None:
        """Append pre-encoded (protocol-4 pickled) record pairs — the
        native resolve kernel's byte arena — VERBATIM, plus ordinary
        ``extra_records``, under the same single lock + sync barrier
        as :meth:`log`.  ``index`` rows are (key_off, key_len,
        val_off, val_len) into ``arena``; the resulting store contents
        are byte-identical to ``log()`` of the decoded records (the
        native/fallback equivalence contract)."""
        with self._lock:
            faults.crashpoint("wal_append")
            st = self._store
            put_many = getattr(st, "put_many_raw", None)
            if put_many is not None:
                put_many(arena, index)
            else:
                for koff, klen, voff, vlen in index.tolist():
                    st.store_raw(bytes(arena[koff:koff + klen]),
                                 bytes(arena[voff:voff + vlen]))
            for key, value in extra_records:
                st.store(key, value)
            if self.sync_mode == "fsync":
                faults.crashpoint("wal_fsync_pre")
                self.sync_hook()
                self._store.sync()
                faults.crashpoint("wal_fsync_post")
            else:
                self._flush_store()

    def delete(self, keys: List[Any]) -> None:
        """Remove records (e.g. a destroyed ensemble's kv entries)
        with the same durability barrier as :meth:`log`."""
        with self._lock:
            faults.crashpoint("wal_append")
            for key in keys:
                self._store.delete(key)
            if self.sync_mode == "fsync":
                faults.crashpoint("wal_fsync_pre")
                self.sync_hook()
                self._store.sync()
                faults.crashpoint("wal_fsync_post")
            else:
                # Mirror log(): buffer mode still promises
                # process-crash durability, and a destroy's kv
                # deletions sitting in the userspace stdio buffer
                # would die with the process — the destroyed tenant's
                # records would replay into a recycled row (ADVICE r3).
                self._flush_store()

    def records(self) -> List[Tuple[Any, Any]]:
        with self._lock:
            return [(k, self._store.fetch(k))
                    for k in self._store.keys()]

    @property
    def count(self) -> int:
        with self._lock:
            return self._store.count()

    def evidence(self) -> Dict[str, Any]:
        """LOCK-FREE read of the store's corruption-handling
        counters (monotonic plain ints, set at open time) — for the
        health/metrics scrape paths, which must never block behind a
        flush holding the lock across a slow fsync."""
        st = self._store
        return {
            "quarantines": int(getattr(st, "quarantines", 0)),
            "truncations": int(getattr(st, "truncations", 0)),
            "truncated_bytes": int(getattr(st, "truncated_bytes", 0)),
            "read_retries": int(getattr(st, "read_retries", 0)),
            "append_repairs": int(getattr(st, "append_repairs", 0)),
        }

    def stats(self) -> Dict[str, Any]:
        """Durability-evidence snapshot: record depth plus the
        store's corruption-handling counters (quarantined logs, torn-
        tail truncations) — what "the bad disk was detected, not
        served" looks like from stats()/health()."""
        with self._lock:
            records = self._store.count()
        return {
            "records": records,
            "sync_mode": self.sync_mode,
            **self.evidence(),
        }

    def close(self) -> None:
        self._store.close()

    # -- generation management -------------------------------------------

    @staticmethod
    def gen_path(base_dir: str, gen: int) -> str:
        return os.path.join(base_dir, f"wal.{gen}")

    @classmethod
    def open_gen(cls, base_dir: str, gen: int,
                 sync_mode: str = "fsync") -> "ServiceWAL":
        return cls(cls.gen_path(base_dir, gen), sync_mode)

    @classmethod
    def rotate(cls, base_dir: str, new_gen: int, old: "ServiceWAL",
               sync_mode: str = "fsync") -> "ServiceWAL":
        """Start generation ``new_gen`` (its records begin empty) and
        drop every older generation — call only AFTER checkpoint
        ``new_gen`` is fully committed (CURRENT flipped)."""
        import shutil

        old.close()
        nw = cls.open_gen(base_dir, new_gen, sync_mode)
        for name in os.listdir(base_dir):
            if name.startswith("wal.") and name != f"wal.{new_gen}":
                shutil.rmtree(os.path.join(base_dir, name),
                              ignore_errors=True)
        return nw
