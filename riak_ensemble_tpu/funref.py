"""Symbolic references to registered modify functions.

The reference never ships closures between nodes: a kmodify carries an
``{Module, Function, Args}`` triple and the put FSM applies it by name
(``riak_ensemble_peer.erl:303-317``, ``riak_ensemble_root.erl:82,104``).
This module is that mechanism for the TPU framework: protocol events
carry ``("fn", name, bound_args)`` tuples — plain data the restricted
wire codec can ship — and the executing peer resolves the name against
a process-local registry of functions registered at import time.

Live callables still pass through :func:`resolve` untouched, so
in-process tests (and the root leader's local gossip kmodify) can keep
using real closures; they simply are not wire-encodable, same as any
other local-only message.

**The device mod-fun table.**  A registered name may additionally be
*device-expressible*: a small fixed family of int32 modify functions
(add/sub/max/min/set/band/bor/bxor with one bound int32 operand, plus
put-if-absent) that the batched engine can run INSIDE a consensus
round as an ``OP_RMW`` op — the fun code rides the op's ``exp_epoch``
plane and the operand its ``val`` plane (:mod:`..ops.engine`).  The
service's kmodify fast-paths funrefs that resolve to table entries:
the read, the fun and the commit fuse into ONE device round under the
round's seq discipline, so device RMWs can never CAS-conflict (the
same reason the reference's kmodify runs its mod-fun inside the
leader's FSM, ``riak_ensemble_peer.erl:303-317``).  Every table entry
is ALSO registered as an ordinary host mod-fun with bit-identical
int32 (wraparound) semantics, so the host-fallback path — and the
device/host equivalence tests — compute the same values.
"""

from __future__ import annotations

import functools
import numbers
from typing import Any, Callable, Dict, Optional, Tuple

_REGISTRY: Dict[str, Callable] = {}

TAG = "fn"

#: device mod-fun table codes — the ``exp_epoch`` plane of an
#: ``OP_RMW`` row carries one of these (ops/engine.py imports them
#: from here: this module is the registry's canonical home and is
#: dependency-free, so the actor plane can import it without jax).
RMW_ADD = 0     # cur + operand            (absent/tombstone cur = 0)
RMW_SUB = 1     # cur - operand
RMW_MAX = 2     # max(cur, operand)
RMW_MIN = 3     # min(cur, operand)
RMW_SET = 4     # operand (unconditional overwrite)
RMW_BAND = 5    # cur & operand
RMW_BOR = 6    # cur | operand
RMW_BXOR = 7    # cur ^ operand
RMW_PIA = 8     # put-if-absent: operand iff nothing committed

#: name -> fun code for device-expressible registered funs
_DEVICE: Dict[str, int] = {}

# -- commutative-replication classification (docs/ARCHITECTURE.md §18) -------
#
# Every device-table fun is tagged by how its applications compose:
#
# - COMMUTATIVE  — add/sub: N applications fold into ONE operand
#   (the int32-wraparound sum; sub is add of the negated operand), so
#   the apply stream can ship a merged operand instead of N cells;
# - SEMILATTICE  — max/min/band/bor: idempotent + commutative +
#   associative, N operands fold by the fun itself;
# - ORDERED      — set/bxor/put_if_absent: the outcome depends on the
#   application ORDER (set: last writer; bxor: parity is commutative
#   but a merged operand could not report per-op computed values;
#   put-if-absent: first writer) — these never leave the per-entry
#   sequenced path.
#
# The fold target is a MERGE class (the wire's per-cell fun byte):
# sub normalizes into MERGE_ADD with a negated operand, so mixed
# add/sub traffic on one slot still coalesces into one cell.

ORDERED = 0
COMMUTATIVE = 1
SEMILATTICE = 2

#: merge-section cell fun codes (disjoint from the RMW_* table codes:
#: they name the FOLD, not the op — applied replica-side against the
#: lane's own current value)
MERGE_ADD = 0   # cur + folded operand (int32 wraparound)
MERGE_MAX = 1   # max(cur, folded operand)
MERGE_MIN = 2   # min(cur, folded operand)
MERGE_AND = 3   # cur & folded operand
MERGE_OR = 4    # cur | folded operand

#: RMW fun code -> replication class
RMW_CLASS: Dict[int, int] = {
    RMW_ADD: COMMUTATIVE,
    RMW_SUB: COMMUTATIVE,
    RMW_MAX: SEMILATTICE,
    RMW_MIN: SEMILATTICE,
    RMW_BAND: SEMILATTICE,
    RMW_BOR: SEMILATTICE,
    RMW_SET: ORDERED,
    RMW_BXOR: ORDERED,
    RMW_PIA: ORDERED,
}

#: RMW fun code -> merge-class code (absent for ORDERED funs)
MERGE_OF: Dict[int, int] = {
    RMW_ADD: MERGE_ADD,
    RMW_SUB: MERGE_ADD,
    RMW_MAX: MERGE_MAX,
    RMW_MIN: MERGE_MIN,
    RMW_BAND: MERGE_AND,
    RMW_BOR: MERGE_OR,
}


def merge_class(code: int) -> Optional[int]:
    """The merge-class code a device RMW fun folds into, or None when
    the fun is ORDERED (must stay on the sequenced path)."""
    return MERGE_OF.get(code)


def fold_operand(code: int, acc: int, operand: int) -> int:
    """Fold one more operand of RMW fun ``code`` into the running
    merged operand ``acc`` — host-exact int32 semantics (the same
    arithmetic the engine kernel and the merge apply run), so leader-
    coalesced and replica-merged values are bit-identical."""
    if code == RMW_ADD:
        return i32(acc + operand)
    if code == RMW_SUB:
        # normalized into MERGE_ADD: subtracting v1 then v2 is adding
        # -(v1 + v2) under int32 wraparound
        return i32(acc - operand)
    if code == RMW_MAX:
        return max(acc, operand)
    if code == RMW_MIN:
        return min(acc, operand)
    if code == RMW_BAND:
        return acc & operand
    if code == RMW_BOR:
        return acc | operand
    raise ValueError(f"fold of ordered RMW fun {code}")


def fold_seed(code: int, operand: int) -> int:
    """The merged-operand seed for the FIRST op of a coalesced cell:
    identity-adjusted for the normalizing funs (sub seeds with the
    negated operand so the cell's merge class is MERGE_ADD)."""
    return i32(-operand) if code == RMW_SUB else i32(operand)


def merge_apply(mcls: int, cur: int, operand: int) -> int:
    """Host mirror of the replica's compiled merge-scatter: apply one
    merged cell against the lane's current value — used for cells
    whose current value was produced earlier in the same apply run
    (the device still holds the pre-run value), and by the
    equivalence tests as the oracle."""
    if mcls == MERGE_ADD:
        return i32(int(cur) + int(operand))
    if mcls == MERGE_MAX:
        return max(int(cur), int(operand))
    if mcls == MERGE_MIN:
        return min(int(cur), int(operand))
    if mcls == MERGE_AND:
        return int(cur) & int(operand)
    if mcls == MERGE_OR:
        return int(cur) | int(operand)
    raise ValueError(f"unknown merge class {mcls}")


def register(name: str) -> Callable[[Callable], Callable]:
    """Decorator: make `fn` addressable on the wire as `name`."""
    def deco(fn: Callable) -> Callable:
        assert name not in _REGISTRY, f"duplicate funref {name}"
        _REGISTRY[name] = fn
        return fn
    return deco


def ref(name: str, *bound: Any) -> Tuple:
    """A wire-safe reference to registered function `name`, with
    `bound` prepended to its call arguments (the Args of an MFA)."""
    assert name in _REGISTRY, f"unregistered funref {name}"
    return (TAG, name, tuple(bound))


def resolve(spec: Any) -> Callable:
    """Spec → callable.  Callables pass through; ``("fn", name,
    bound)`` resolves against the registry; anything else raises."""
    if callable(spec):
        return spec
    if (isinstance(spec, tuple) and len(spec) == 3 and spec[0] == TAG
            and spec[1] in _REGISTRY):
        fn = _REGISTRY[spec[1]]
        return functools.partial(fn, *spec[2]) if spec[2] else fn
    raise ValueError(f"unresolvable function spec: {spec!r}")


# -- device mod-fun table -----------------------------------------------------


def register_device(name: str, code: int
                    ) -> Callable[[Callable], Callable]:
    """Decorator: register ``fn`` as BOTH an ordinary host mod-fun
    (addressable as ``name``) and a device-table entry with fun code
    ``code``.  ``fn`` is the HOST MIRROR — called as
    ``fn(operand, vsn, cur)`` with an int ``cur`` — and must match
    the engine's int32 semantics exactly (the equivalence sweep in
    tests/test_rmw.py pins this)."""
    def deco(fn: Callable) -> Callable:
        register(name)(fn)
        _DEVICE[name] = code
        return fn
    return deco


def is_int32(x: Any) -> bool:
    """An int32-expressible integer operand/default: any Integral
    EXCEPT bool (``ref("rmw:add", True)`` is a caller bug, not an
    operand of 1) — numpy integer scalars qualify, so operands pulled
    from ndarrays don't silently demote to the host retry path."""
    return (isinstance(x, numbers.Integral)
            and not isinstance(x, bool)
            and -(1 << 31) <= int(x) < (1 << 31))


def device_entry(spec: Any) -> Optional[Tuple[int, int]]:
    """``(fun_code, operand)`` when ``spec`` is a funref whose name is
    in the device table and whose bound args are exactly one int32
    operand; None otherwise (the caller keeps the host retry path)."""
    if (isinstance(spec, tuple) and len(spec) == 3 and spec[0] == TAG
            and spec[1] in _DEVICE):
        bound = spec[2]
        if len(bound) == 1 and is_int32(bound[0]):
            return _DEVICE[spec[1]], int(bound[0])
    return None


def device_code(spec: Any) -> Optional[int]:
    """The table code of a funref's NAME alone, whatever its bound
    operand looks like — callers that must route by SEMANTICS (the
    service's put-if-absent delegation) need this even when the
    operand is not int32-expressible, or a non-int operand would
    silently fall into the generic fn path and lose the routing."""
    if isinstance(spec, tuple) and len(spec) == 3 and spec[0] == TAG:
        return _DEVICE.get(spec[1])
    return None


def i32(x: int) -> int:
    """int32 wraparound — the host mirror of device arithmetic."""
    return ((int(x) + (1 << 31)) & 0xFFFFFFFF) - (1 << 31)


def _cur_int(cur: Any) -> int:
    """The host mirror's view of the current value: device RMW reads
    an absent key (or a tombstone) as 0; the service's host path hands
    the fun ``default`` (0) in that case, so ints pass through and
    anything else is a caller error surfacing as a contained
    exception."""
    return int(cur)


@register_device("rmw:add", RMW_ADD)
def _rmw_add(operand, vsn, cur):
    return i32(_cur_int(cur) + operand)


@register_device("rmw:sub", RMW_SUB)
def _rmw_sub(operand, vsn, cur):
    return i32(_cur_int(cur) - operand)


@register_device("rmw:max", RMW_MAX)
def _rmw_max(operand, vsn, cur):
    return max(_cur_int(cur), operand)


@register_device("rmw:min", RMW_MIN)
def _rmw_min(operand, vsn, cur):
    return min(_cur_int(cur), operand)


@register_device("rmw:set", RMW_SET)
def _rmw_set(operand, vsn, cur):
    return operand


@register_device("rmw:band", RMW_BAND)
def _rmw_band(operand, vsn, cur):
    return _cur_int(cur) & operand


@register_device("rmw:bor", RMW_BOR)
def _rmw_bor(operand, vsn, cur):
    return _cur_int(cur) | operand


@register_device("rmw:bxor", RMW_BXOR)
def _rmw_bxor(operand, vsn, cur):
    return _cur_int(cur) ^ operand


@register_device("rmw:put_if_absent", RMW_PIA)
def _rmw_pia(operand, vsn, cur):
    # value 0 is the engine's tombstone/absent encoding, which is what
    # the host path's default-of-0 hands us for an absent key.  NOTE:
    # the service never routes put-if-absent through this mirror on a
    # host-payload key (a live payload of int 0 would read as absent)
    # — it takes the exact-contract kput_once (0,0)-CAS instead; this
    # fn exists for the registry and direct int-domain callers.
    return operand if _cur_int(cur) == 0 else "failed"
