"""Symbolic references to registered modify functions.

The reference never ships closures between nodes: a kmodify carries an
``{Module, Function, Args}`` triple and the put FSM applies it by name
(``riak_ensemble_peer.erl:303-317``, ``riak_ensemble_root.erl:82,104``).
This module is that mechanism for the TPU framework: protocol events
carry ``("fn", name, bound_args)`` tuples — plain data the restricted
wire codec can ship — and the executing peer resolves the name against
a process-local registry of functions registered at import time.

Live callables still pass through :func:`resolve` untouched, so
in-process tests (and the root leader's local gossip kmodify) can keep
using real closures; they simply are not wire-encodable, same as any
other local-only message.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple

_REGISTRY: Dict[str, Callable] = {}

TAG = "fn"


def register(name: str) -> Callable[[Callable], Callable]:
    """Decorator: make `fn` addressable on the wire as `name`."""
    def deco(fn: Callable) -> Callable:
        assert name not in _REGISTRY, f"duplicate funref {name}"
        _REGISTRY[name] = fn
        return fn
    return deco


def ref(name: str, *bound: Any) -> Tuple:
    """A wire-safe reference to registered function `name`, with
    `bound` prepended to its call arguments (the Args of an MFA)."""
    assert name in _REGISTRY, f"unregistered funref {name}"
    return (TAG, name, tuple(bound))


def resolve(spec: Any) -> Callable:
    """Spec → callable.  Callables pass through; ``("fn", name,
    bound)`` resolves against the registry; anything else raises."""
    if callable(spec):
        return spec
    if (isinstance(spec, tuple) and len(spec) == 3 and spec[0] == TAG
            and spec[1] in _REGISTRY):
        fn = _REGISTRY[spec[1]]
        return functools.partial(fn, *spec[2]) if spec[2] else fn
    raise ValueError(f"unresolvable function spec: {spec!r}")
