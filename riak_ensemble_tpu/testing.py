"""Integration-test harness: multi-peer ensembles in one host process.

The reference's central test trick (``test/ens_test.erl:31-45``) is to
run a whole ensemble on ONE Erlang node — peers are just processes.
Here peers are actors in one deterministic virtual-time runtime, so a
multi-second protocol timeline (election, lease expiry, failover) runs
in milliseconds and is reproducible from the seed.

Fault-injection surface (SURVEY §4 parity):
- ``suspend_peer``/``resume_peer``: erlang:suspend_process analog
  (test/basic_test.erl:15-21).
- ``runtime.net.drop_hook`` / ``partition``: message dropping
  (riak_ensemble_msg maybe_drop; sc.erl partitions).
- backend subclasses dropping puts; tree corruption via
  ``tree_of(...).tree.corrupt(...)`` (synctree intercepts).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from riak_ensemble_tpu import peer as peerlib
from riak_ensemble_tpu.config import Config, fast_test_config
from riak_ensemble_tpu.directory import StaticDirectory
from riak_ensemble_tpu.peer import (
    Peer, do_kmodify, do_kput_once, do_kupdate, peer_name, sync_send_event,
)
from riak_ensemble_tpu.runtime import Runtime
from riak_ensemble_tpu.storage import Storage
from riak_ensemble_tpu.types import NOTFOUND, Obj, PeerId


class Cluster:
    def __init__(self, seed: int = 0, config: Optional[Config] = None,
                 data_root: Optional[str] = None) -> None:
        self.runtime = Runtime(seed)
        self.config = config if config is not None else fast_test_config()
        self.directory = StaticDirectory(self.runtime)
        self.data_root = data_root
        self.storages: Dict[str, Storage] = {}

    def storage(self, node: str) -> Storage:
        if node not in self.storages:
            root = (f"{self.data_root}/{node}" if self.data_root else None)
            self.storages[node] = Storage(self.runtime, node, self.config,
                                          root)
        return self.storages[node]

    # -- ensemble lifecycle ------------------------------------------------

    def create_ensemble(self, ensemble: Any, peer_ids: Sequence[PeerId],
                        backend: str = "basic", **peer_kw) -> List[Peer]:
        views = (tuple(peer_ids),)
        peers = []
        for pid in peer_ids:
            peers.append(self.start_peer(ensemble, pid, views, backend,
                                         **peer_kw))
        return peers

    def start_peer(self, ensemble, pid: PeerId, views=None,
                   backend: str = "basic", **peer_kw) -> Peer:
        p = Peer(self.runtime, ensemble, pid, self.config, self.directory,
                 self.storage(pid.node), backend=backend,
                 initial_views=views, **peer_kw)
        self.directory.register_peer(ensemble, pid, p.name)
        return p

    def peer(self, ensemble, pid: PeerId) -> Optional[Peer]:
        return self.runtime.whereis(peer_name(ensemble, pid))

    def tree_of(self, ensemble, pid: PeerId):
        return self.runtime.whereis(peerlib.tree_name(ensemble, pid))

    # -- convergence -------------------------------------------------------

    def leader_id(self, ensemble) -> Optional[PeerId]:
        """The live leader: a non-suspended peer in `leading` state.
        (A suspended ex-leader is still frozen in `leading`; among
        multiple claimants the highest epoch is the real one.)"""
        best = None
        for actor in list(self.runtime.actors.values()):
            if isinstance(actor, Peer) and actor.ensemble == ensemble \
                    and actor.fsm_state == "leading" \
                    and not actor.suspended:
                if best is None or actor.epoch > best.epoch:
                    best = actor
        return best.id if best else None

    def leader(self, ensemble) -> Optional[Peer]:
        lid = self.leader_id(ensemble)
        return self.peer(ensemble, lid) if lid else None

    def wait_leader(self, ensemble, max_time: float = 60.0) -> PeerId:
        ok = self.runtime.run_until(
            lambda: self.leader_id(ensemble) is not None, max_time)
        assert ok, f"no leader for {ensemble} in {max_time}s virtual"
        return self.leader_id(ensemble)

    def wait_stable(self, ensemble, max_time: float = 60.0) -> PeerId:
        """ens_test:wait_stable (ens_test.erl:47-66): poll until a
        leader exists, its tree is ready, and check_quorum succeeds
        (retried — a stale claimant mid-step-down may answer first)."""
        deadline = self.runtime.now + max_time
        while self.runtime.now < deadline:
            ldr = self.leader(ensemble)
            if ldr is None or not ldr.tree_ready:
                self.runtime.run_for(0.05)
                continue
            if self.check_quorum(ensemble) == "ok":
                lid = self.leader_id(ensemble)
                if lid is not None:
                    return lid
            self.runtime.run_for(0.05)
        raise AssertionError(f"{ensemble} not stable in {max_time}s virtual")

    def check_quorum(self, ensemble, timeout: float = 10.0):
        lid = self.leader_id(ensemble)
        if lid is None:
            return "timeout"
        return sync_send_event(self.runtime, peer_name(ensemble, lid),
                               ("check_quorum",), timeout)

    # -- fault injection ---------------------------------------------------

    def suspend_peer(self, ensemble, pid: PeerId) -> None:
        self.runtime.suspend(peer_name(ensemble, pid))

    def resume_peer(self, ensemble, pid: PeerId) -> None:
        self.runtime.resume(peer_name(ensemble, pid))

    # -- K/V surface (client-level ops against an ensemble) ---------------

    def _target(self, ensemble):
        lid = self.leader_id(ensemble) or self.directory.get_leader(ensemble)
        assert lid is not None, "no known leader"
        return peer_name(ensemble, lid)

    def kget(self, ensemble, key, timeout: float = 10.0, opts=()):
        return sync_send_event(self.runtime, self._target(ensemble),
                               ("get", key, tuple(opts)), timeout)

    def kover(self, ensemble, key, value, timeout: float = 10.0):
        return sync_send_event(self.runtime, self._target(ensemble),
                               ("overwrite", key, value), timeout)

    def kput_once(self, ensemble, key, value, timeout: float = 10.0):
        return sync_send_event(self.runtime, self._target(ensemble),
                               ("put", key, do_kput_once, [value]), timeout)

    def kupdate(self, ensemble, key, current: Obj, new, timeout=10.0):
        return sync_send_event(self.runtime, self._target(ensemble),
                               ("put", key, do_kupdate, [current, new]),
                               timeout)

    def kmodify(self, ensemble, key, mod_fun, default, timeout=10.0):
        return sync_send_event(self.runtime, self._target(ensemble),
                               ("put", key, do_kmodify, [mod_fun, default]),
                               timeout)

    def kdelete(self, ensemble, key, timeout: float = 10.0):
        return self.kover(ensemble, key, NOTFOUND, timeout)

    def ksafe_delete(self, ensemble, key, current: Obj, timeout=10.0):
        return self.kupdate(ensemble, key, current, NOTFOUND, timeout)

    def update_members(self, ensemble, changes, timeout: float = 20.0):
        return sync_send_event(self.runtime, self._target(ensemble),
                               ("update_members", tuple(changes)), timeout)

    # -- assertion helpers -------------------------------------------------

    def kput_ok(self, ensemble, key, value):
        result = self.kover(ensemble, key, value)
        assert isinstance(result, tuple) and result[0] == "ok", result
        return result[1]

    def kget_value(self, ensemble, key):
        result = self.kget(ensemble, key)
        assert isinstance(result, tuple) and result[0] == "ok", result
        return result[1].value

    def read_until(self, ensemble, key, expect, max_time: float = 30.0):
        """drop_write_test's read_until: retry reads until the expected
        value is visible (healed)."""
        def check():
            r = self.kget(ensemble, key)
            return isinstance(r, tuple) and r[0] == "ok" and \
                r[1].value == expect
        ok = self.runtime.run_until(check, max_time, poll=0.1)
        assert ok, f"value {expect!r} for {key!r} not visible"


def make_peers(n: int, n_nodes: Optional[int] = None) -> List[PeerId]:
    """Peer ids spread over nodes (node per peer by default)."""
    n_nodes = n_nodes if n_nodes is not None else n
    return [PeerId(i, f"node{i % n_nodes}") for i in range(n)]


class ManagedCluster:
    """Full-stack harness: per-node Manager + routers + storage, the
    root ensemble, gossip, and the client API — the analog of a real
    multi-node deployment of the reference app, driven in one
    deterministic virtual-time runtime.

    Typical bring-up (mirrors the riak_ensemble README sequence):
    ``enable(node0)`` → ``join(node1, node0)`` → expand the root
    ensemble's members → ``create_ensemble(...)`` → client K/V ops.
    """

    def __init__(self, seed: int = 0, nodes: Sequence[str] = ("node0",),
                 config: Optional[Config] = None,
                 data_root: Optional[str] = None, **peer_kw) -> None:
        from riak_ensemble_tpu.manager import Manager

        self.runtime = Runtime(seed)
        self.config = config if config is not None else fast_test_config()
        self.data_root = data_root
        self.peer_kw = peer_kw
        self.managers: Dict[str, Manager] = {}
        self.storages: Dict[str, Storage] = {}
        for node in nodes:
            self.add_node(node)

    def add_node(self, node: str):
        from riak_ensemble_tpu.manager import Manager

        root = (f"{self.data_root}/{node}" if self.data_root else None)
        storage = Storage(self.runtime, node, self.config, root)
        self.storages[node] = storage
        mgr = Manager(self.runtime, node, self.config, storage,
                      **self.peer_kw)
        self.managers[node] = mgr
        return mgr

    def mgr(self, node: str):
        return self.managers[node]

    def client(self, node: str):
        from riak_ensemble_tpu.client import Client

        return Client(self.runtime, node)

    # -- cluster lifecycle ----------------------------------------------

    def enable(self, node: str) -> None:
        assert self.mgr(node).enable() == "ok"
        self.wait_stable("root")

    def join(self, joining: str, existing: str, timeout: float = 60.0):
        fut = self.mgr(joining).join_async(existing, timeout)
        result = self.runtime.await_future(fut, timeout=timeout + 5.0)
        assert result == "ok", f"join failed: {result!r}"
        # converged when every enabled manager lists the new member
        ok = self.runtime.run_until(
            lambda: all(joining in m.cluster_state.members
                        for m in self.managers.values()
                        if m.cluster_state.enabled), 60.0, poll=0.1)
        assert ok, "join did not converge via gossip"
        return result

    def remove(self, from_node: str, target: str, timeout: float = 60.0):
        fut = self.mgr(from_node).remove_async(target, timeout)
        result = self.runtime.await_future(fut, timeout=timeout + 5.0)
        assert result == "ok", f"remove failed: {result!r}"
        return result

    def create_ensemble(self, ensemble, peer_ids: Sequence[PeerId],
                        mod: str = "basic", args=(),
                        timeout: float = 30.0) -> None:
        leader = peer_ids[0]
        fut = self.mgr(leader.node).create_ensemble(
            ensemble, leader, list(peer_ids), mod, args, timeout)
        result = self.runtime.await_future(fut, timeout=timeout + 5.0)
        assert result == "ok", f"create_ensemble failed: {result!r}"
        # Wait until every hosting node has started its peers.
        wanted = {(p.node, ensemble) for p in peer_ids}

        def started():
            return all(
                any(k[0] == ensemble for k in self.managers[n].local_peers)
                for n, _ in wanted)
        ok = self.runtime.run_until(started, 60.0, poll=0.1)
        assert ok, f"peers for {ensemble} not started via gossip"

    def update_members(self, ensemble, changes, timeout: float = 30.0):
        """ens_test:expand analog — update_members on the leader."""
        lid = self.wait_leader(ensemble)
        return sync_send_event(self.runtime, peer_name(ensemble, lid),
                               ("update_members", tuple(changes)), timeout)

    # -- ens_test.erl-style single-node harness (ens_test.erl:24-45) -----

    @property
    def node0(self) -> str:
        return next(iter(self.managers))

    def ens_start(self, n: int = 1) -> None:
        """ens_test:start/0,1 — enable on one node, expand the root
        ensemble to n peers (all hosted on that node: the reference's
        central multi-peer-without-multi-node trick)."""
        self.enable(self.node0)
        if n > 1:
            self.ens_expand(n)

    def ens_expand(self, n: int) -> None:
        """ens_test:expand/1 — root grows by peers {2..n, node0}."""
        adds = [("add", PeerId(i, self.node0)) for i in range(2, n + 1)]
        r = self.update_members("root", adds)
        assert r == "ok", r
        expected = [PeerId("root", self.node0)] + \
            [PeerId(i, self.node0) for i in range(2, n + 1)]
        self.wait_members("root", expected)
        self.wait_stable("root")

    def wait_members(self, ensemble, expected, max_time: float = 60.0):
        """ens_test:wait_members — manager view includes expected."""
        def ok():
            members = self.mgr(self.node0).get_members(ensemble)
            return all(p in members for p in expected)
        assert self.runtime.run_until(ok, max_time, poll=0.1), \
            f"members of {ensemble} never reached {expected}"

    def kput(self, key, value, timeout: float = 5.0):
        return self.client(self.node0).kover("root", key, value, timeout)

    def kget(self, key, timeout: float = 5.0, opts=()):
        return self.client(self.node0).kget("root", key, timeout, opts)

    def read_until(self, key, max_time: float = 60.0):
        """ens_test:read_until — retry until a non-notfound value is
        readable; a successful read must never return notfound."""
        c = self.client(self.node0)

        def check():
            r = c.kget("root", key, timeout=5.0)
            if r[0] == "ok":
                assert r[1].value is not NOTFOUND, \
                    "read_until saw a notfound object (data loss)"
                return True
            return False
        assert self.runtime.run_until(check, max_time, poll=0.1), \
            f"key {key!r} never became readable"

    # -- introspection (shared logic with Cluster) -----------------------

    leader_id = Cluster.leader_id
    leader = Cluster.leader
    peer = Cluster.peer
    tree_of = Cluster.tree_of
    wait_leader = Cluster.wait_leader
    wait_stable = Cluster.wait_stable
    check_quorum = Cluster.check_quorum
    suspend_peer = Cluster.suspend_peer
    resume_peer = Cluster.resume_peer
