"""Restricted wire codec for the TCP transport.

The reference ships terms over disterl, whose decoder constructs only
plain Erlang terms — it can never execute code.  This codec gives the
TCP transport (:mod:`riak_ensemble_tpu.netruntime`) the same property:
:func:`decode` builds values exclusively from an allowlist of plain
containers and the protocol's registered record types.  A hostile peer
that can reach the node port can at worst inject a well-formed protocol
message (the disterl trust model without the cookie); it cannot make
the decoder run arbitrary code the way ``pickle.loads`` would.

Format: one tag byte per value, then a payload.  Sizes/counts are
unsigned varints; ints are sign-magnitude big-endian byte strings so
arbitrary precision survives.  Containers are count-prefixed element
sequences; registered records are a type code plus their field values
in declaration order.

Anything not encodable (actor refs, futures, closures) raises
:class:`WireError` at *encode* time — the transport drops such frames
as local-only, exactly as it did for unpicklable values.

Raw-buffer section (the zero-copy bulk lane): a frame whose value
contains :class:`Raw` wrappers encodes via :func:`encode_parts` into a
``'B'``-tagged payload — a buffer-length table, the term section (in
which each Raw leaf is a 1-byte-tagged index reference), then the raw
bytes themselves, UNCOPIED: ``encode_parts`` returns the header plus
the callers' own buffers for a scatter-gather write, so a bulk numpy
plane rides after the term codec without ever being concatenated into
an intermediate bytes.  :func:`decode` resolves the references to
memoryview slices of the received payload (no second copy on the
receive side either).  The allowlist property is unchanged — a buffer
decodes to plain read-only memory, never code.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Sequence, Tuple

from riak_ensemble_tpu.state import ClusterState
from riak_ensemble_tpu.types import (EnsembleInfo, Fact, NOTFOUND, Obj,
                                     PeerId)

__all__ = ["encode", "decode", "encode_parts", "Raw", "WireError"]


class WireError(Exception):
    """Value outside the wire allowlist, or a malformed frame."""


class Raw:
    """Zero-copy bulk-buffer wrapper for :func:`encode_parts`.

    Wraps anything exposing the buffer protocol (bytes, memoryview, a
    C-contiguous numpy array's ``.data``).  Inside a frame value a Raw
    encodes as a small index reference; the buffer itself rides after
    the term section, handed back verbatim by ``encode_parts`` so the
    sender can scatter-gather it onto the socket without a copy.  On
    decode the reference resolves to a read-only memoryview slice of
    the received payload."""

    __slots__ = ("buf",)

    def __init__(self, buf) -> None:
        m = memoryview(buf)
        if not m.contiguous:
            raise WireError("Raw buffer must be contiguous")
        if m.nbytes == 0:
            # cast("B") rejects zero-in-shape views; an empty buffer
            # is just the empty byte run
            self.buf = memoryview(b"")
        else:
            self.buf = (m.cast("B") if m.format != "B" or m.ndim != 1
                        else m)


_F64 = struct.Struct(">d")

#: recursion guard, both directions: protocol messages are shallow (a
#: gossip ClusterState inside a tuple is the deepest real frame).  On
#: encode it also turns pathological user values (1000-deep nesting,
#: self-referential containers) into WireError instead of
#: RecursionError, so the transport's drop path handles them.
_MAX_DEPTH = 32

# Registered record types: code -> (class, field names).  Field values
# recurse through the same codec, so nested records (a Fact's PeerId,
# a ClusterState's EnsembleInfo dict) need no special casing.
_RECORDS: Tuple[Tuple[type, Tuple[str, ...]], ...] = (
    (PeerId, ("name", "node")),
    (Obj, ("epoch", "seq", "key", "value")),
    (Fact, ("epoch", "seq", "leader", "views", "view_vsn", "pend_vsn",
            "commit_vsn", "pending")),
    (EnsembleInfo, ("vsn", "leader", "views", "seq", "mod", "args")),
    (ClusterState, ("id", "enabled", "members_vsn", "members",
                    "ensembles", "pending")),
)
_RECORD_BY_CLS = {cls: (code, fields)
                  for code, (cls, fields) in enumerate(_RECORDS)}


def _put_uvarint(out: List[bytes], n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(bytes((b | 0x80,)))
        else:
            out.append(bytes((b,)))
            return


def _encode(out: List[bytes], v: Any, depth: int = 0,
            bufs: "List[memoryview]" = None) -> None:
    if depth > _MAX_DEPTH:
        raise WireError("value too deeply nested")
    t = type(v)
    if t is Raw:
        if bufs is None:
            raise WireError(
                "Raw buffers need encode_parts (raw-frame encoding)")
        out.append(b"r")
        _put_uvarint(out, len(bufs))
        bufs.append(v.buf)
        return
    if v is None:
        out.append(b"N")
    elif t is bool:
        out.append(b"T" if v else b"F")
    elif t is int:
        raw = v.to_bytes((v.bit_length() + 8) // 8 or 1, "big", signed=True)
        out.append(b"i")
        _put_uvarint(out, len(raw))
        out.append(raw)
    elif t is float:
        out.append(b"f" + _F64.pack(v))
    elif t is str:
        raw = v.encode("utf-8")
        out.append(b"s")
        _put_uvarint(out, len(raw))
        out.append(raw)
    elif t is bytes:
        out.append(b"b")
        _put_uvarint(out, len(v))
        out.append(v)
    elif t is tuple or t is list or t is set or t is frozenset:
        out.append({tuple: b"t", list: b"l", set: b"e", frozenset: b"z"}[t])
        _put_uvarint(out, len(v))
        for item in v:
            _encode(out, item, depth + 1, bufs)
    elif t is dict:
        out.append(b"d")
        _put_uvarint(out, len(v))
        for k, val in v.items():
            _encode(out, k, depth + 1, bufs)
            _encode(out, val, depth + 1, bufs)
    elif v is NOTFOUND:
        out.append(b"0")
    elif t in _RECORD_BY_CLS:
        code, fields = _RECORD_BY_CLS[t]
        out.append(b"R")
        _put_uvarint(out, code)
        for name in fields:
            _encode(out, getattr(v, name), depth + 1, bufs)
    else:
        raise WireError(f"type {t.__name__} is not wire-encodable")


def encode_py(v: Any) -> bytes:
    """Pure-Python encoder (fallback + differential-test oracle for
    the native codec)."""
    out: List[bytes] = []
    _encode(out, v)
    return b"".join(out)


def encode(v: Any) -> bytes:
    """Serialize an allowlisted value to a wire frame payload.

    Routed through the C++ codec (``native/wirecodec.cc`` — the
    disterl-term-codec-in-C role) when it builds; byte-exact with
    :func:`encode_py`, so native and Python frames are
    interchangeable on the wire.
    """
    native = _native_codec()
    if native is not None:
        return native.encode(v)
    return encode_py(v)


def encode_parts(v: Any) -> List[Any]:
    """Serialize a value that may contain :class:`Raw` buffers into a
    raw frame, WITHOUT copying the buffers.

    Returns ``[header, buf0, buf1, ...]``: the header bytes (the
    ``'B'`` tag, the buffer-length table and the term section) followed
    by the wrapped buffers in reference order.  The frame payload on
    the wire is the plain concatenation of the parts — hand the list to
    a scatter-gather send (``socket.sendmsg``) and the bulk planes go
    from their owning arrays straight to the kernel.  A value with no
    Raw leaves still encodes as a (bufferless) raw frame, so callers
    need not special-case.  The term walk runs in Python — it is the
    SMALL section by design; the native codec's role on this path is
    the decode side."""
    out: List[bytes] = []
    bufs: List[memoryview] = []
    _encode(out, v, 0, bufs)
    head: List[bytes] = [b"B"]
    _put_uvarint(head, len(bufs))
    for b in bufs:
        _put_uvarint(head, b.nbytes)
    return [b"".join(head + out)] + bufs


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        end = self.pos + n
        if n < 0 or end > len(self.buf):
            raise WireError("truncated frame")
        chunk = self.buf[self.pos:end]
        self.pos = end
        return chunk

    def uvarint(self) -> int:
        shift = 0
        n = 0
        while True:
            byte = self.take(1)[0]
            n |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return n
            shift += 7
            if shift > 63:
                raise WireError("varint too long")


def _decode(r: _Reader, depth: int,
            bufs: "List[memoryview]" = None) -> Any:
    if depth > _MAX_DEPTH:
        raise WireError("frame too deep")
    tag = r.take(1)
    if tag == b"r":
        idx = r.uvarint()
        if bufs is None or idx >= len(bufs):
            raise WireError(f"buffer ref {idx} outside raw frame")
        return bufs[idx]
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return int.from_bytes(r.take(r.uvarint()), "big", signed=True)
    if tag == b"f":
        return _F64.unpack(r.take(8))[0]
    if tag == b"s":
        try:
            return r.take(r.uvarint()).decode("utf-8")
        except UnicodeDecodeError as exc:
            raise WireError(f"bad utf-8 in frame: {exc}") from None
    if tag == b"b":
        return r.take(r.uvarint())
    if tag in (b"t", b"l", b"e", b"z"):
        n = r.uvarint()
        items = [_decode(r, depth + 1, bufs) for _ in range(n)]
        if tag == b"t":
            return tuple(items)
        if tag == b"l":
            return items
        try:  # unhashable members are a malformed frame, not a crash
            if tag == b"e":
                return set(items)
            return frozenset(items)
        except TypeError as exc:
            raise WireError(f"unhashable set member: {exc}") from None
    if tag == b"d":
        n = r.uvarint()
        try:
            return {_decode(r, depth + 1, bufs):
                    _decode(r, depth + 1, bufs)
                    for _ in range(n)}
        except TypeError as exc:
            raise WireError(f"unhashable dict key: {exc}") from None
    if tag == b"0":
        return NOTFOUND
    if tag == b"R":
        code = r.uvarint()
        if code >= len(_RECORDS):
            raise WireError(f"unknown record code {code}")
        cls, fields = _RECORDS[code]
        vals = [_decode(r, depth + 1, bufs) for _ in fields]
        return cls(**dict(zip(fields, vals)))
    raise WireError(f"unknown tag {tag!r}")


def _decode_raw_frame(payload: bytes) -> Any:
    """Decode a ``'B'``-tagged raw frame: length table, term section,
    then the raw bytes — resolved as read-only memoryview slices of
    ``payload`` (the receive-side zero-copy half)."""
    mv = memoryview(payload)
    r = _Reader(payload)
    r.take(1)  # the 'B' tag
    nbufs = r.uvarint()
    lens = [r.uvarint() for _ in range(nbufs)]
    total = sum(lens)
    data_start = len(payload) - total
    if data_start < r.pos:
        raise WireError("raw-buffer table exceeds frame")
    bufs: List[memoryview] = []
    off = data_start
    for n in lens:
        bufs.append(mv[off:off + n])
        off += n
    v = _decode(r, 0, bufs)
    if r.pos != data_start:
        raise WireError("trailing bytes in frame")
    return v


def decode_py(payload: bytes) -> Any:
    """Pure-Python decoder (fallback + differential-test oracle)."""
    if payload[:1] == b"B":
        return _decode_raw_frame(payload)
    r = _Reader(payload)
    v = _decode(r, 0)
    if r.pos != len(payload):
        raise WireError("trailing bytes in frame")
    return v


def decode(payload: bytes) -> Any:
    """Deserialize a frame payload; raises WireError on anything
    malformed or outside the allowlist.  Routed through the C++
    codec when available (same allowlist property: the native decoder
    constructs only plain containers and registered records)."""
    native = _native_codec()
    if native is not None:
        return native.decode(payload)
    return decode_py(payload)


_NATIVE = None
_NATIVE_TRIED = False


def _native_codec():
    """Lazily build/load/register the C++ codec; None -> pure Python.
    ``RETPU_NO_NATIVE_WIRE=1`` pins the Python paths (used by the
    differential tests and as an operational escape hatch)."""
    global _NATIVE, _NATIVE_TRIED
    if _NATIVE_TRIED:
        return _NATIVE
    _NATIVE_TRIED = True
    import os

    if os.environ.get("RETPU_NO_NATIVE_WIRE"):
        return None
    try:
        import importlib.util

        from riak_ensemble_tpu.utils.native import NATIVE_DIR, build_target

        so = os.path.join(NATIVE_DIR, "_retpu_wire.so")
        if not build_target("_retpu_wire.so", so):
            return None
        spec = importlib.util.spec_from_file_location("_retpu_wire", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.register([(cls, fields) for cls, fields in _RECORDS],
                     NOTFOUND, WireError)
        _NATIVE = mod
    except Exception:
        _NATIVE = None
    return _NATIVE
