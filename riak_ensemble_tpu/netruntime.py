"""Real-time networked host runtime: asyncio + TCP transport.

The production counterpart of the deterministic simulator
(:mod:`riak_ensemble_tpu.runtime`): each OS process hosts ONE node's
actor stack (storage, manager, routers, peers) on an asyncio loop with
wall-clock timers, and node-to-node messages travel as length-prefixed
frames over TCP in the restricted :mod:`riak_ensemble_tpu.wire` codec
(no code execution on decode).  This is the DCN/host half of the distributed
communication backend (SURVEY §5): protocol math batches onto TPU via
the ops kernels; membership/timers/messaging run here — the role the
reference delegates to Erlang distribution (disterl,
riak_ensemble_msg:send_request msg.erl:132-142).

Failure semantics mirror the reference's:

- Unreachable node → frames are dropped after a bounded connect
  attempt; the quorum layer's timeouts/synthesized-nack machinery does
  the rest (noconnect casts, router.erl:144-160).
- Connections are re-established lazily per send batch; no session
  state is required by the protocol (every request carries its reqid).

Actor addressing is by the same structured names the simulator uses;
:func:`node_of_name` extracts the home node (peer ids embed their
node, service names carry it at index 1), so `Actor.send` works
unchanged on either runtime.
"""

from __future__ import annotations

import asyncio
import os
import random
import struct
import sys
import time
from typing import Any, Callable, Dict, Generator, List, Optional, Tuple

from riak_ensemble_tpu import faults, wire
from riak_ensemble_tpu.runtime import Actor, Future, Task, Timer
from riak_ensemble_tpu.types import PeerId

FRAME_HEADER = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024

#: log every dropped (non-encodable or malformed) frame to stderr
_WIRE_DEBUG = bool(os.environ.get("RETPU_WIRE_DEBUG"))

_warned_types: set = set()


def _warn_unencodable(item: Any, exc: BaseException) -> None:
    """A value the wire codec rejects is silently lost to the caller
    (they see only a timeout), so say so — once per distinct cause
    (every time, with the full repr, under RETPU_WIRE_DEBUG).  The
    frame is always a (dst, msg) tuple, so dedupe on the error text,
    which names the actual offending inner type.  Everything here is
    guarded: this runs in the except path that must never kill the
    sender task, and a hostile __repr__/__str__ may raise."""
    try:
        cause = str(exc)[:200]
    except Exception:
        cause = type(exc).__name__
    try:
        desc = f"{cause}: {repr(item)[:300]}" if _WIRE_DEBUG else cause
    except Exception:
        desc = cause
    if _WIRE_DEBUG or desc not in _warned_types:
        if not _WIRE_DEBUG:
            _warned_types.add(desc)
        print(f"riak_ensemble_tpu: dropping non-wire-encodable frame "
              f"({desc}); only plain data and registered protocol "
              f"types cross nodes", file=sys.stderr, flush=True)

#: service-style names carrying their node at index 1
_NODE_AT_1 = ("manager", "router", "rtr_proxy", "storage", "collector",
              "xproxy")


def node_of_name(name: Any) -> Optional[str]:
    """Home node of an actor name; None = deliver locally only."""
    if isinstance(name, tuple) and name:
        if name[0] in ("peer", "tree") and len(name) >= 3 and \
                isinstance(name[2], PeerId):
            return name[2].node
        if name[0] in _NODE_AT_1 and len(name) >= 2 and \
                isinstance(name[1], str):
            return name[1]
    return None


class NetRuntime:
    """Runtime-API-compatible real-time host for one node.

    ``peers`` maps node name → (host, port); this node's own entry
    defines the listen address.  All actor callbacks run on the
    asyncio loop thread — the same single-threaded execution model as
    the simulator (and the gen_server model it mirrors).
    """

    def __init__(self, node: str, peers: Dict[str, Tuple[str, int]],
                 seed: int = 0) -> None:
        self.node = node
        self.peers = dict(peers)
        self.rng = random.Random(seed)
        self.actors: Dict[Any, Actor] = {}
        self._monitors: Dict[Any, List[Callable[[Any], None]]] = {}
        self.trace: Optional[Callable[[str, Any], None]] = None
        self.net = _NetPolicy()
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: Dict[str, "_Conn"] = {}

    # -- time --------------------------------------------------------------

    @property
    def now(self) -> float:
        return time.monotonic()

    # -- registry (same surface as the simulator) --------------------------

    def register(self, actor: Actor) -> None:
        assert actor.name not in self.actors, f"duplicate {actor.name}"
        self.actors[actor.name] = actor

    def whereis(self, name: Any) -> Optional[Actor]:
        return self.actors.get(name)

    def stop_actor(self, name: Any) -> None:
        actor = self.actors.pop(name, None)
        if actor is not None:
            actor.alive = False
            actor.on_stop()
            for fn in self._monitors.pop(name, []):
                self.defer(lambda fn=fn: fn(name))

    def monitor(self, name: Any, callback: Callable[[Any], None]) -> None:
        if name not in self.actors:
            self.defer(lambda: callback(name))
            return
        self._monitors.setdefault(name, []).append(callback)

    def demonitor(self, name: Any,
                  callback: Callable[[Any], None]) -> None:
        fns = self._monitors.get(name)
        if fns is None:
            return
        try:
            fns.remove(callback)
        except ValueError:
            pass
        if not fns:
            del self._monitors[name]

    def suspend(self, name: Any) -> None:
        self.actors[name].suspended = True

    def resume(self, name: Any) -> None:
        actor = self.actors[name]
        if not actor.suspended:
            return
        actor.suspended = False
        backlog, actor._backlog = actor._backlog, []
        for msg in backlog:
            self.post(actor.name, msg)

    # -- scheduling --------------------------------------------------------

    def defer(self, fn: Callable[[], None]) -> None:
        assert self.loop is not None
        self.loop.call_soon(fn)

    def schedule(self, delay: float, fn: Callable[[], None]) -> Timer:
        assert self.loop is not None
        timer = Timer(self.now + delay)

        def fire() -> None:
            if not timer.cancelled:
                fn()

        self.loop.call_later(delay, fire)
        return timer

    def send_after(self, delay: float, dst: Any, msg: Any) -> Timer:
        return self.schedule(delay, lambda: self.post(dst, msg))

    def sleep(self, delay: float) -> Future:
        fut = Future()
        self.schedule(delay, lambda: fut.resolve(None))
        return fut

    def with_timeout(self, fut: Future, timeout: float,
                     timeout_value: Any = "timeout") -> Future:
        out = Future()
        fut.add_waiter(out.resolve)
        self.schedule(timeout, lambda: out.resolve(timeout_value))
        return out

    def spawn_task(self, gen: Generator, name: str = "task") -> Task:
        task = Task(self, gen, name)
        self.defer(lambda: task._step(None))
        return task

    # -- messaging ---------------------------------------------------------

    def post(self, dst: Any, msg: Any) -> None:
        def deliver() -> None:
            actor = self.actors.get(dst)
            if actor is not None:
                if self.trace:
                    self.trace("deliver", (dst, msg))
                actor._deliver(msg)

        self.defer(deliver)

    def net_send(self, src_node: str, dst: Any, msg: Any) -> None:
        dst_node = node_of_name(dst)
        if dst_node is None or dst_node == self.node:
            self.post(dst, msg)
            return
        if self.net.drop_hook is not None and \
                self.net.drop_hook(src_node, dst, msg):
            return
        fp = self.net.active_plan()
        if fp is not None:
            # fault-injection plane (docs/ARCHITECTURE.md §13):
            # directional drop, then injected per-link delay.  Delay
            # defers only the ENQUEUE onto the per-node connection
            # (call_later), so concurrent frames overlap their delays
            # like real in-flight latency; jitter within the delay
            # reorders frames on the link — the bounded-reorder mode
            # of this runtime (TCP still delivers each enqueue run in
            # order).
            if fp.should_drop(src_node, dst_node):
                return
            d = fp.delay_s(src_node, dst_node)
            if d > 0.0 and self.loop is not None:
                self.loop.call_later(
                    d, lambda: self._net_forward(dst_node, dst, msg))
                return
        self._net_forward(dst_node, dst, msg)

    def _net_forward(self, dst_node: str, dst: Any, msg: Any) -> None:
        conn = self._conns.get(dst_node)
        if conn is None:
            addr = self.peers.get(dst_node)
            if addr is None:
                return  # unknown node = unreachable (noconnect)
            conn = _Conn(self, dst_node, addr)
            self._conns[dst_node] = conn
        conn.send((dst, msg))

    # -- server ------------------------------------------------------------

    async def start(self) -> None:
        self.loop = asyncio.get_running_loop()
        host, port = self.peers[self.node]
        self._server = await asyncio.start_server(self._on_client,
                                                  host, port)

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                head = await reader.readexactly(FRAME_HEADER.size)
                (length,) = FRAME_HEADER.unpack(head)
                if length > MAX_FRAME:
                    break
                payload = await reader.readexactly(length)
                try:
                    dst, msg = wire.decode(payload)
                except Exception as exc:
                    if _WIRE_DEBUG:
                        print("WIRE-RXDROP", exc, payload[:120],
                              file=sys.stderr, flush=True)
                    continue  # corrupt/hostile frame: drop (the codec
                    # only constructs allowlisted protocol types)
                self.post(dst, msg)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def stop(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- blocking bridge (callers outside the loop) ------------------------

    async def await_future(self, fut: Future, timeout: float = 60.0):
        afut = asyncio.get_running_loop().create_future()
        fut.add_waiter(lambda v: afut.done() or afut.set_result(v))
        return await asyncio.wait_for(afut, timeout)


class _NetPolicy:
    """Test-hook surface kept API-compatible with the simulator's
    Network (partition/heal map to the drop hook here).  ``plan``
    attaches a :class:`riak_ensemble_tpu.faults.FaultPlan`
    (directional drop / per-link delay / reorder-by-jitter); it
    defaults to the process-global plan, so the environment fault
    knobs arm a subprocess node without code changes."""

    def __init__(self) -> None:
        self.drop_hook: Optional[Callable[[str, Any, Any], bool]] = None
        #: fault-injection plan; None = consult the process-global
        #: plan (env-armed) on every send
        self.plan: Optional[faults.FaultPlan] = None

    def active_plan(self) -> Optional[faults.FaultPlan]:
        if self.plan is not None:
            return self.plan if self.plan.active() else None
        return faults.active_plan()

    def heal(self) -> None:
        self.drop_hook = None
        if self.plan is not None:
            self.plan.heal()


class _Conn:
    """Lazy outbound connection with a bounded send queue; frames are
    dropped while the node is unreachable (noconnect semantics)."""

    MAX_QUEUE = 10_000

    def __init__(self, runtime: NetRuntime, node: str,
                 addr: Tuple[str, int]) -> None:
        self.runtime = runtime
        self.node = node
        self.addr = addr
        self.queue: asyncio.Queue = asyncio.Queue(self.MAX_QUEUE)
        self._task = asyncio.get_running_loop().create_task(self._run())

    def send(self, frame: Any) -> None:
        try:
            self.queue.put_nowait(frame)
        except asyncio.QueueFull:
            pass  # backpressure by drop, like a full distribution buffer

    def close(self) -> None:
        self._task.cancel()

    async def _run(self) -> None:
        writer: Optional[asyncio.StreamWriter] = None
        try:
            while True:
                item = await self.queue.get()
                try:
                    payload = wire.encode(item)
                except Exception as exc:
                    # WireError for out-of-allowlist values; anything
                    # else (a hostile __repr__/__eq__, etc.) must not
                    # kill the sender task and wedge the link.
                    _warn_unencodable(item, exc)
                    continue  # not wire-encodable: local-only, drop
                if writer is None:
                    try:
                        _r, writer = await asyncio.wait_for(
                            asyncio.open_connection(*self.addr),
                            timeout=2.0)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        # node down: drop the frame, pace retries
                        await asyncio.sleep(0.2)
                        continue
                try:
                    writer.write(FRAME_HEADER.pack(len(payload)) + payload)
                    await writer.drain()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    writer.close()
                    writer = None  # frame dropped; reconnect next send
        except asyncio.CancelledError:
            if writer is not None:
                writer.close()
            raise
