"""Stateless proxy/ingress tier (docs/ARCHITECTURE.md §16).

The serving plane's fan-in role, split out of the engine front-end:
a proxy terminates N client connections — accept, frame parse,
per-connection backpressure — and forwards each op to the group
leader over ONE shared upstream connection, so the leader's event
loop sees a handful of pipelined proxy sockets instead of every
client in the fleet.  Accept/parse/fan-out CPU now scales with proxy
count (run as many as ingress needs; they share nothing), which is
the compartmentalization move of HT-Paxos / Compartmentalized Paxos:
the consensus engine stops being the connection-termination tier.

Protocol: byte-compatible with :mod:`riak_ensemble_tpu.svcnode` on
both sides — clients speak the same length-prefixed
``(req_id, op, args...)`` frames to a proxy they would speak to the
engine, and the proxy speaks them upstream.  The ``*_slab`` verbs
stay on the zero-copy lane END TO END: a decoded client slab's
length tables and arenas surface as memoryview slices of the
received frame, and the proxy forwards them wrapped in
:class:`wire.Raw` through ``encode_parts`` — one scatter-gather hop
per client batch, no re-framing, no per-key term decode anywhere in
the proxy.

Leader discovery: give a proxy the client ports of every group host;
it probes ``("stats",)`` for a host whose ``group.leader`` flag is
set, sticks to it, and re-resolves when an op answers
``("error", "not-leader")`` — the wire shape of ``DeposedError`` —
or the upstream socket drops.  Ops that were rejected not-leader
were never dispatched, so the proxy retries them transparently
against the new leader; an op that dies mid-flight surfaces
``("error", "disconnected")`` to the client unless its verb is
idempotent (``kget*``, ``stats``, ``health``, ``metrics``), which
retry once.  Proxies hold no durable state — restart one and it
re-discovers and serves; clients reconnect through any proxy.

    ("proxy_stats",) -> dict   # answered locally, never forwarded:
                               # live client count, forwarded/retry/
                               # reconnect counters, backpressure
                               # drops, current upstream address

    python -m riak_ensemble_tpu.proxy --port 7701 \
        --upstream 127.0.0.1:7601,127.0.0.1:7602,127.0.0.1:7603
"""

from __future__ import annotations

import argparse
import asyncio
import time
from typing import Any, List, Optional, Tuple

from riak_ensemble_tpu import wire
from riak_ensemble_tpu.svcnode import (
    _HDR, _MAX_FRAME, _MAX_INFLIGHT, _MAX_WRITE_BUF, ServiceClient)

#: slab verbs ride the parts lane upstream: every buffer-typed arg
#: (the length tables and arenas, memoryview slices of the client's
#: frame) is re-wrapped in wire.Raw — the one re-encode is the frame
#: header, never the planes
_SLAB_OPS = frozenset({"kput_slab", "kget_slab"})


class LeaderLink:
    """One proxy's shared upstream: discovers the leader among the
    candidate addresses, pipelines every client's ops over a single
    connection, and re-resolves on not-leader / socket loss.

    The retry discipline mirrors :class:`repgroup.GroupClient`:
    a not-leader rejection always retries (the op was never
    dispatched into a flush); a mid-flight DISCONNECTED retries only
    for idempotent verbs — auto-retrying a write whose first attempt
    may have committed would double-apply."""

    CONNECT_TIMEOUT = 5.0

    def __init__(self, upstreams, op_timeout: float = 30.0,
                 discover_timeout: float = 30.0) -> None:
        self.upstreams = [(str(h), int(p)) for h, p in upstreams]
        self.op_timeout = op_timeout
        self.discover_timeout = discover_timeout
        self._client: Optional[ServiceClient] = None
        self.leader_addr: Optional[Tuple[str, int]] = None
        self._dlock = asyncio.Lock()
        self.rediscoveries = 0
        self.not_leader_retries = 0

    async def _discover(self, budget: float) -> ServiceClient:
        deadline = time.monotonic() + budget
        async with self._dlock:
            if self._client is not None:  # a sibling op already won
                return self._client
            first_err: Optional[str] = None
            while time.monotonic() < deadline:
                responsive = None
                for addr in self.upstreams:
                    c = ServiceClient(*addr)
                    try:
                        await asyncio.wait_for(c.connect(),
                                               self.CONNECT_TIMEOUT)
                        st = await c.call("stats", timeout=10.0)
                    except (OSError, ConnectionError,
                            asyncio.TimeoutError):
                        await c.close()
                        continue
                    if not isinstance(st, dict):
                        await c.close()
                        continue
                    grp = st.get("group")
                    if grp is None and responsive is None:
                        # a standalone svcnode (no replication
                        # group): any responsive host is the engine
                        responsive = (c, addr)
                        continue
                    if isinstance(grp, dict) and grp.get("leader"):
                        if responsive is not None:
                            await responsive[0].close()
                        self._client, self.leader_addr = c, addr
                        return c
                    await c.close()
                if responsive is not None:
                    self._client, self.leader_addr = responsive
                    return self._client
                first_err = first_err or "no leader elected yet"
                await asyncio.sleep(0.25)
        raise TimeoutError(
            f"no upstream leader among {self.upstreams}: "
            f"{first_err or 'all unreachable'}")

    async def _drop(self, failed: Optional[ServiceClient]) -> None:
        """Compare-and-drop (the GroupClient rule): a stale failure
        must not close a freshly discovered leader under siblings."""
        if failed is not None and self._client is not failed:
            await failed.close()
            return
        if self._client is not None:
            await self._client.close()
        self._client = None
        self.leader_addr = None
        self.rediscoveries += 1

    async def forward(self, op: str, args: tuple):
        """One client op against the current leader; transparent
        re-resolve + retry on safe-to-retry outcomes, bounded by
        ~discover_timeout overall."""
        deadline = time.monotonic() + self.discover_timeout
        retried_disconnect = False
        while True:
            c = self._client
            if c is None:
                try:
                    c = await self._discover(
                        max(1.0, deadline - time.monotonic()))
                except TimeoutError:
                    return ("error", "no-leader")
            try:
                if op in _SLAB_OPS:
                    fwd = tuple(
                        wire.Raw(a) if isinstance(
                            a, (bytes, bytearray, memoryview))
                        else a for a in args)
                    r = await c.call_parts(op, *fwd,
                                           timeout=self.op_timeout)
                else:
                    r = await c.call(op, *args,
                                     timeout=self.op_timeout)
            except asyncio.TimeoutError:
                r = ServiceClient.DISCONNECTED
            except wire.WireError:
                return ("error", "bad-request")
            if r == ("error", "not-leader"):
                # DeposedError's wire shape: never dispatched, always
                # safe to re-resolve and retry
                await self._drop(c)
                if time.monotonic() < deadline:
                    self.not_leader_retries += 1
                    continue
            if r == ServiceClient.DISCONNECTED:
                await self._drop(c)
                # writes — kmodify/kmodify_many included (NOT
                # idempotent: an ambiguous drop may have committed,
                # and a retried RMW double-applies) — surface the
                # ambiguity to the proxy client unchanged
                if (op in ServiceClient.IDEMPOTENT_OPS
                        and not retried_disconnect
                        and time.monotonic() < deadline):
                    retried_disconnect = True
                    continue
            return r

    async def close(self) -> None:
        if self._client is not None:
            await self._client.close()
        self._client = None


class ProxyServer:
    """TCP ingress front-end: svcnode-protocol server whose dispatch
    is a forward over one :class:`LeaderLink`."""

    def __init__(self, upstreams, host: str = "127.0.0.1",
                 port: int = 0, op_timeout: float = 30.0,
                 discover_timeout: float = 30.0) -> None:
        self.link = LeaderLink(upstreams, op_timeout=op_timeout,
                               discover_timeout=discover_timeout)
        self.host, self.port = host, port
        self._server: Optional[asyncio.AbstractServer] = None
        self.clients = 0
        self.forwarded = 0
        self.backpressure = {"inflight_stalls": 0,
                             "write_buf_drops": 0}

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port)
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        await self.link.close()

    def proxy_stats(self) -> dict:
        la = self.link.leader_addr
        return {
            "clients": self.clients,
            "forwarded": self.forwarded,
            "not_leader_retries": self.link.not_leader_retries,
            "rediscoveries": self.link.rediscoveries,
            "backpressure": dict(self.backpressure),
            "upstream": (f"{la[0]}:{la[1]}" if la else None),
        }

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        # Same per-connection budget discipline as the engine
        # front-end (svcnode._on_client): a pipelining client blocks
        # at _MAX_INFLIGHT unresolved ops, a stalled reader is
        # dropped at the write-buffer cap — both counted.
        inflight = asyncio.Semaphore(_MAX_INFLIGHT)
        bp = self.backpressure
        self.clients += 1

        def send(req_id: Any, result: Any) -> None:
            if writer.is_closing():
                return
            try:
                payload = wire.encode((req_id, result))
            except wire.WireError:
                payload = wire.encode((req_id, "failed"))
            writer.write(_HDR.pack(len(payload)) + payload)
            transport = writer.transport
            if (transport is not None
                    and transport.get_write_buffer_size()
                    > _MAX_WRITE_BUF):
                bp["write_buf_drops"] += 1
                transport.abort()

        async def forward_one(req_id: Any, op: str,
                              args: tuple) -> None:
            try:
                r = await self.link.forward(op, args)
            except Exception:
                r = ("error", "bad-request")
            finally:
                inflight.release()
            self.forwarded += 1
            send(req_id, r)

        try:
            while True:
                head = await reader.readexactly(_HDR.size)
                (length,) = _HDR.unpack(head)
                if length > _MAX_FRAME:
                    break  # hostile length: drop the connection
                frame = await reader.readexactly(length)
                try:
                    msg = wire.decode(frame)
                    req_id, op = msg[0], msg[1]
                    args = tuple(msg[2:])
                except (wire.WireError, IndexError, TypeError):
                    break  # malformed: drop the connection
                if op == "proxy_stats":
                    send(req_id, self.proxy_stats())
                    continue
                if inflight.locked():
                    bp["inflight_stalls"] += 1
                await inflight.acquire()
                # Forwarding runs as its own task so a slow upstream
                # op never serializes the whole connection behind it
                # — clients pipeline through a proxy exactly as they
                # would against the engine.  The task keeps `frame`
                # alive via `args` (slab memoryviews slice into it).
                asyncio.get_running_loop().create_task(
                    forward_one(req_id, op, args))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self.clients -= 1
            writer.close()


async def serve_proxy(upstreams, host: str = "127.0.0.1",
                      port: int = 0, op_timeout: float = 30.0,
                      discover_timeout: float = 30.0) -> ProxyServer:
    """Bring up one proxy; returns the started server (call
    ``await proxy.stop()`` to tear down).  Discovery is lazy — the
    first forwarded op dials upstream — so a proxy can boot before
    its group has elected."""
    proxy = ProxyServer(upstreams, host, port, op_timeout=op_timeout,
                        discover_timeout=discover_timeout)
    await proxy.start()
    return proxy


def _parse_addrs(s: str) -> List[Tuple[str, int]]:
    out = []
    for part in s.split(","):
        host, _, port = part.strip().rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7701)
    ap.add_argument("--upstream", required=True,
                    help="comma-separated host:port candidates — the "
                         "group hosts' CLIENT ports (or one "
                         "standalone svcnode)")
    ap.add_argument("--op-timeout", type=float, default=30.0)
    ap.add_argument("--discover-timeout", type=float, default=30.0)
    args = ap.parse_args(argv)

    async def run() -> None:
        proxy = await serve_proxy(
            _parse_addrs(args.upstream), args.host, args.port,
            op_timeout=args.op_timeout,
            discover_timeout=args.discover_timeout)
        print(f"proxy serving on {proxy.host}:{proxy.port} -> "
              f"{args.upstream}", flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await proxy.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
