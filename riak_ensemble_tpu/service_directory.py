"""One cluster story for both planes: the scale path advertised
through the consensus-backed cluster directory.

The reference has a single ensemble directory — the root ensemble's
``cluster_state``, reconciled by every manager
(``riak_ensemble_manager.erl:610-641``) — and every ensemble, whatever
its backend, is discovered through it.  This module gives the BATCHED
service plane the same citizenship: a running svcnode registers its
service under a ``("svc", name)`` ensemble id with ``mod="service"``
(a directory-only backend: empty member views, so manager
reconciliation starts no actor peers for it), carrying the TCP
address + shape in ``args``.  Registration flows through the root
ensemble's kmodify like any create_ensemble (strong consistency),
then gossip propagates it to every node; any node's client resolves
the service plane from its local directory cache and dials the
svcnode front-end.

This closes VERDICT r2 missing #2's stretch: the scale path is no
longer a standalone plane — it shares the cluster's consensus-backed
namespace, discovery and gossip with the actor stack.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

#: EnsembleInfo.mod marker for directory-only (scale-plane) entries.
SERVICE_MOD = "service"


def service_id(name: Any) -> Tuple[str, Any]:
    return ("svc", name)


def register_service(mgr, runtime, name: Any, host: str, port: int,
                     shape: Tuple[int, int, int],
                     timeout: float = 30.0):
    """Advertise a batched service in the cluster directory (runs the
    full create_ensemble path through the root ensemble —
    manager.erl:157-166 — with no peers to start).  ``shape`` is
    (n_ens, n_peers, n_slots) so clients can validate addressing.
    ``mgr`` is the local node's Manager.  Returns the create result
    ("ok" | error tuple)."""
    fut = mgr.create_ensemble(service_id(name), None, [],
                              SERVICE_MOD,
                              (host, int(port), tuple(shape)), timeout)
    return runtime.await_future(fut, timeout + 5.0)


def resolve_service(directory, name: Any
                    ) -> Optional[Dict[str, Any]]:
    """Look a service plane up in the (gossip-replicated) directory:
    ``{"host", "port", "shape"}`` or None.  Works on any node once
    gossip has propagated the registration."""
    info = directory.known_ensembles().get(service_id(name))
    if info is None or info.mod != SERVICE_MOD:
        return None
    host, port, shape = info.args
    return {"host": host, "port": port, "shape": tuple(shape)}


def list_services(directory) -> Dict[Any, Dict[str, Any]]:
    """Every advertised service plane in the directory."""
    out = {}
    for ens_id, info in directory.known_ensembles().items():
        if (isinstance(ens_id, tuple) and len(ens_id) == 2
                and ens_id[0] == "svc" and info.mod == SERVICE_MOD):
            host, port, shape = info.args
            out[ens_id[1]] = {"host": host, "port": port,
                              "shape": tuple(shape)}
    return out
