"""Consensus-managed membership for the scale plane (VERDICT r3 #3).

The reference's cluster story is one loop: every mutation flows
through the root ensemble's kmodify (``riak_ensemble_root.erl:38-45``),
gossip replicates the state, and every node's manager reconciles —
starting wanted-but-missing peers and stopping running-but-unwanted
ones (``riak_ensemble_manager.erl:610-641``, ``check_peers:697-715``).
Round 3 bridged the scale plane's *endpoints* into that story
(:mod:`riak_ensemble_tpu.service_directory`); tenant ensembles were
still managed by direct ``create_ensemble``/``update_members`` calls
on one process.  This module finishes the story:

- **Tenant registry in cluster state.**  A scale-plane tenant is an
  ensemble record ``("svct", name)`` with ``mod="svc_tenant"`` and NO
  peer members (so actor reconciliation starts no processes for it),
  created/retired through the root ensemble exactly like any other
  ensemble and spread by gossip.
- **Derived placement.**  A tenant's owner is not stored — it is the
  rendezvous-hash winner over the svcnodes REGISTERED in the same
  directory (``service_directory``).  Registering a new svcnode
  through the root is therefore the entire join protocol: gossip
  carries the registration, every reconciler recomputes placement,
  and tenants rebalance with no further writes — "ensembles move via
  gossip alone".
- **Reconciliation loop.**  :class:`ServiceReconciler` (one per node
  owning a :class:`BatchedEnsembleService`) is ``check_peers`` for
  tenants: create wanted-but-missing rows, retire
  running-but-unwanted ones, and apply per-tenant view changes from
  the registry through ``update_members``.
- **Handoff.**  When placement moves a tenant away, the retiring
  owner atomically exports the tenant's keyed data and destroys the
  row IN THE SAME TICK (no flush in between: late writes fail fast —
  clients retry against the directory rather than writing into a
  dropped copy), then offers the export to the new owner, which
  imports before serving.  Objects move WITH their {epoch, seq}
  versions (a version-preserving install, not a re-ingest), so a
  client's CAS token survives the placement move — the reference's
  membership-change semantics (replace_members_test.erl:26-30,
  doc/Readme.md:156-167); the importing row's ballot epoch rises past
  the installed maximum, so post-move writes version-dominate.

v1 boundaries (documented, not hidden): a tenant is placed on ONE
svcnode (the repgroup is the cross-host availability story; compose
by making the owner a replication-group leader); writes racing the
exact export tick fail fast rather than forward.
"""

from __future__ import annotations

import hashlib
from dataclasses import replace
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from riak_ensemble_tpu import funref
from riak_ensemble_tpu import service_directory as sd
from riak_ensemble_tpu import state as statelib

TENANT_MOD = "svc_tenant"


def tenant_id(name: Any) -> Tuple[str, Any]:
    return ("svct", name)


def create_tenant(mgr, runtime, name: Any,
                  view: Optional[List[bool]] = None,
                  timeout: float = 30.0):
    """Register a tenant through the root ensemble
    (manager.erl:157-166 → root:set_ensemble).  Placement is derived,
    so creation carries only the per-tenant peer view (None = all
    peers of the owning service)."""
    if view is not None:
        view = [bool(b) for b in view]
        if not any(view):
            raise ValueError(
                "a tenant view needs at least one member")
    fut = mgr.create_ensemble(
        tenant_id(name), None, [], TENANT_MOD, (view,), timeout)
    return runtime.await_future(fut, timeout + 5.0)


@funref.register("svct:set_args")
def _set_args_fun(ens_id: Any, args: Tuple, _vsn, cs):
    """Root-FSM mutator: read-modify-write of a tenant record ON THE
    CURRENT consensus state (root.erl:74-90 discipline) — a
    local-replica RMW would let a stale gossip copy no-op a retire or
    silently drop one of two concurrent updates (review r4)."""
    cur = cs.ensembles.get(ens_id)
    if cur is None:
        return "failed"
    info = replace(cur, vsn=(cur.vsn[0], cur.vsn[1] + 1),
                   args=tuple(args))
    out = statelib.set_ensemble(ens_id, info, cs)
    return out if out is not None else "failed"


def _mutate_args(mgr, runtime, name: Any, args: Tuple,
                 timeout: float):
    from riak_ensemble_tpu import root as rootlib

    fut = rootlib._call(mgr, mgr.node,
                        funref.ref("svct:set_args", tenant_id(name),
                                   tuple(args)), timeout)
    return runtime.await_future(fut, timeout + 5.0)


def retire_tenant(mgr, runtime, name: Any, timeout: float = 30.0):
    """Retire a tenant cluster-wide: an atomic root-FSM update marks
    its record retired at the next vsn.  Returns "failed" when the
    root has no such tenant (e.g. the record hasn't reached consensus
    yet — retry, don't assume done).  Reconcilers destroy local rows
    on convergence."""
    return _mutate_args(mgr, runtime, name, ("retired",), timeout)


def set_tenant_view(mgr, runtime, name: Any, view: List[bool],
                    timeout: float = 30.0):
    """Consensus-managed per-tenant membership change: the new view
    lands in the registry through an atomic root-FSM update, gossips,
    and every owner's reconciler drives it into the device arrays via
    update_members — never a direct call on the service."""
    view = [bool(b) for b in view]
    if not any(view):
        raise ValueError("a tenant view needs at least one member")
    return _mutate_args(mgr, runtime, name, (view,), timeout)


def tenants(directory) -> Dict[Any, Optional[List[bool]]]:
    """name -> view for every live tenant in the local directory."""
    out = {}
    for ens_id, info in directory.known_ensembles().items():
        if (isinstance(ens_id, tuple) and len(ens_id) == 2
                and ens_id[0] == "svct" and info.mod == TENANT_MOD
                and tuple(info.args) != ("retired",)):
            out[ens_id[1]] = info.args[0] if info.args else None
    return out


def place(name: Any, svcnodes: List[Any]) -> Optional[Any]:
    """Rendezvous (highest-random-weight) placement: deterministic
    from (tenant, registered svcnode set) alone, so every node
    computes the same owner from its gossip replica with no placement
    writes; adding a svcnode moves ~1/N of tenants (the minimal
    reshuffle, unlike mod-N)."""
    if not svcnodes:
        return None

    def weight(node):
        h = hashlib.blake2b(repr((name, node)).encode(),
                            digest_size=8).digest()
        return int.from_bytes(h, "big")
    return max(sorted(svcnodes, key=repr), key=weight)


class ServiceReconciler:
    """check_peers for the scale plane: converge the LOCAL batched
    service onto the gossip-replicated tenant registry + svcnode
    directory.

    ``svc_name`` is this node's registration name in
    ``service_directory``; ``resolve(svc_name)`` returns a handle to
    another node's reconciler (tests: a dict of in-process
    reconcilers; deployment: a ServiceClient dialed from the
    directory address).  The handle needs ``offer_handoff`` and
    ``has_tenant``.
    """

    def __init__(self, runtime, mgr, svc, svc_name: Any,
                 resolve: Callable[[Any], Optional["ServiceReconciler"]],
                 poll: float = 0.25) -> None:
        assert svc.dynamic, "tenant reconciliation needs dynamic=True"
        self.runtime = runtime
        self.mgr = mgr
        self.svc = svc
        self.svc_name = svc_name
        self.resolve = resolve
        self.poll = poll
        #: handoffs offered by retiring owners, pending import
        self._inbox: Dict[Any, List[Tuple[Any, Any]]] = {}
        #: tenants whose import future hasn't resolved yet
        self._importing: Dict[Any, Any] = {}
        #: bounded import retries per tenant (persistent quorum loss
        #: must surface, not spin)
        self._import_attempts: Dict[Any, int] = {}
        self.max_import_attempts = 8
        #: in-flight import payloads, for per-key result verification
        self._import_data: Dict[Any, Tuple] = {}
        #: grace ticks before creating a missing tenant EMPTY (gives a
        #: live retiring owner time to offer the handoff instead)
        self._want_since: Dict[Any, int] = {}
        self.empty_grace_ticks = 8
        self._tick_no = 0
        #: poll=None -> caller-driven tick() (WallRuntime deployments
        #: and repgroup owners drive it from their own loops)
        self._timer = (runtime.schedule(poll, self._on_tick)
                       if poll is not None else None)

    # -- handoff surface (called by peer reconcilers) -----------------------

    def offer_handoff(self, name: Any, data: List[Tuple[Any, Any]]
                      ) -> bool:
        """A retiring owner pushes a tenant's exported keyed data."""
        self._inbox.setdefault(name, []).extend(data)
        return True

    def has_tenant(self, name: Any) -> bool:
        return self.svc.resolve_ensemble(name) is not None \
            or name in self._importing

    # -- the loop -----------------------------------------------------------

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _on_tick(self) -> None:
        try:
            self.tick()
        finally:
            if self._timer is not None:
                self._timer = self.runtime.schedule(self.poll,
                                                    self._on_tick)

    def tick(self) -> None:
        """One reconciliation pass (manager.erl:610-641 discipline).
        Exception-shielded HERE, not in the timer wrapper, so
        caller-driven (poll=None) loops get the same crash isolation
        — one bad pass (malformed registry data, a repgroup lifecycle
        losing its quorum) must never kill the owner's drive loop."""
        try:
            self._tick_body()
        except Exception:
            import traceback
            self.svc._emit("svc_reconcile_error",
                           {"error": traceback.format_exc(limit=8)})

    def _tick_body(self) -> None:
        self._tick_no += 1
        reg = tenants(self.mgr)
        nodes = sorted(sd.list_services(self.mgr), key=repr)
        mine = {n for n in reg if place(n, nodes) == self.svc_name}
        running = set(self.svc._ens_names)

        # grace bookkeeping only matters while a tenant is wanted
        # here: entries for tenants that left placement before local
        # creation would accumulate forever and poison the grace
        # window on a much-later name reuse (review r4)
        for name in [n for n in self._want_since if n not in mine]:
            del self._want_since[name]

        # retire: running but no longer placed here (moved/retired) —
        # atomic export+destroy (late writes fail fast), then offer.
        # A tenant mid-import is NOT retired yet: destroying it would
        # fail the queued import ops and forward only the flushed
        # subset (review r4) — the move waits one import cycle.
        # Each tenant's pass is contained: one malformed registry
        # record (wrong-length view, a racing destroy) must not wedge
        # reconciliation for every later-sorted tenant (review r4).
        for name in sorted(running - mine, key=repr):
            if name in self._importing:
                continue
            self._contained(self._retire_local, name, reg, nodes)

        # create: placed here but not running
        for name in sorted(mine - running, key=repr):
            if name in self._importing:
                continue
            self._contained(self._adopt, name, reg[name])

        # view changes from the registry → device arrays; and late
        # handoffs for tenants we already adopted empty (grace lapsed
        # or the retiring owner was transiently unreachable) merge in
        # create-if-missing — local writes made since stay newest
        for name in sorted(mine & running, key=repr):
            self._contained(self._apply_view, name, reg[name])
            if name in self._inbox and name not in self._importing:
                self._contained(self._import, name,
                                self._inbox.pop(name),
                                create_only=True)

        # resolved imports: verify per-key results — 'failed' entries
        # (no quorum that flush) re-queue for a bounded retry instead
        # of silently serving partial data (review r4)
        for name in [n for n, f in self._importing.items() if f.done]:
            fut = self._importing.pop(name)
            self._contained(self._check_import, name, fut)

    def _contained(self, fn, name, *args, **kw) -> None:
        try:
            fn(name, *args, **kw)
        except Exception:
            import traceback
            self.svc._emit("svc_reconcile_tenant_error",
                           {"name": name,
                            "error": traceback.format_exc(limit=8)})

    def _retire_local(self, name: Any, reg, nodes) -> None:
        svc = self.svc
        ens = svc.resolve_ensemble(name)
        if ens is None:
            return
        new_owner = place(name, nodes) if name in reg else None
        data = self._export(ens)
        # leftover inbox data (a handoff that raced this move) rides
        # along for keys our committed export doesn't cover — it is
        # strictly older, so export entries win
        stale = self._inbox.pop(name, None)
        if stale:
            have = {e[0] for e in data}
            data += [e for e in stale if e[0] not in have]
        svc.destroy_ensemble(name)
        self._want_since.pop(name, None)
        self._import_attempts.pop(name, None)
        if new_owner is None or not data:
            return
        target = self.resolve(new_owner)
        if target is not None:
            target.offer_handoff(name, data)
        # an unreachable new owner loses the push; it will adopt the
        # tenant empty after its grace window — same availability
        # floor as the reference when a node dies holding unhanded
        # data (durability story: compose owners from repgroups)

    def _export(self, ens: int) -> List[Tuple[Any, Any, Tuple]]:
        """Snapshot a tenant's keyed data — WITH versions — from the
        host mirrors + one device gather; synchronous (no flush),
        which is what makes export+destroy atomic within one tick.
        Entries are (key, payload, (epoch, seq)).

        Versions are the per-slot MAX (epoch, seq) across the UP
        member lanes (ADVICE r5): on a leaderless row, lane 0 can
        lag a quorum-committed write (e.g. it was down when the write
        committed), and exporting its stale version would pair the
        newest payload with an old (epoch, seq) — CAS tokens minted
        from the true version would then fail after the install, the
        exact continuity the handoff exists to preserve.  Any
        quorum-committed version is held by at least one up lane of
        the committing quorum, so the masked lexicographic max is the
        committed version (a live leader's lane can never exceed it).
        """
        svc = self.svc
        # settle in-flight launches first: at pipeline_depth > 1 a
        # write may be committed-but-unresolved (slot_handle fills at
        # resolve), and exporting without it while destroy's own
        # drain then ACKS it would lose an acked write across the
        # handoff.  Settling only the launch pipeline keeps the
        # export+destroy tick atomic (no new ops are admitted here).
        svc._drain_launches()
        items = [(key, slot) for key, slot in svc.key_slot[ens].items()
                 if svc.slot_handle[ens].get(slot, 0)]
        if not items:
            return []
        slots = np.asarray([s for _k, s in items], np.int32)
        lanes = svc.up[ens] & svc.member_np[ens]        # [M]
        if not lanes.any():
            lanes = svc.member_np[ens].copy()
        if not lanes.any():
            lanes[0] = True
        eps_l = np.asarray(svc.state.obj_epoch[ens])[:, slots]  # [M, n]
        sqs_l = np.asarray(svc.state.obj_seq[ens])[:, slots]
        vls_l = np.asarray(svc.state.obj_val[ens])[:, slots]
        mask = lanes[:, None]
        # lexicographic max: epoch first, then seq among max-epoch
        # lanes; a slot with no copy on any masked lane exports (0, 0)
        eps = np.maximum(np.where(mask, eps_l, -1).max(0), 0)   # [n]
        sqs = np.maximum(np.where(mask & (eps_l == eps[None, :]),
                                  sqs_l, -1).max(0), 0)
        # the winning version's value — device-native (inline RMW)
        # slots export IT as the payload: their value lives in the
        # engine arrays, not the handle store (slot_handle holds the
        # -1 sentinel)
        vls = np.where(mask & (eps_l == eps[None, :])
                       & (sqs_l == sqs[None, :]), vls_l,
                       np.iinfo(np.int32).min).max(0)
        out = []
        for (key, slot), ve, vs, dv in zip(items, eps, sqs, vls):
            h = svc.slot_handle[ens][slot]
            payload = int(dv) if h == -1 else svc.values[h]
            out.append((key, payload, (int(ve), int(vs))))
        return out

    def _bad_view(self, name: Any, view) -> bool:
        """Malformed registry views (no members, or a length that
        doesn't match this service's peer count) surface as traces,
        never as crashed ticks."""
        if view is None:
            return False
        if not any(view) or len(view) != self.svc.n_peers:
            self.svc._emit("svc_tenant_bad_view",
                           {"name": name, "view": list(view)})
            return True
        return False

    def _adopt(self, name: Any, view) -> None:
        svc = self.svc
        if self._bad_view(name, view):
            return
        first = self._want_since.setdefault(name, self._tick_no)
        if name not in self._inbox and self._tick_no - first < \
                self.empty_grace_ticks:
            # a live retiring owner may still be about to offer; only
            # create EMPTY once the grace window passes
            if self._anyone_else_has(name):
                return
        row = svc.create_ensemble(
            name, None if view is None else np.asarray(view, bool))
        if row is None:
            return  # no capacity: retried next tick, inbox KEPT
        self._want_since.pop(name, None)
        data = self._inbox.pop(name, None)
        if data:
            self._import(name, data)
        self.svc._emit("svc_tenant_adopt",
                       {"name": name, "imported": len(data or ())})

    def _import(self, name: Any, data: List[Tuple],
                create_only: bool = False) -> None:
        """Start an import for an adopted tenant via the
        version-preserving install (CAS continuity across the move).
        With ``create_only`` (late handoffs merging into a live
        tenant) only keys with NO committed local copy install —
        local writes made since the empty adoption stay newest, and
        keep their local versions.  Legacy 2-tuple entries (no
        version) install at (1, 1): still CAS-able, visibly
        pre-move."""
        svc = self.svc
        row = svc.resolve_ensemble(name)
        if row is None:
            self._inbox.setdefault(name, []).extend(data)
            return
        if create_only:
            sh = svc.slot_handle[row]
            ks = svc.key_slot[row]
            data = [e for e in data
                    if not (ks.get(e[0]) is not None
                            and sh.get(ks[e[0]], 0))]
            if not data:
                return
        items = [(e[0], (e[2] if len(e) > 2 else (1, 1)), e[1])
                 for e in data]
        self._import_data[name] = (data, create_only)
        from riak_ensemble_tpu.runtime import Future
        fut = Future()
        try:
            fut.resolve(svc.install_objs(row, items))
        except Exception:
            # lost quorum mid-install (repgroup owners): the whole
            # batch retries through the bounded path
            fut.resolve(["failed"] * len(items))
        self._importing[name] = fut

    def _check_import(self, name: Any, fut) -> None:
        """Verify per-key import results; re-queue genuine failures
        (no quorum that flush) for a bounded retry — a silently
        partial import is data loss with no signal (review r4)."""
        svc = self.svc
        data, create_only = self._import_data.pop(
            name, ((), False))
        results = fut.value if isinstance(fut.value, list) else []
        if len(results) < len(data):
            # an unrecognized/truncated result shape must default to
            # LOST, not to success — this function exists to prevent
            # silent partial imports (review r4)
            results = list(results) + ["failed"] * (len(data)
                                                    - len(results))
        row = svc.resolve_ensemble(name)
        lost: List[Tuple] = []
        for entry, res in zip(data, results):
            key = entry[0]
            if isinstance(res, tuple) and res[0] == "ok":
                continue
            # a 'failed' key that nonetheless holds a committed local
            # copy (raced local write — local wins) needs no retry
            if row is not None:
                slot = svc.key_slot[row].get(key)
                if slot is not None and \
                        svc.slot_handle[row].get(slot, 0):
                    continue
            lost.append(entry)
        if not lost:
            self._import_attempts.pop(name, None)
            return
        n = self._import_attempts.get(name, 0) + 1
        self._import_attempts[name] = n
        if n >= self.max_import_attempts:
            svc._emit("svc_tenant_import_giveup",
                      {"name": name, "keys": len(lost), "attempts": n})
            return
        svc._emit("svc_tenant_import_retry",
                  {"name": name, "keys": len(lost), "attempt": n})
        self._inbox.setdefault(name, []).extend(lost)

    def _anyone_else_has(self, name: Any) -> bool:
        for other in sd.list_services(self.mgr):
            if other == self.svc_name:
                continue
            peer = self.resolve(other)
            if peer is not None and peer.has_tenant(name):
                return True
        return False

    def _apply_view(self, name: Any, view) -> None:
        if view is None or self._bad_view(name, view):
            return
        svc = self.svc
        ens = svc.resolve_ensemble(name)
        if ens is None:
            return
        want = np.asarray(view, bool)
        cur = svc.member_np[ens]
        pending = (svc._desired_mask[ens] or svc._pending_mask[ens]
                   or svc._queued_mask[ens])
        if (cur == want).all() or pending:
            return
        sel = np.zeros((svc.n_ens,), bool)
        sel[ens] = True
        nv = svc.member_np.copy()
        nv[ens] = want
        svc.update_members(sel, nv)
