"""Network front-end for the batched (TPU) service path.

The actor stack has :mod:`riak_ensemble_tpu.netnode` as its one-node
process entry; this module is the same thing for the SCALE path: an
asyncio TCP server exposing a :class:`BatchedEnsembleService` — the
engine-backed thousands-of-ensembles K/V plane — to remote clients.
It is the piece that turns "host service around a device engine" into
"service reachable over DCN", the role the reference's client API
played over disterl (riak_ensemble_client.erl via gen_fsm sends).

Protocol: length-prefixed frames in the restricted wire codec
(:mod:`riak_ensemble_tpu.wire` — no code execution on decode; the
same trust model as the cluster transport).  Requests are
``(req_id, op, args...)`` tuples; each gets one ``(req_id, result)``
response, resolved when the op's flush lands, so a client can pipeline
requests and correlate out-of-order completions:

    ("kput", ens, key, value)        -> ("ok", (epoch, seq)) | "failed"
    ("kget", ens, key)               -> ("ok", value|NOTFOUND) | "failed"
    ("kget_vsn", ens, key)           -> ("ok", value, vsn) | "failed"
    ("kupdate", ens, key, vsn, val)  -> ("ok", new_vsn) | "failed"
    ("kdelete", ens, key)            -> ("ok", vsn) | ("ok", NOTFOUND
                                        when no such key) | "failed"
    ("ksafe_delete", ens, key, vsn)  -> ("ok", new_vsn) | "failed"
    ("stats",)                       -> dict

Malformed or non-allowlisted frames drop the connection (the codec
cannot construct anything outside the protocol types).

    python -m riak_ensemble_tpu.svcnode --port 7601 \
        --n-ens 1024 --n-peers 5 --n-slots 128 [--fast]
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import struct
from typing import Any, Dict, Optional, Tuple

from riak_ensemble_tpu import wire
from riak_ensemble_tpu.config import Config, fast_test_config
from riak_ensemble_tpu.netruntime import NetRuntime
from riak_ensemble_tpu.parallel.batched_host import BatchedEnsembleService

_HDR = struct.Struct(">I")
_MAX_FRAME = 16 << 20


class ServiceServer:
    """TCP front-end around one BatchedEnsembleService."""

    def __init__(self, svc: BatchedEnsembleService,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.svc = svc
        self.host, self.port = host, port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port)
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self.svc.stop()

    def _dispatch(self, op: str, args: tuple):
        svc = self.svc
        if args:
            # The ensemble index comes from the network: reject
            # anything outside [0, n_ens) — Python negative indexing
            # would otherwise alias ens=-1 onto ensemble n_ens-1,
            # crossing the trust boundary.
            ens = args[0]
            if type(ens) is not int or not 0 <= ens < svc.n_ens:
                raise ValueError(f"bad ensemble index {ens!r}")
        if op == "kput":
            return svc.kput(*args)
        if op == "kget":
            return svc.kget(*args)
        if op == "kget_vsn":
            return svc.kget_vsn(*args)
        if op == "kupdate":
            return svc.kupdate(*args)
        if op == "kdelete":
            return svc.kdelete(*args)
        if op == "ksafe_delete":
            return svc.ksafe_delete(*args)
        return None

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        loop = asyncio.get_running_loop()

        def send(req_id: Any, result: Any) -> None:
            try:
                payload = wire.encode((req_id, result))
            except wire.WireError:
                payload = wire.encode((req_id, "failed"))
            writer.write(_HDR.pack(len(payload)) + payload)

        try:
            while True:
                head = await reader.readexactly(_HDR.size)
                (length,) = _HDR.unpack(head)
                if length > _MAX_FRAME:
                    break  # hostile length: drop the connection
                frame = await reader.readexactly(length)
                try:
                    msg = wire.decode(frame)
                    req_id, op = msg[0], msg[1]
                    args = tuple(msg[2:])
                except (wire.WireError, IndexError, TypeError):
                    break  # malformed: drop the connection
                if op == "stats":
                    send(req_id, self.svc.stats())
                    continue
                try:
                    fut = self._dispatch(op, args)
                except Exception:
                    # wrong arity / types from a hostile or buggy
                    # client: answer, don't let the task die with an
                    # unhandled traceback
                    send(req_id, ("error", "bad-request"))
                    continue
                if fut is None:
                    send(req_id, ("error", "unknown-op"))
                    continue
                # Resolution happens inside a flush on this same
                # loop; the waiter writes the response directly.
                fut.add_waiter(
                    lambda result, rid=req_id: send(rid, result))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()


class ServiceClient:
    """Pipelined client: awaitable ops correlated by request id."""

    def __init__(self, host: str, port: int) -> None:
        self.host, self.port = host, port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._ids = itertools.count(1)
        self._pump: Optional[asyncio.Task] = None

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._pump = asyncio.get_running_loop().create_task(
            self._read_loop())

    #: result for ops whose outcome is UNKNOWN (connection lost before
    #: the response arrived): distinct from the protocol's "failed",
    #: which is a definitive rejection — conflating them would let a
    #: retry loop double-apply a write that actually committed.
    DISCONNECTED = ("error", "disconnected")

    async def close(self) -> None:
        if self._pump is not None:
            self._pump.cancel()
        if self._writer is not None:
            self._writer.close()
        self._fail_pending()

    def _fail_pending(self) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_result(self.DISCONNECTED)
        self._pending.clear()

    async def _read_loop(self) -> None:
        try:
            while True:
                head = await self._reader.readexactly(_HDR.size)
                (length,) = _HDR.unpack(head)
                frame = await self._reader.readexactly(length)
                req_id, result = wire.decode(frame)
                fut = self._pending.pop(req_id, None)
                if fut is not None and not fut.done():
                    fut.set_result(result)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError, wire.WireError):
            self._fail_pending()

    async def call(self, op: str, *args: Any, timeout: float = 30.0):
        req_id = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        payload = wire.encode((req_id, op) + args)
        self._writer.write(_HDR.pack(len(payload)) + payload)
        await self._writer.drain()
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._pending.pop(req_id, None)  # a long-lived pipelined
            raise                            # client must not leak ids

    # convenience wrappers
    async def kput(self, ens, key, value, **kw):
        return await self.call("kput", ens, key, value, **kw)

    async def kget(self, ens, key, **kw):
        return await self.call("kget", ens, key, **kw)

    async def kget_vsn(self, ens, key, **kw):
        return await self.call("kget_vsn", ens, key, **kw)

    async def kupdate(self, ens, key, vsn, value, **kw):
        return await self.call("kupdate", ens, key, vsn, value, **kw)

    async def kdelete(self, ens, key, **kw):
        return await self.call("kdelete", ens, key, **kw)

    async def ksafe_delete(self, ens, key, vsn, **kw):
        return await self.call("ksafe_delete", ens, key, vsn, **kw)

    async def stats(self, **kw):
        return await self.call("stats", **kw)


async def serve(n_ens: int, n_peers: int, n_slots: int,
                host: str = "127.0.0.1", port: int = 0,
                tick: float = 0.005,
                config: Optional[Config] = None,
                engine: Any = None) -> ServiceServer:
    """Bring up runtime + service + server; returns the started
    server (call ``await server.stop()`` to tear down)."""
    runtime = NetRuntime("svc", {"svc": (host, 0)})
    runtime.loop = asyncio.get_running_loop()
    svc = BatchedEnsembleService(
        runtime, n_ens, n_peers, n_slots, tick=tick,
        config=config if config is not None else Config(),
        engine=engine)
    server = ServiceServer(svc, host, port)
    await server.start()
    return server


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7601)
    ap.add_argument("--n-ens", type=int, default=1024)
    ap.add_argument("--n-peers", type=int, default=5)
    ap.add_argument("--n-slots", type=int, default=128)
    ap.add_argument("--tick", type=float, default=0.005)
    ap.add_argument("--fast", action="store_true",
                    help="fast_test_config timeouts")
    args = ap.parse_args(argv)

    async def run() -> None:
        server = await serve(
            args.n_ens, args.n_peers, args.n_slots, args.host,
            args.port, args.tick,
            config=fast_test_config() if args.fast else None)
        print(f"svcnode serving {args.n_ens} ensembles on "
              f"{server.host}:{server.port}", flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await server.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
