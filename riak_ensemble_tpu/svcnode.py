"""Network front-end for the batched (TPU) service path.

The actor stack has :mod:`riak_ensemble_tpu.netnode` as its one-node
process entry; this module is the same thing for the SCALE path: an
asyncio TCP server exposing a :class:`BatchedEnsembleService` — the
engine-backed thousands-of-ensembles K/V plane — to remote clients.
It is the piece that turns "host service around a device engine" into
"service reachable over DCN", the role the reference's client API
played over disterl (riak_ensemble_client.erl via gen_fsm sends).

Protocol: length-prefixed frames in the restricted wire codec
(:mod:`riak_ensemble_tpu.wire` — no code execution on decode; the
same trust model as the cluster transport).  Requests are
``(req_id, op, args...)`` tuples; each gets one ``(req_id, result)``
response, resolved when the op's flush lands, so a client can pipeline
requests and correlate out-of-order completions:

    ("kput", ens, key, value)        -> ("ok", (epoch, seq)) | "failed"
    ("kget", ens, key)               -> ("ok", value|NOTFOUND) | "failed"
    ("kget_vsn", ens, key)           -> ("ok", value, vsn) | "failed"
    ("kupdate", ens, key, vsn, val)  -> ("ok", new_vsn) | "failed"
    ("kput_once", ens, key, val)     -> ("ok", vsn) | "failed"
    ("kdelete", ens, key)            -> ("ok", vsn) | ("ok", NOTFOUND
                                        when no such key) | "failed"
    ("ksafe_delete", ens, key, vsn)  -> ("ok", new_vsn) | "failed"
    ("kput_many", ens, keys, vals)   -> [per-key results, in order]
    ("kget_many", ens, keys)         -> [per-key results, in order]
    ("kupdate_many", ens, keys, vsns, vals) / ("kdelete_many",
    ens, keys)                       -> [per-key results, in order]
    ("kput_slab", ens, key_lens, key_arena, val_lens, val_arena)
                                     -> [per-key results, in order]
    ("kget_slab", ens, key_lens, key_arena[, want_vsn])
                                     -> [per-key results, in order]

    ("stats",)                       -> dict
    ("controller",)                  -> dict: the runtime controller's
                                       health section + its full
                                       retained decision journal
                                       (docs/ARCHITECTURE.md §14;
                                       `--autotune` arms actuation)
    ("metrics",)                     -> dict: the service's full obs
                                       registry snapshot (counters,
                                       gauges, histograms, per-tenant
                                       attribution; docs/
                                       ARCHITECTURE.md §11)
    ("metrics", "prometheus")        -> str: the same registry in
                                       Prometheus text exposition
                                       format (scrape-ready)
    ("health",)                      -> dict: ensemble-health summary
                                       (leadered/electing/corrupt row
                                       counts, lease validity, WAL/
                                       queue/pending-write depths) —
                                       host mirrors only, zero device
                                       rounds (the cluster-status
                                       analog; ARCHITECTURE §11).
                                       While a fault-injection plan
                                       is armed (env fault knobs or
                                       programmatic) it carries an
                                       ``injected`` section — rules +
                                       counters — so an operator can
                                       tell a running nemesis from a
                                       real outage (ARCHITECTURE §13)
    ("health", ens)                  -> dict: one row's leader,
                                       lease validity + remaining,
                                       election churn, corrupt flag,
                                       committed (epoch, seq) high-
                                       water, queue/pending depths
    ("fleet", "metrics")             -> dict: every group host's
                                       registry snapshot under host
                                       labels + per-link clock-offset
                                       estimates (leader pulls its
                                       replicas over the obsq
                                       sideband; ARCHITECTURE §11)
    ("fleet", "metrics",
     "prometheus")                   -> str: ONE merged Prometheus
                                       scrape for the whole fleet,
                                       every sample host-labeled
    ("fleet", "health")              -> dict: every host's health
                                       section, host-labeled
    ("fleet", "timeline", fid)       -> dict: the clock-aligned
                                       cross-host timeline of one
                                       flush — leader and replica
                                       spans on ONE axis, honest to
                                       the estimated offset bounds

Reads (``kget``/``kget_vsn``/``kget_many``) are served through the
service's lease-protected fast path when its conditions hold — the
response arrives without waiting for a flush; semantics are
unchanged (linearizable).  ``--no-fast-reads`` (or
``RETPU_FAST_READS=0`` in the server's environment) opts out;
``("stats",)`` reports ``read_fastpath_hits``/``misses`` with
per-reason miss counters and the live ``lease_valid_fraction``.

The ``*_slab`` verbs are the zero-copy batched lane
(docs/ARCHITECTURE.md §12b): whole client-side op slabs — an int32
byte-length table plus one joined arena per column, ascii keys /
bytes payloads — ride a ``wire.Raw``/``encode_parts`` raw frame
client→leader the way PR 5's delta frames already ride
leader→replica, so a 10k-key batch decodes as a handful of term
objects + arena slices instead of 10k per-key containers.
:class:`ServiceClient`'s ``kput_many``/``kget_many`` route through
them automatically whenever the batch fits the slab subset (all-str
ascii keys, all-bytes values) and fall back to the legacy list verbs
otherwise — byte-exotic batches lose nothing.

Dynamic-lifecycle ops (service constructed with ``dynamic=True``;
the runtime create/destroy surface of
``riak_ensemble_manager:create_ensemble``, manager.erl:157-166):

    ("create_ensemble", name[, view]) -> ("ok", ens_id) |
                                         ("error", "no-capacity")
    ("destroy_ensemble", name)        -> ("ok",) | ("error", "unknown")
    ("resolve_ensemble", name)        -> ("ok", ens_id) |
                                         ("error", "unknown")

Malformed or non-allowlisted frames drop the connection (the codec
cannot construct anything outside the protocol types).

    python -m riak_ensemble_tpu.svcnode --port 7601 \
        --n-ens 1024 --n-peers 5 --n-slots 128 [--fast]
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import os
import struct
import sys
from typing import Any, Dict, Optional, Tuple

from riak_ensemble_tpu import faults, wire
from riak_ensemble_tpu.config import Config, fast_test_config
from riak_ensemble_tpu.netruntime import NetRuntime
from riak_ensemble_tpu.parallel.batched_host import BatchedEnsembleService

_HDR = struct.Struct(">I")
_MAX_FRAME = 16 << 20


def _slab_lens(lens_buf, arena_buf) -> "np.ndarray":
    """Validate one slab column's int32 byte-length table against its
    arena (trust boundary: both arrive off the network) and return
    the cumulative offsets [n+1].  Little-endian on the wire — the
    delta lane's existing raw-plane contract."""
    import numpy as np
    lens = np.frombuffer(lens_buf, dtype="<i4")
    if len(lens) and int(lens.min()) < 0:
        raise ValueError("negative slab length")
    offs = np.zeros((len(lens) + 1,), np.int64)
    np.cumsum(lens, out=offs[1:])
    if int(offs[-1]) != memoryview(arena_buf).nbytes:
        raise ValueError("slab arena size mismatch")
    return offs


def _slab_keys(lens_buf, arena_buf) -> list:
    """Key slab -> key list: ONE arena decode (the client's slab lane
    is ascii-only, so char offsets equal byte offsets) + one slice
    per key."""
    offs = _slab_lens(lens_buf, arena_buf)
    s = bytes(arena_buf).decode("ascii")
    o = offs.tolist()
    return [s[o[i]:o[i + 1]] for i in range(len(o) - 1)]


def _slab_vals(lens_buf, arena_buf) -> list:
    """Value slab -> bytes list: memoryview slices of the received
    frame, materialized per value (the payload store owns them past
    the frame's lifetime)."""
    offs = _slab_lens(lens_buf, arena_buf)
    mv = memoryview(arena_buf)
    o = offs.tolist()
    return [bytes(mv[o[i]:o[i + 1]]) for i in range(len(o) - 1)]


#: per-connection backpressure bounds: a client may pipeline at most
#: this many unresolved ops (further frames stay in the TCP receive
#: path — flow control rides the transport), and a client that stops
#: READING while the server responds is dropped once the send buffer
#: passes the cap (it can reconnect; unbounded buffering cannot be
#: taken back).
_MAX_INFLIGHT = 1024
_MAX_WRITE_BUF = 8 << 20


class ServiceServer:
    """TCP front-end around one BatchedEnsembleService."""

    def __init__(self, svc: BatchedEnsembleService,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.svc = svc
        self.host, self.port = host, port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port)
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self.svc.stop()

    def _dispatch(self, op: str, args: tuple):
        svc = self.svc
        if args:
            # The ensemble index comes from the network: reject
            # anything outside [0, n_ens) — Python negative indexing
            # would otherwise alias ens=-1 onto ensemble n_ens-1,
            # crossing the trust boundary.
            ens = args[0]
            if type(ens) is not int or not 0 <= ens < svc.n_ens:
                raise ValueError(f"bad ensemble index {ens!r}")
        if op == "kput":
            return svc.kput(*args)
        if op == "kget":
            return svc.kget(*args)
        if op == "kput_many":
            return svc.kput_many(*args)
        if op == "kget_many":
            return svc.kget_many(*args)
        if op == "kput_slab":
            # zero-copy batched lane: decode = arena slicing, not
            # per-key term decode (malformed tables raise here and
            # answer bad-request)
            return svc.kput_many(ens, _slab_keys(args[1], args[2]),
                                 _slab_vals(args[3], args[4]))
        if op == "kget_slab":
            return svc.kget_many(
                ens, _slab_keys(args[1], args[2]),
                want_vsn=bool(args[3]) if len(args) > 3 else False)
        if op == "kupdate_many":
            return svc.kupdate_many(*args)
        if op == "kdelete_many":
            return svc.kdelete_many(*args)
        if op == "kget_vsn":
            return svc.kget_vsn(*args)
        if op == "kupdate":
            return svc.kupdate(*args)
        if op == "kmodify":
            # mod_fun arrives as a wire-safe funref tuple; resolution
            # (and rejection of unregistered names) happens inside
            # kmodify — the no-code-on-decode trust model holds
            return svc.kmodify(*args)
        if op == "kmodify_many":
            return svc.kmodify_many(*args)
        if op == "kput_once":
            return svc.kput_once(*args)
        if op == "kdelete":
            return svc.kdelete(*args)
        if op == "ksafe_delete":
            return svc.ksafe_delete(*args)
        return None

    def _lifecycle(self, op: str, args: tuple):
        """Synchronous dynamic-ensemble ops (no flush involved).

        Event-loop note: create/destroy dispatch one ``reset_rows``
        launch.  Its XLA program is compiled at SERVICE CONSTRUCTION
        (the dynamic=True constructor issues a same-shape reset over
        all rows), so these handlers never pay the tens-of-seconds
        first-compile on the loop — only an async device dispatch.
        """
        try:
            name = args[0]
            if op == "create_ensemble":
                view = None
                if len(args) > 1 and args[1] is not None:
                    import numpy as np
                    view = np.asarray(args[1], bool)
                if name in self.svc._ens_names:
                    # duplicate != capacity (an orchestrator must not
                    # provision more rows over an idempotent retry)
                    return ("error", "exists")
                row = self.svc.create_ensemble(name, view)
                return (("ok", row) if row is not None
                        else ("error", "no-capacity"))
            if op == "destroy_ensemble":
                return (("ok",) if self.svc.destroy_ensemble(name)
                        else ("error", "unknown"))
            row = self.svc.resolve_ensemble(name)
            return ("ok", row) if row is not None \
                else ("error", "unknown")
        except Exception:
            return ("error", "bad-request")

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        # Per-connection op budget: the read loop blocks once
        # _MAX_INFLIGHT ops are unresolved, so a pipelining client
        # can't grow the queues/pending maps without bound (the
        # VERDICT/advisor backpressure finding).
        inflight = asyncio.Semaphore(_MAX_INFLIGHT)
        bp = self.svc.svc_backpressure

        def send(req_id: Any, result: Any) -> None:
            # Responses are written from flush-context future waiters
            # too — never after close, and never into an unbounded
            # buffer for a client that stopped reading (advisor: drain
            # is only awaited on the request path).
            if writer.is_closing():
                return
            try:
                payload = wire.encode((req_id, result))
            except wire.WireError:
                payload = wire.encode((req_id, "failed"))
            writer.write(_HDR.pack(len(payload)) + payload)
            transport = writer.transport
            if (transport is not None
                    and transport.get_write_buffer_size()
                    > _MAX_WRITE_BUF):
                # slow-reader drop: counted, so ``stats()`` and the
                # retpu_svc_backpressure_total family carry the
                # evidence an operator needs to tell a misbehaving
                # client from a server fault
                bp["write_buf_drops"] += 1
                transport.abort()

        try:
            while True:
                head = await reader.readexactly(_HDR.size)
                (length,) = _HDR.unpack(head)
                if length > _MAX_FRAME:
                    break  # hostile length: drop the connection
                frame = await reader.readexactly(length)
                try:
                    msg = wire.decode(frame)
                    req_id, op = msg[0], msg[1]
                    args = tuple(msg[2:])
                except (wire.WireError, IndexError, TypeError):
                    break  # malformed: drop the connection
                if op == "stats":
                    send(req_id, self.svc.stats())
                    continue
                if op == "metrics":
                    # the obs-plane export verb: the whole registry
                    # as plain JSON-able containers, or Prometheus
                    # text when asked (both wire-encodable)
                    if args and args[0] == "prometheus":
                        send(req_id,
                             self.svc.obs_registry.render_prometheus())
                    else:
                        send(req_id, self.svc.obs_registry.snapshot())
                    continue
                if op == "controller":
                    # runtime-controller verb (ARCHITECTURE §14):
                    # the health section plus the full retained
                    # decision journal — how an operator audits the
                    # self-tuning without grepping dumps
                    send(req_id, {
                        "controller":
                            self.svc.controller.health_section(),
                        "decisions":
                            self.svc.controller.journal.snapshot(),
                    })
                    continue
                if op == "health":
                    # ensemble-health verb (the cluster-status
                    # analog): host-mirror-sourced, zero device
                    # rounds — safe to poll on a loaded service
                    try:
                        ens_arg = None
                        if args:
                            ens_arg = args[0]
                            if type(ens_arg) is not int or \
                                    not 0 <= ens_arg < self.svc.n_ens:
                                raise ValueError(ens_arg)
                        send(req_id, self.svc.health(ens_arg))
                    except Exception:
                        send(req_id, ("error", "bad-request"))
                    continue
                if op == "fleet":
                    # fleet-scope obs verbs (docs/ARCHITECTURE.md
                    # §11): one process answering for the whole
                    # group — merged metrics/health under host
                    # labels, clock-aligned cross-host timelines.
                    # On a standalone service the fleet is this host
                    # alone (same shapes, trivial clock section) and
                    # answers instantly; a service whose fleet call
                    # PULLS (a replicated leader fronted by this
                    # server) blocks up to FLEET_PULL_TIMEOUT on
                    # replica round-trips, so the call runs in the
                    # default executor — never on the event loop,
                    # where it would stall EVERY client's ops.
                    try:
                        sub = args[0] if args else "health"
                        if sub == "metrics":
                            fmt = args[1] if len(args) > 1 else None
                            fn = (lambda f=("prometheus"
                                            if fmt == "prometheus"
                                            else None):
                                  self.svc.fleet_metrics(f))
                        elif sub == "health":
                            fn = self.svc.fleet_health
                        elif sub == "timeline":
                            fid = args[1]
                            if type(fid) is not int or fid <= 0:
                                raise ValueError(fid)
                            fn = (lambda f=fid:
                                  self.svc.fleet_timeline(f))
                        else:
                            send(req_id, ("error", "bad-request"))
                            continue
                        result = await asyncio.get_running_loop() \
                            .run_in_executor(None, fn)
                        send(req_id, result)
                    except Exception:
                        send(req_id, ("error", "bad-request"))
                    continue
                if op in ("create_ensemble", "destroy_ensemble",
                          "resolve_ensemble"):
                    send(req_id, self._lifecycle(op, args))
                    continue
                if inflight.locked():
                    # the read loop is about to block on the
                    # per-connection op budget: a pipelining client
                    # has _MAX_INFLIGHT unresolved ops
                    bp["inflight_stalls"] += 1
                await inflight.acquire()
                try:
                    fut = self._dispatch(op, args)
                except Exception:
                    # wrong arity / types from a hostile or buggy
                    # client: answer, don't let the task die with an
                    # unhandled traceback
                    inflight.release()
                    send(req_id, ("error", "bad-request"))
                    continue
                if fut is None:
                    inflight.release()
                    send(req_id, ("error", "unknown-op"))
                    continue

                # Resolution happens inside a flush on this same
                # loop; the waiter writes the response directly.
                def on_done(result: Any, rid: Any = req_id) -> None:
                    inflight.release()
                    send(rid, result)
                fut.add_waiter(on_done)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()


class ServiceClient:
    """Pipelined client: awaitable ops correlated by request id.

    A dropped socket no longer strands the client: the next op
    transparently reconnects (bounded backoff) before sending — safe
    for every verb, nothing was dispatched yet.  In-flight ops at the
    moment of the drop still resolve ``DISCONNECTED`` (ambiguous by
    contract), but the **idempotent** verbs (``kget*``, ``stats``,
    ``health``, ``metrics``) additionally retry ONCE on a fresh
    connection — a read that dies mid-flight cannot double-apply, so
    the caller never sees the blip.  Writes keep surfacing the
    ambiguity: auto-retrying a ``kput`` whose first attempt may have
    committed would double-apply."""

    #: side-effect-free verbs a mid-flight connection loss may safely
    #: re-issue (exactly once) after reconnecting.  ``kmodify`` /
    #: ``kmodify_many`` are deliberately NOT here and must never be
    #: added: a modify is a read-modify-WRITE, so an ambiguous drop
    #: after which the first attempt may have committed would
    #: double-apply on retry (rmw:add applied twice is a wrong
    #: counter — unlike a CAS, nothing downstream rejects the
    #: duplicate).  The §18 commutative lane raises the stakes: its
    #: early ack makes RMW storms the hot ambiguous-drop shape.
    #: tests/test_comm_repl.py pins this set's write-free-ness.
    IDEMPOTENT_OPS = frozenset({
        "kget", "kget_vsn", "kget_many", "kget_slab",
        "stats", "health", "metrics"})

    #: reconnect backoff schedule (seconds slept before attempts
    #: 2..N; the first attempt is immediate)
    RECONNECT_BACKOFF = (0.05, 0.1, 0.2)

    def __init__(self, host: str, port: int) -> None:
        self.host, self.port = host, port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._ids = itertools.count(1)
        self._pump: Optional[asyncio.Task] = None
        self._ever_connected = False
        self._closed = False
        self.reconnects = 0
        #: serializes reconnection so concurrent ops on a dropped
        #: socket dial once, not once each
        self._rlock = asyncio.Lock()

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._ever_connected = True
        self._pump = asyncio.get_running_loop().create_task(
            self._read_loop())

    async def _reconnect(self) -> bool:
        """Bounded-backoff redial of the configured address; True on
        success.  Only meaningful after a successful :meth:`connect`
        (a never-connected or explicitly closed client stays in the
        documented DISCONNECTED regime)."""
        async with self._rlock:
            if self._closed or not self._ever_connected:
                return False
            if self._writer is not None \
                    and not self._writer.is_closing():
                return True  # a sibling op already re-dialed
            if self._pump is not None:
                self._pump.cancel()
            if self._writer is not None:
                self._writer.close()
            self._fail_pending()
            for i in range(1 + len(self.RECONNECT_BACKOFF)):
                if i:
                    await asyncio.sleep(self.RECONNECT_BACKOFF[i - 1])
                try:
                    await self.connect()
                except (OSError, ConnectionError):
                    continue
                self.reconnects += 1
                return True
            return False

    #: result for ops whose outcome is UNKNOWN (connection lost before
    #: the response arrived): distinct from the protocol's "failed",
    #: which is a definitive rejection — conflating them would let a
    #: retry loop double-apply a write that actually committed.
    DISCONNECTED = ("error", "disconnected")

    async def close(self) -> None:
        self._closed = True
        if self._pump is not None:
            self._pump.cancel()
        if self._writer is not None:
            self._writer.close()
        self._fail_pending()

    def _fail_pending(self) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_result(self.DISCONNECTED)
        self._pending.clear()

    async def _read_loop(self) -> None:
        try:
            while True:
                head = await self._reader.readexactly(_HDR.size)
                (length,) = _HDR.unpack(head)
                frame = await self._reader.readexactly(length)
                req_id, result = wire.decode(frame)
                fut = self._pending.pop(req_id, None)
                if fut is not None and not fut.done():
                    fut.set_result(result)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError, wire.WireError):
            self._fail_pending()

    async def _roundtrip(self, encode, timeout: float):
        """The shared request lifecycle both frame flavors ride:
        disconnected guard, req-id allocation, pending registration,
        scatter-gather write, and the leak-proof cleanup on
        connection loss / timeout (advisor findings — ONE copy, so a
        fix can never miss a flavor).  ``encode(req_id)`` returns the
        frame's parts; encoding errors raise BEFORE the id registers
        (a WireError is a caller bug, never a leaked future)."""
        # Never-connected or already-closed clients get the documented
        # DISCONNECTED result, not an AttributeError (advisor finding).
        # A previously-connected client whose socket DROPPED instead
        # redials (bounded backoff) before sending — nothing was
        # dispatched yet, so this is safe for every verb.
        if self._writer is None or self._writer.is_closing():
            if not await self._reconnect():
                return self.DISCONNECTED
        req_id = next(self._ids)
        parts = encode(req_id)
        length = sum(memoryview(p).nbytes for p in parts)
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            self._writer.write(_HDR.pack(length))
            for p in parts:
                self._writer.write(p)
            await self._writer.drain()
        except (ConnectionError, OSError):
            # The write raced a connection loss: the future must not
            # leak in _pending (advisor finding).
            self._pending.pop(req_id, None)
            return self.DISCONNECTED
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._pending.pop(req_id, None)  # a long-lived pipelined
            raise                            # client must not leak ids

    async def _call_once_retry(self, op: str, encode, timeout: float):
        """One roundtrip, plus the idempotent-verb retry: a read that
        resolved DISCONNECTED mid-flight re-issues exactly once on a
        fresh connection (side-effect-free, so no double-apply risk);
        every other verb surfaces the ambiguity unchanged."""
        r = await self._roundtrip(encode, timeout)
        if r == self.DISCONNECTED and op in self.IDEMPOTENT_OPS \
                and await self._reconnect():
            r = await self._roundtrip(encode, timeout)
        return r

    async def call(self, op: str, *args: Any, timeout: float = 30.0):
        return await self._call_once_retry(
            op, lambda rid: [wire.encode((rid, op) + args)], timeout)

    async def call_parts(self, op: str, *args: Any,
                         timeout: float = 30.0):
        """Zero-copy variant of :meth:`call` for ``wire.Raw``-carrying
        frames (the ``*_slab`` verbs): the request encodes through
        :func:`wire.encode_parts`, so each wrapped buffer goes from
        its owning array straight to the transport — no per-key term
        encode, no arena concatenation into an intermediate frame."""
        return await self._call_once_retry(
            op, lambda rid: wire.encode_parts((rid, op) + args),
            timeout)

    # convenience wrappers
    async def kput(self, ens, key, value, **kw):
        return await self.call("kput", ens, key, value, **kw)

    async def kget(self, ens, key, **kw):
        return await self.call("kget", ens, key, **kw)

    async def kget_vsn(self, ens, key, **kw):
        return await self.call("kget_vsn", ens, key, **kw)

    async def kupdate(self, ens, key, vsn, value, **kw):
        return await self.call("kupdate", ens, key, vsn, value, **kw)

    async def kput_once(self, ens, key, value, **kw):
        return await self.call("kput_once", ens, key, value, **kw)

    async def kmodify(self, ens, key, fnref, default, **kw):
        """Server-side modify; ``fnref`` is a
        :func:`riak_ensemble_tpu.funref.ref` tuple (names resolve in
        the SERVER's registry, the MFA discipline of
        riak_ensemble_peer:kmodify).  Funrefs that resolve to device
        mod-fun table entries (rmw:add etc.) take the single-round
        engine fast path server-side."""
        return await self.call("kmodify", ens, key, tuple(fnref),
                               default, **kw)

    async def kmodify_many(self, ens, keys, fnref, default=0, **kw):
        """Vectorized kmodify: one fnref applied to N keys, per-key
        ('ok', vsn) | 'failed' results in order."""
        return await self.call("kmodify_many", ens, list(keys),
                               tuple(fnref), default, **kw)

    async def kdelete(self, ens, key, **kw):
        return await self.call("kdelete", ens, key, **kw)

    async def ksafe_delete(self, ens, key, vsn, **kw):
        return await self.call("ksafe_delete", ens, key, vsn, **kw)

    @staticmethod
    def _key_slab(keys):
        """(lens, arena) for an all-str ascii key batch; None when the
        batch is outside the slab subset (the caller then takes the
        legacy list verb — nothing is lost, only the zero-copy lane)."""
        if not keys or not all(type(k) is str for k in keys):
            return None
        joined = "".join(keys)
        if not joined.isascii():  # byte lens must equal char lens
            return None
        import numpy as np
        lens = np.fromiter(map(len, keys), np.int32, len(keys))
        return lens, joined.encode("ascii")

    async def kput_many(self, ens, keys, values, **kw):
        """Vectorized keyed writes.  Slab-native: an all-str-ascii /
        all-bytes batch rides the ``kput_slab`` zero-copy lane (one
        length table + one joined arena per column, `wire.Raw` framed
        — no per-key term encode either side); anything else takes
        the legacy ``kput_many`` list verb with identical results."""
        keys, values = list(keys), list(values)
        ks = self._key_slab(keys)
        if ks is not None and len(keys) == len(values) \
                and all(type(v) is bytes for v in values):
            import numpy as np
            key_lens, key_arena = ks
            val_lens = np.fromiter(map(len, values), np.int32,
                                   len(values))
            return await self.call_parts(
                "kput_slab", ens, wire.Raw(key_lens),
                wire.Raw(key_arena), wire.Raw(val_lens),
                wire.Raw(b"".join(values)), **kw)
        return await self.call("kput_many", ens, keys, values, **kw)

    async def kget_many(self, ens, keys, want_vsn=False, **kw):
        """Vectorized keyed reads; all-str-ascii batches ride the
        ``kget_slab`` zero-copy lane (see :meth:`kput_many`)."""
        keys = list(keys)
        ks = self._key_slab(keys)
        if ks is not None:
            key_lens, key_arena = ks
            return await self.call_parts(
                "kget_slab", ens, wire.Raw(key_lens),
                wire.Raw(key_arena), bool(want_vsn), **kw)
        if want_vsn:
            return await self.call("kget_many", ens, list(keys), True,
                                   **kw)
        return await self.call("kget_many", ens, keys, **kw)

    async def kupdate_many(self, ens, keys, vsns, values, **kw):
        return await self.call("kupdate_many", ens, list(keys),
                               [tuple(v) for v in vsns], list(values),
                               **kw)

    async def kdelete_many(self, ens, keys, **kw):
        return await self.call("kdelete_many", ens, list(keys), **kw)

    async def stats(self, **kw):
        return await self.call("stats", **kw)

    async def metrics(self, fmt: Optional[str] = None, **kw):
        """Obs-registry export: dict snapshot by default,
        ``fmt="prometheus"`` for the text exposition format."""
        if fmt is None:
            return await self.call("metrics", **kw)
        return await self.call("metrics", fmt, **kw)

    async def health(self, ens: Optional[int] = None, **kw):
        """Ensemble-health snapshot (the riak_ensemble cluster-status
        analog): service-level depths + per-row aggregates, or one
        row's leader/lease/epoch/churn/corrupt detail with ``ens`` —
        served from host mirrors, zero device rounds."""
        if ens is None:
            return await self.call("health", **kw)
        return await self.call("health", ens, **kw)

    async def controller(self, **kw):
        """Runtime-controller audit verb (docs/ARCHITECTURE.md §14):
        the ``health()`` controller section plus the full retained
        decision journal (cause metric, observed value, old→new knob,
        flush id per decision)."""
        return await self.call("controller", **kw)

    async def fleet_metrics(self, fmt: Optional[str] = None, **kw):
        """Fleet metrics (docs/ARCHITECTURE.md §11): every group
        host's registry under ``host`` labels — dict snapshots by
        default, ``fmt="prometheus"`` for ONE merged scrape text."""
        if fmt is None:
            return await self.call("fleet", "metrics", **kw)
        return await self.call("fleet", "metrics", fmt, **kw)

    async def fleet_health(self, **kw):
        """Every group host's health section, host-labeled, plus the
        per-link clock-offset estimates."""
        return await self.call("fleet", "health", **kw)

    async def fleet_timeline(self, fid: int, **kw):
        """The clock-aligned cross-host timeline of one flush:
        leader and replica spans on ONE (leader-clock) axis, each
        role honest to its link's estimated offset bound."""
        return await self.call("fleet", "timeline", int(fid), **kw)

    async def create_ensemble(self, name, view=None, **kw):
        return await self.call("create_ensemble", name, view, **kw)

    async def destroy_ensemble(self, name, **kw):
        return await self.call("destroy_ensemble", name, **kw)

    async def resolve_ensemble(self, name, **kw):
        return await self.call("resolve_ensemble", name, **kw)


async def serve(n_ens: int, n_peers: int, n_slots: int,
                host: str = "127.0.0.1", port: int = 0,
                tick: float = 0.005,
                config: Optional[Config] = None,
                engine: Any = None, dynamic: Optional[bool] = None,
                data_dir: Optional[str] = None,
                warm: bool = False,
                fast_reads: Optional[bool] = None,
                autotune: Optional[bool] = None) -> ServiceServer:
    """Bring up runtime + service + server; returns the started
    server (call ``await server.stop()`` to tear down).

    ``dynamic`` is tri-state: None (default) = no assertion — a
    restore adopts the persisted lifecycle mode; True/False = the
    caller's explicit assertion — a restore of a data_dir persisted
    with the OTHER mode fails loudly (``_merge_dynamic``).

    ``fast_reads`` is tri-state too: None keeps the service default
    (RETPU_FAST_READS env + config.trust_lease); True/False forces
    the lease-protected read fast path on/off for this server."""
    runtime = NetRuntime("svc", {"svc": (host, 0)})
    runtime.loop = asyncio.get_running_loop()
    cfg = config if config is not None else Config()
    if data_dir is not None and (
            os.path.exists(os.path.join(data_dir, "META"))
            or os.path.exists(os.path.join(data_dir, "CURRENT"))):
        # Operator restart: a data_dir with prior state RESTORES
        # (checkpoint + WAL replay) — a fresh service over an old WAL
        # would silently serve empty while poisoning the log.  The
        # persisted shape wins over the CLI shape, and the persisted
        # lifecycle MODE wins unless the caller explicitly asserted
        # one: restore() treats any present 'dynamic' kwarg as an
        # explicit choice and fails loudly on mismatch, so forwarding
        # an unasserted default would crash every restart of a
        # --dynamic-persisted data_dir (ADVICE r3).  An explicit
        # True OR False still forwards, keeping the loud error for
        # genuinely contradictory assertions in both directions.
        dyn_kw = {} if dynamic is None else {"dynamic": bool(dynamic)}
        svc = BatchedEnsembleService.restore(
            runtime, data_dir, tick=tick, config=cfg, engine=engine,
            data_dir=data_dir, **dyn_kw)
    else:
        svc = BatchedEnsembleService(
            runtime, n_ens, n_peers, n_slots, tick=tick, config=cfg,
            engine=engine, dynamic=bool(dynamic), data_dir=data_dir)
    if fast_reads is not None:
        svc.set_fast_reads(fast_reads)
    if autotune is not None:
        # tri-state like fast_reads: None keeps the service default
        # (the RETPU_AUTOTUNE env knob, off for one release)
        svc.set_autotune(autotune)
    if warm:
        # pre-compile the (K, A) bucket grid — pow2 flush depths x
        # pow2 active-column widths, both want_vsn pack variants
        # (covers the read fast path's get-only fallback shapes) — so
        # no client ever pays a mid-serving first-compile inside its
        # op latency (the dispatch p99 blip).  A --warm boot also
        # captures the per-bucket XLA cost gauges
        # (retpu_step_cost_flops/_bytes) for the metrics verb.
        svc.warmup(capture_costs=True)
    server = ServiceServer(svc, host, port)
    await server.start()
    return server


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7601)
    ap.add_argument("--n-ens", type=int, default=1024)
    ap.add_argument("--n-peers", type=int, default=5)
    ap.add_argument("--n-slots", type=int, default=128)
    ap.add_argument("--tick", type=float, default=0.005)
    ap.add_argument("--fast", action="store_true",
                    help="fast_test_config timeouts")
    ap.add_argument("--dynamic", action="store_true", default=None,
                    help="start with zero ensembles; clients create/"
                         "destroy them at runtime (on restart of an "
                         "existing --data-dir, omitting this adopts "
                         "the persisted mode)")
    ap.add_argument("--data-dir", default=None,
                    help="durability root (WAL + checkpoints); acked "
                         "writes survive crashes")
    ap.add_argument("--warm", "--warm-flush-ladder", dest="warm",
                    action="store_true",
                    help="pre-compile the (K, A) flush ladder — pow2 "
                         "batch depths x pow2 active-column buckets — "
                         "before accepting clients (slower boot, no "
                         "mid-serving compile spikes)")
    ap.add_argument("--no-fast-reads", action="store_true",
                    help="disable the lease-protected read fast path "
                         "(every read takes a device round; same as "
                         "RETPU_FAST_READS=0)")
    ap.add_argument("--mesh-devices", type=int, default=0,
                    help="serve from a mesh engine sharded over this "
                         "many devices along the 'ens' axis (0 = "
                         "single-shard).  On CPU export XLA_FLAGS="
                         "--xla_force_host_platform_device_count=8 "
                         "BEFORE starting the node so jax sees the "
                         "virtual devices")
    ap.add_argument("--autotune", action="store_true", default=None,
                    help="arm the obs-actuated runtime controller "
                         "(same as RETPU_AUTOTUNE=1): auto-tunes the "
                         "launch/replication pipeline knobs and the "
                         "tenant-admission guard from the measured "
                         "obs plane, every decision journaled "
                         "(docs/ARCHITECTURE.md §14; audit via the "
                         "('controller',) verb)")
    args = ap.parse_args(argv)

    engine = None
    if args.mesh_devices:
        from riak_ensemble_tpu.parallel.mesh import mesh_engine
        engine = mesh_engine(args.mesh_devices)

    async def run() -> None:
        server = await serve(
            args.n_ens, args.n_peers, args.n_slots, args.host,
            args.port, args.tick,
            config=fast_test_config() if args.fast else None,
            engine=engine,
            dynamic=args.dynamic, data_dir=args.data_dir,
            warm=args.warm,
            fast_reads=False if args.no_fast_reads else None,
            autotune=args.autotune)
        print(f"svcnode serving {args.n_ens} ensembles on "
              f"{server.host}:{server.port}", flush=True)
        fp = faults.active_plan()
        if fp is not None:
            # loud, once, at boot: a node started under fault-injection
            # knobs is part of a nemesis — an operator tailing the
            # log must never mistake its injected failures for a real
            # incident (the health verb carries the same section)
            print(f"svcnode: FAULT INJECTION ACTIVE "
                  f"{fp.describe()!r}", file=sys.stderr, flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await server.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
