"""Adversarial fault-injection plane (docs/ARCHITECTURE.md §13).

One :class:`FaultPlan` is shared by every transport the repo runs —
the asyncio scalar runtime (:mod:`riak_ensemble_tpu.netruntime`), the
deterministic simulator (:class:`riak_ensemble_tpu.runtime.Network`),
the replication-group leader links
(:class:`riak_ensemble_tpu.parallel.repgroup.PeerLink`) and the WAL's
fsync barrier (:class:`riak_ensemble_tpu.parallel.wal.ServiceWAL`) —
so one nemesis schedule can express the sc.erl fault modes the
reference's EQC suite injects, plus the ones it could not:

- **directional drop** ``A→B`` — the one-directional link failure
  (A's frames to B vanish; B→A still delivers), the classic failover
  killer no symmetric ``partition()`` can reproduce;
- **per-link one-way delay with jitter** — injected RTT, which makes
  the launch/replication pipelining claims falsifiable on one box
  (ops/s must rise with ``pipeline_depth`` once the link is slow);
- **bounded reorder** — adjacent-frame swaps on a link (the
  replica's seq discipline must nack and re-sync, never misapply);
- **fsync delay** — a slow disk under the WAL's ack barrier.

Round 15 extends the plane below the transports into the STORAGE
stack (docs/ARCHITECTURE.md §15) — the reference's headline safety
property is surviving a bad disk (synctree.erl:21-73), so the disk
gets the same injection discipline as the network:

- **per-path-class storage errors** — ``EIO``/``ENOSPC`` raised on
  ``write`` or ``fsync`` for a path class (``wal`` / ``ckpt`` /
  ``tree``), consulted by :mod:`..parallel.wal`, the checkpoint blob
  writer (:mod:`..save`) and the synctree/treestore backends;
- **torn writes** — the next write of a class is truncated at an
  injected byte offset (then fails), leaving a genuinely torn
  record for replay to detect;
- **bit-flip read corruption** — store reads flip a seeded random
  bit with a per-class probability, exercising every CRC gate;
- **crash points** — ``RETPU_CRASHPOINT=<barrier>[:<nth>]``
  terminates the process (``os._exit(CRASH_EXIT)``) at the nth hit
  of a named durability barrier (``wal_append``,
  ``wal_fsync_pre``/``post``, ``ckpt_tmp_write``, ``ckpt_rename``,
  ``replica_apply_pre_ack``, ``tree_save``) — the kill -9 analog
  aimed exactly at the protocol's recovery contract.

Rules are keyed by ``(src, dst)`` endpoint names with ``"*"``
wildcards.  The scalar runtimes use node names; a ``PeerLink`` is
addressed as ``"host:port"`` with :data:`LOCAL` as the leader-side
name.  Faults are installed programmatically (:func:`install`, or a
plan handed directly to a ``Network``) or via environment knobs —
``RETPU_FAULT_DROP``, ``RETPU_FAULT_RTT_MS``,
``RETPU_FAULT_RTT_JITTER_MS``, ``RETPU_FAULT_REORDER``,
``RETPU_FAULT_FSYNC_MS``, ``RETPU_FAULT_STORAGE``,
``RETPU_FAULT_TORN``, ``RETPU_FAULT_CORRUPT``, ``RETPU_FAULT_SEED``,
``RETPU_FAULT_SILENT`` (see the README knob table) — so a subprocess
replica host can run under the same nemesis as its in-process leader.

Every active fault is observable: injected-fault gauges ride each
service's metrics registry, ``svc.health()`` carries an ``injected``
section while a plan is active, and flight-recorder dumps embed the
plan + its counters (an operator can always distinguish a running
nemesis from a real outage).

Drop semantics: by default a dropped frame FAILS FAST at the
injection point (a ``PeerLink`` ticket fires unresolved — a missed
ack; the quorum consequences are identical to a silent blackhole,
the timing is compressed so nemesis sweeps stay cheap).
``silent=True`` (``RETPU_FAULT_SILENT=1``) keeps the true blackhole
timing: nothing fires, callers ride their own deadlines.
"""

from __future__ import annotations

import errno as _errno
import os
import random
import sys
import threading
import time
from typing import Any, Dict, Optional, Tuple

__all__ = ["FaultPlan", "LOCAL", "install", "clear", "plan",
           "active_plan", "from_env", "fsync_sleep", "SoakSchedule",
           "wedge_soak", "storage_raise", "torn_limit", "read_filter",
           "crashpoint", "CRASH_EXIT", "STORAGE_ERRNOS"]

#: the local endpoint name a PeerLink uses for its own (leader) side
LOCAL = "local"

#: exit status of a process killed at an injected crash point — a
#: parent driving a recovery sweep distinguishes "died exactly at the
#: barrier" from any ordinary failure
CRASH_EXIT = 86

#: the storage errno names an injected storage error may carry (the
#: two real bad-disk signals the degradation machinery reacts to)
STORAGE_ERRNOS = {"EIO": _errno.EIO, "ENOSPC": _errno.ENOSPC}

#: the path classes / ops the storage seams consult — rule setters
#: validate against these so a typo'd class can never arm an
#: injecting-nothing nemesis (worse than a crash at arm time)
STORAGE_CLASSES = ("wal", "ckpt", "tree")
STORAGE_OPS = ("write", "fsync")


def _key(src: Optional[str], dst: Optional[str]) -> Tuple[str, str]:
    return (str(src) if src is not None else "*",
            str(dst) if dst is not None else "*")


def _check_class(path_class: str, wild: bool = False) -> None:
    """Reject unknown storage path classes at RULE-SET time — the
    seams look classes up exactly (torn/corrupt) or by candidate
    list (errors), so a typo would arm a rule nothing consults."""
    ok = STORAGE_CLASSES + (("*",) if wild else ())
    if str(path_class) not in ok:
        raise ValueError(
            f"storage path class must be one of {ok}, "
            f"not {path_class!r}")


class FaultPlan:
    """One nemesis schedule: directional rules + injection counters.

    Thread-safe: rule mutation and rule queries take one lock; the
    seeded RNG makes a fixed schedule reproducible.  Counters only
    ever grow (``heal()`` clears the rules, not the evidence).
    """

    def __init__(self, seed: int = 0, silent: bool = False) -> None:
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self.seed = int(seed)
        #: drops surface as immediately-failed sends (False) or as a
        #: true silent blackhole (True: nothing fires, callers hit
        #: their own deadlines)
        self.silent = bool(silent)
        self._drop: set = set()
        self._rtt: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self._reorder: Dict[Tuple[str, str], float] = {}
        self.fsync_ms = 0.0
        self.fsync_jitter_ms = 0.0
        # -- storage rules (docs/ARCHITECTURE.md §15) ----------------
        #: (path_class, op) -> [errno, remaining count or None];
        #: op in ("write", "fsync"), "*" wildcards on either field
        self._storage_err: Dict[Tuple[str, str], list] = {}
        #: path_class -> byte offset; ONE-SHOT: the next write of the
        #: class truncates there (a torn record) and fails
        self._torn: Dict[str, int] = {}
        #: path_class -> probability a store read flips one bit
        self._corrupt: Dict[str, float] = {}
        # -- counters (monotonic; per-link under the same keys) ------
        self.dropped_frames = 0
        self.delayed_frames = 0
        self.delay_injected_ms = 0.0
        self.reordered_frames = 0
        self.fsync_delays = 0
        self.fsync_delay_injected_ms = 0.0
        self.storage_errors_injected = 0
        self.torn_writes_injected = 0
        self.corrupt_reads_injected = 0
        self._per_link: Dict[Tuple[str, str], Dict[str, Any]] = {}

    # -- rule surface ------------------------------------------------------

    def drop(self, src: Optional[str], dst: Optional[str]) -> "FaultPlan":
        """Blackhole frames ``src→dst`` (one direction only)."""
        with self._lock:
            self._drop.add(_key(src, dst))
        return self

    def undrop(self, src: Optional[str], dst: Optional[str]) -> None:
        with self._lock:
            self._drop.discard(_key(src, dst))

    def set_rtt(self, src: Optional[str], dst: Optional[str],
                ms: float, jitter_ms: float = 0.0) -> "FaultPlan":
        """Inject ``ms`` of ONE-WAY delay (± uniform jitter) on every
        frame ``src→dst``.  ``ms=0`` removes the rule."""
        with self._lock:
            if ms <= 0.0 and jitter_ms <= 0.0:
                self._rtt.pop(_key(src, dst), None)
            else:
                self._rtt[_key(src, dst)] = (float(ms),
                                             float(jitter_ms))
        return self

    def set_link_rtt(self, a: Optional[str], b: Optional[str],
                     rtt_ms: float,
                     jitter_ms: float = 0.0) -> "FaultPlan":
        """Convenience: a full round trip of ``rtt_ms`` on the
        ``a↔b`` link, split evenly across the two directions."""
        self.set_rtt(a, b, rtt_ms / 2.0, jitter_ms / 2.0)
        self.set_rtt(b, a, rtt_ms / 2.0, jitter_ms / 2.0)
        return self

    def set_reorder(self, src: Optional[str], dst: Optional[str],
                    prob: float) -> "FaultPlan":
        """Swap adjacent frames ``src→dst`` with probability
        ``prob`` (bounded reorder: a window of exactly two)."""
        with self._lock:
            if prob <= 0.0:
                self._reorder.pop(_key(src, dst), None)
            else:
                self._reorder[_key(src, dst)] = min(float(prob), 1.0)
        return self

    def set_fsync_delay(self, ms: float,
                        jitter_ms: float = 0.0) -> "FaultPlan":
        """Delay every WAL fsync barrier by ``ms`` (± jitter)."""
        with self._lock:
            self.fsync_ms = max(float(ms), 0.0)
            self.fsync_jitter_ms = max(float(jitter_ms), 0.0)
        return self

    def set_storage_error(self, path_class: str, op: str,
                          err: str = "EIO",
                          count: Optional[int] = None) -> "FaultPlan":
        """Raise ``err`` (an errno name from :data:`STORAGE_ERRNOS`)
        on every ``op`` ("write"/"fsync", ``"*"`` = both) touching
        ``path_class`` ("wal"/"ckpt"/"tree", ``"*"`` = all).
        ``count`` bounds the injections (None = until healed)."""
        code = STORAGE_ERRNOS.get(str(err).upper())
        if code is None:
            raise ValueError(
                f"storage fault errno must be one of "
                f"{sorted(STORAGE_ERRNOS)}, not {err!r}")
        _check_class(path_class, wild=True)
        if str(op) not in STORAGE_OPS + ("*",):
            raise ValueError(
                f"storage fault op must be one of "
                f"{STORAGE_OPS + ('*',)}, not {op!r}")
        if count is not None and int(count) < 1:
            raise ValueError(
                f"storage fault count must be >= 1, not {count!r} "
                "(a zero-count rule would arm an armed-but-"
                "injecting-nothing nemesis forever)")
        with self._lock:
            self._storage_err[(str(path_class), str(op))] = [
                code, None if count is None else int(count)]
        return self

    def set_torn_write(self, path_class: str,
                       offset: int) -> "FaultPlan":
        """Tear the NEXT write of ``path_class`` at byte ``offset``
        (the prefix lands on disk, the rest vanishes, the writer sees
        EIO) — one shot, consumed by the write it hits."""
        _check_class(path_class)
        with self._lock:
            self._torn[str(path_class)] = max(0, int(offset))
        return self

    def set_read_corruption(self, path_class: str,
                            prob: float) -> "FaultPlan":
        """Flip one seeded-random bit in each ``path_class`` store
        read with probability ``prob`` (0 removes the rule) — the
        silent-disk-corruption mode every CRC/synctree gate must
        catch, never serve."""
        _check_class(path_class)
        with self._lock:
            if prob <= 0.0:
                self._corrupt.pop(str(path_class), None)
            else:
                self._corrupt[str(path_class)] = min(float(prob), 1.0)
        return self

    def heal(self) -> None:
        """Clear every rule; counters (the evidence) survive."""
        with self._lock:
            self._drop.clear()
            self._rtt.clear()
            self._reorder.clear()
            self.fsync_ms = 0.0
            self.fsync_jitter_ms = 0.0
            self._storage_err.clear()
            self._torn.clear()
            self._corrupt.clear()

    def active(self) -> bool:
        with self._lock:
            return self._active_locked()

    def _active_locked(self) -> bool:
        return bool(self._drop or self._rtt or self._reorder
                    or self.fsync_ms > 0.0
                    or self.fsync_jitter_ms > 0.0
                    or self._storage_err or self._torn
                    or self._corrupt)

    # -- query surface (the transports call these per frame) ---------------

    @staticmethod
    def _candidates(src: str, dst: str):
        return ((src, dst), (src, "*"), ("*", dst), ("*", "*"))

    def _link_counters(self, src: str, dst: str) -> Dict[str, Any]:
        return self._per_link.setdefault(
            (src, dst), {"drops": 0, "delayed": 0, "delay_ms": 0.0,
                         "reorders": 0})

    def should_drop(self, src: str, dst: str) -> bool:
        """True = drop the frame (counted against the link)."""
        with self._lock:
            for k in self._candidates(src, dst):
                if k in self._drop:
                    self.dropped_frames += 1
                    self._link_counters(src, dst)["drops"] += 1
                    return True
        return False

    def dropping(self, src: str, dst: str) -> bool:
        """Rule check WITHOUT counting (planning/health queries)."""
        with self._lock:
            return any(k in self._drop
                       for k in self._candidates(src, dst))

    def delay_s(self, src: str, dst: str) -> float:
        """Sampled injected one-way delay in SECONDS (0.0 = no rule);
        counts every nonzero sample against the link."""
        with self._lock:
            for k in self._candidates(src, dst):
                rule = self._rtt.get(k)
                if rule is None:
                    continue
                ms, jitter = rule
                if jitter > 0.0:
                    ms += self._rng.uniform(-jitter, jitter)
                ms = max(ms, 0.0)
                if ms <= 0.0:
                    return 0.0
                self.delayed_frames += 1
                self.delay_injected_ms += ms
                lc = self._link_counters(src, dst)
                lc["delayed"] += 1
                lc["delay_ms"] += ms
                return ms / 1000.0
        return 0.0

    def should_swap(self, src: str, dst: str) -> bool:
        """True = TRY to swap this frame with the next one on the
        link.  Not counted here: a swap only really happens when a
        second frame is queued — the sender calls
        :meth:`count_reorder` at the moment it actually reorders."""
        with self._lock:
            for k in self._candidates(src, dst):
                prob = self._reorder.get(k)
                if prob is None:
                    continue
                return self._rng.random() < prob
        return False

    def count_reorder(self, src: str, dst: str) -> None:
        """Record one REAL adjacent-frame swap (wire order changed)."""
        with self._lock:
            self.reordered_frames += 1
            self._link_counters(src, dst)["reorders"] += 1

    def fsync_delay_s(self) -> float:
        """Sampled fsync delay in seconds (counts when nonzero)."""
        with self._lock:
            ms = self.fsync_ms
            if self.fsync_jitter_ms > 0.0:
                ms += self._rng.uniform(-self.fsync_jitter_ms,
                                        self.fsync_jitter_ms)
            ms = max(ms, 0.0)
            if ms <= 0.0:
                return 0.0
            self.fsync_delays += 1
            self.fsync_delay_injected_ms += ms
            return ms / 1000.0

    def sleep_fsync(self) -> None:
        d = self.fsync_delay_s()
        if d > 0.0:
            time.sleep(d)

    # -- storage query surface (the stores call these per access) -----------

    def storage_error(self, path_class: str,
                      op: str) -> Optional[OSError]:
        """The OSError an armed storage rule injects for this access
        (None = clean).  Counted; a bounded rule decrements and
        self-removes at zero."""
        with self._lock:
            for k in ((path_class, op), (path_class, "*"),
                      ("*", op), ("*", "*")):
                rule = self._storage_err.get(k)
                if rule is None:
                    continue
                code, remaining = rule
                if remaining is not None:
                    if remaining <= 0:
                        continue
                    rule[1] = remaining - 1
                    if rule[1] <= 0:
                        self._storage_err.pop(k, None)
                self.storage_errors_injected += 1
                return OSError(
                    code, f"injected {_errno.errorcode[code]} on "
                          f"{path_class} {op}")
        return None

    def torn_limit(self, path_class: str) -> Optional[int]:
        """Byte offset the next write of ``path_class`` must tear at
        (None = no rule).  One-shot: consumes the rule, counts."""
        with self._lock:
            off = self._torn.pop(str(path_class), None)
            if off is not None:
                self.torn_writes_injected += 1
            return off

    def corrupt_read(self, path_class: str, data: bytes) -> bytes:
        """Maybe flip one seeded-random bit of ``data`` (per the
        class's read-corruption probability); counted when it fires."""
        with self._lock:
            prob = self._corrupt.get(str(path_class))
            if not data or prob is None or self._rng.random() >= prob:
                return data
            i = self._rng.randrange(len(data))
            bit = 1 << self._rng.randrange(8)
            self.corrupt_reads_injected += 1
        out = bytearray(data)
        out[i] ^= bit
        return bytes(out)

    # -- observability -----------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        """Plain-data (wire-encodable) snapshot of rules + counters —
        the health verb's ``injected`` section, the flight-recorder
        dump section, and the bench's embedded fault config."""
        with self._lock:
            return {
                "active": self._active_locked(),
                "silent": self.silent,
                "seed": self.seed,
                "drop": sorted(f"{s}>{d}" for s, d in self._drop),
                "rtt_ms": {f"{s}>{d}": [ms, jit] for (s, d), (ms, jit)
                           in sorted(self._rtt.items())},
                "reorder": {f"{s}>{d}": p for (s, d), p
                            in sorted(self._reorder.items())},
                "fsync_ms": self.fsync_ms,
                "fsync_jitter_ms": self.fsync_jitter_ms,
                "storage": {
                    f"{c}.{o}": [_errno.errorcode.get(code, code), n]
                    for (c, o), (code, n)
                    in sorted(self._storage_err.items())},
                "torn": dict(sorted(self._torn.items())),
                "corrupt": dict(sorted(self._corrupt.items())),
                "counters": self.counters(),
            }

    def counters(self) -> Dict[str, Any]:
        return {
            "dropped_frames": self.dropped_frames,
            "delayed_frames": self.delayed_frames,
            "delay_injected_ms": round(self.delay_injected_ms, 3),
            "reordered_frames": self.reordered_frames,
            "fsync_delays": self.fsync_delays,
            "fsync_delay_injected_ms": round(
                self.fsync_delay_injected_ms, 3),
            "storage_errors_injected": self.storage_errors_injected,
            "torn_writes_injected": self.torn_writes_injected,
            "corrupt_reads_injected": self.corrupt_reads_injected,
        }

    def link_injected(self, src: str, dst: str) -> Dict[str, Any]:
        """One link's injected-fault view (per-link health section):
        whether rules target it right now, plus its counters."""
        with self._lock:
            cand = self._candidates(src, dst)
            rtt = next((self._rtt[k] for k in cand if k in self._rtt),
                       None)
            reorder = next((self._reorder[k] for k in cand
                            if k in self._reorder), None)
            counts = self._per_link.get((src, dst), {})
            return {
                "dropping": any(k in self._drop for k in cand),
                "rtt_ms": (0.0 if rtt is None else rtt[0]),
                "rtt_jitter_ms": (0.0 if rtt is None else rtt[1]),
                "reorder": (0.0 if reorder is None else reorder),
                "drops": int(counts.get("drops", 0)),
                "delayed": int(counts.get("delayed", 0)),
                "delay_ms": round(float(counts.get("delay_ms", 0.0)),
                                  3),
                "reorders": int(counts.get("reorders", 0)),
            }


# -- the process-global plan (env-armed) --------------------------------------

def _parse_links(spec: str, with_value: bool = False,
                 knob: str = "fault knob"):
    """``"a>b,b>*"`` → [("a", "b"), ("b", "*")].  With
    ``with_value=True`` each entry MUST carry a trailing ``=value``
    suffix → [("a", "b", 2.0)] — ``=`` (not ``:``) precisely so a
    ``host:port`` endpoint can never have its port consumed as the
    value; an entry without a parseable value raises loudly (a
    silently-ignored rule would report an armed nemesis that injects
    nothing)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        val = None
        if with_value:
            part, sep, v = part.rpartition("=")
            try:
                val = float(v) if sep else None
            except ValueError:
                val = None
            if val is None:
                raise ValueError(
                    f"{knob}: per-link entry {part + sep + v!r} "
                    f"needs a trailing =value (e.g. 'a>b=2.5')")
        if ">" not in part:
            src, dst = "*", part
        else:
            src, _, dst = part.partition(">")
        entry = (src.strip() or "*", dst.strip() or "*")
        out.append(entry + ((val,) if val is not None else ()))
    return out


def _parse_storage(spec: str):
    """``"wal.fsync=ENOSPC,ckpt.write=EIO:2"`` →
    [("wal", "fsync", "ENOSPC", None), ("ckpt", "write", "EIO", 2)].
    A malformed entry raises loudly (same contract as
    :func:`_parse_links`: an armed-but-injecting-nothing nemesis is
    worse than a crash at arm time)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        pc_op, sep, err = part.partition("=")
        cls, dot, op = pc_op.partition(".")
        count = None
        if ":" in err:
            err, _, n = err.partition(":")
            count = int(n)
        if not sep or not dot or err.upper() not in STORAGE_ERRNOS:
            raise ValueError(
                f"RETPU_FAULT_STORAGE: entry {part!r} must be "
                f"<class>.<op>=<{'|'.join(sorted(STORAGE_ERRNOS))}>"
                f"[:count]")
        out.append((cls.strip() or "*", op.strip() or "*",
                    err.upper(), count))
    return out


def _parse_class_values(spec: str, knob: str, conv):
    """``"wal:100,tree:0.5"`` → [("wal", conv("100")), ...]."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        cls, sep, v = part.partition(":")
        try:
            val = conv(v) if sep else None
        except ValueError:
            val = None
        if val is None:
            raise ValueError(
                f"{knob}: entry {part!r} needs <class>:<value>")
        out.append((cls.strip(), val))
    return out


def from_env(environ=None) -> Optional[FaultPlan]:
    """Build a plan from the environment fault knobs; None when no
    knob is set (the common case costs one dict scan at arm time)."""
    env = os.environ if environ is None else environ
    keys = ("RETPU_FAULT_DROP", "RETPU_FAULT_RTT_MS",
            "RETPU_FAULT_RTT_JITTER_MS", "RETPU_FAULT_REORDER",
            "RETPU_FAULT_FSYNC_MS", "RETPU_FAULT_STORAGE",
            "RETPU_FAULT_TORN", "RETPU_FAULT_CORRUPT")
    if not any(env.get(k) for k in keys):
        return None
    p = FaultPlan(seed=int(env.get("RETPU_FAULT_SEED", "0") or 0),
                  silent=env.get("RETPU_FAULT_SILENT", "") == "1")
    jitter = float(env.get("RETPU_FAULT_RTT_JITTER_MS", "0") or 0.0)
    for entry in _parse_links(env.get("RETPU_FAULT_DROP", "")):
        p.drop(entry[0], entry[1])
    rtt_spec = env.get("RETPU_FAULT_RTT_MS", "").strip()
    if rtt_spec:
        try:
            # global form: one number, every link both directions
            p.set_rtt("*", "*", float(rtt_spec), jitter)
        except ValueError:
            for entry in _parse_links(rtt_spec, with_value=True,
                                      knob="RETPU_FAULT_RTT_MS"):
                p.set_rtt(entry[0], entry[1], entry[2], jitter)
    ro_spec = env.get("RETPU_FAULT_REORDER", "").strip()
    if ro_spec:
        try:
            p.set_reorder("*", "*", float(ro_spec))
        except ValueError:
            for entry in _parse_links(ro_spec, with_value=True,
                                      knob="RETPU_FAULT_REORDER"):
                p.set_reorder(entry[0], entry[1], entry[2])
    fs = env.get("RETPU_FAULT_FSYNC_MS", "").strip()
    if fs:
        p.set_fsync_delay(float(fs))
    for cls, op, err, count in _parse_storage(
            env.get("RETPU_FAULT_STORAGE", "")):
        p.set_storage_error(cls, op, err, count)
    for cls, off in _parse_class_values(
            env.get("RETPU_FAULT_TORN", ""), "RETPU_FAULT_TORN", int):
        p.set_torn_write(cls, off)
    for cls, prob in _parse_class_values(
            env.get("RETPU_FAULT_CORRUPT", ""), "RETPU_FAULT_CORRUPT",
            float):
        p.set_read_corruption(cls, prob)
    return p


_global: Optional[FaultPlan] = None
_armed = False
_arm_lock = threading.Lock()


def install(p: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``p`` as the process-global plan (None = disarm; the
    env knobs are NOT re-read after an explicit install/clear)."""
    global _global, _armed
    with _arm_lock:
        _global = p
        _armed = True
    return p


def clear() -> None:
    install(None)


def plan() -> Optional[FaultPlan]:
    """The process-global plan: an explicit :func:`install` wins;
    otherwise the environment fault knobs arm one lazily (once).
    A malformed knob spec disarms the plane and shouts to stderr —
    the first consumer may be a transport worker thread, and an
    exception there would kill the thread and wedge its link, which
    is worse than running without the nemesis."""
    global _global, _armed
    if not _armed:
        with _arm_lock:
            if not _armed:
                try:
                    _global = from_env()
                except Exception as exc:
                    print("riak_ensemble_tpu.faults: IGNORING "
                          f"malformed fault-injection knobs: {exc}",
                          file=sys.stderr, flush=True)
                    _global = None
                _armed = True
    return _global


def active_plan() -> Optional[FaultPlan]:
    """The global plan iff it has at least one live rule — the ONE
    call every hot path makes (None short-circuits everything)."""
    p = plan()
    return p if p is not None and p.active() else None


def fsync_sleep() -> None:
    """The ServiceWAL sync hook's default: sleep the injected fsync
    delay of the active global plan (no-op otherwise)."""
    p = active_plan()
    if p is not None:
        p.sleep_fsync()


# -- storage seams (stores call these; no-ops without an armed plan) ----------

def storage_raise(path_class: str, op: str) -> None:
    """Raise the active plan's injected storage error for this
    access, if any — the one call every store write/fsync path makes
    (None plan short-circuits to a single function call)."""
    p = active_plan()
    if p is not None:
        err = p.storage_error(path_class, op)
        if err is not None:
            raise err


def torn_limit(path_class: str) -> Optional[int]:
    """Byte offset the next write must tear at (None = whole write).
    One-shot against the active plan."""
    p = active_plan()
    return p.torn_limit(path_class) if p is not None else None


def read_filter(path_class: str, data: bytes) -> bytes:
    """Pass store-read bytes through the active plan's bit-flip
    corruption rule (identity without one)."""
    p = active_plan()
    return data if p is None else p.corrupt_read(path_class, data)


# -- crash-point scheduler (docs/ARCHITECTURE.md §15) -------------------------

#: hits per barrier name this process has seen (the nth selector's
#: state; reading it from a test is fine, the process usually dies
#: before anyone can)
CRASHPOINT_HITS: Dict[str, int] = {}


def crashpoint(name: str) -> None:
    """A named durability barrier: when ``RETPU_CRASHPOINT`` names
    this barrier (``<name>`` or ``<name>:<nth>``), the nth hit
    terminates the process via ``os._exit(CRASH_EXIT)`` — no atexit,
    no flushes, no cleanup beyond draining std streams, exactly the
    kill -9 a recovery sweep aims at the barrier.  Unarmed (the
    normal case) this is one env read per barrier crossing, orders
    of magnitude under the fsync it sits next to."""
    spec = os.environ.get("RETPU_CRASHPOINT", "")
    if not spec:
        return
    target, _, nth = spec.partition(":")
    if target != name:
        return
    try:
        need = int(nth) if nth else 1
    except ValueError:
        # malformed nth: the first consumer is a durability barrier
        # inside the serving loop (WAL lock held) — shout and disarm
        # rather than raise there, the plan()-knob discipline
        print("riak_ensemble_tpu.faults: IGNORING malformed "
              f"RETPU_CRASHPOINT={spec!r} (bad :nth)",
              file=sys.stderr, flush=True)
        os.environ.pop("RETPU_CRASHPOINT", None)
        return
    hits = CRASHPOINT_HITS.get(name, 0) + 1
    CRASHPOINT_HITS[name] = hits
    if hits >= need:
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:  # noqa: BLE001 — dying anyway
            pass
        os._exit(CRASH_EXIT)


# -- standing chaos: scheduled nemesis soaks ----------------------------------

class SoakSchedule:
    """Run a nemesis soak on a time schedule — chaos as a STANDING
    gate, not a one-off test run.

    The runtime controller's chaos actuator owns one of these: every
    ``interval_s`` of clock time, ``maybe_run(now)`` invokes the
    runner (default :func:`wedge_soak`) and retains its verdict.
    ``interval_s <= 0`` disarms the schedule entirely (the default —
    a soak injects real faults into a serving system, so it is armed
    explicitly, never inherited).  The clock is injectable so tests
    drive the schedule on virtual time."""

    def __init__(self, interval_s: float,
                 runner: Optional[Any] = None,
                 clock: Optional[Any] = None) -> None:
        self.interval_s = float(interval_s)
        self.runner = runner if runner is not None else wedge_soak
        self._clock = clock if clock is not None else time.monotonic
        self._next_due = (self._clock() + self.interval_s
                          if self.interval_s > 0 else float("inf"))
        self.runs = 0
        self.failures = 0
        self.last: Optional[Dict[str, Any]] = None

    def due(self, now: Optional[float] = None) -> bool:
        if self.interval_s <= 0:
            return False
        return (self._clock() if now is None else now) >= self._next_due

    def maybe_run(self, target: Any,
                  now: Optional[float] = None
                  ) -> Optional[Dict[str, Any]]:
        """Run the soak against ``target`` if it is due; returns the
        soak result dict (``ok``/``detect_s``/``bound_s``/...) or
        None when not due.  A raising runner records a failed soak
        instead of propagating — the soak gate must never take down
        the serving loop it polices."""
        now = self._clock() if now is None else now
        if not self.due(now):
            return None
        self._next_due = now + self.interval_s
        self.runs += 1
        try:
            result = self.runner(target)
        except Exception as exc:  # noqa: BLE001 — verdict, not crash
            result = {"ok": False, "error": repr(exc)}
        if not result.get("ok"):
            self.failures += 1
        self.last = result
        return result


def wedge_soak(svc: Any) -> Dict[str, Any]:
    """The default standing soak: a SILENT ack blackhole on every
    replication link (``FaultPlan(silent=True)`` — true half-open
    timing, nothing fails fast; the same mode the ``slow``-marked
    nemesis sweeps run under via ``RETPU_FAULT_SILENT=1``), then one
    :meth:`heartbeat` round.  The assertion is WEDGE DETECTION, the
    PR 9 half-open bound: a leader whose acks silently vanish must
    observe the lost quorum within ``2 x PeerLink.IO_TIMEOUT``, never
    ride a dead link forever.  The previously-armed plan (an outer
    nemesis) is restored afterward, rules healed, quorum re-confirmed
    with a second heartbeat so the soak leaves the group exactly as
    it found it.  Services without links (no group, or a replica
    lane) report ``skipped`` — there is no ack path to wedge."""
    links = getattr(svc, "_links", None)
    if not links:
        return {"ok": True, "skipped": "no replication links"}
    bound_s = 2.0 * max(type(l).IO_TIMEOUT for l in links)
    prev = plan()
    soak = FaultPlan(silent=True)
    for link in links:
        # acks vanish, applies deliver: src = the peer's label, dst
        # wildcard so a custom leader-side fault_label still matches
        soak.drop(link.label, None)
    install(soak)
    t0 = time.monotonic()
    try:
        quorum_ok = bool(svc.heartbeat())
        detect_s = time.monotonic() - t0
    finally:
        soak.heal()
        install(prev)
    healed_ok = bool(svc.heartbeat())
    return {
        "ok": (not quorum_ok) and detect_s <= bound_s and healed_ok,
        "detect_s": round(detect_s, 6),
        "bound_s": round(bound_s, 3),
        "quorum_ok_under_blackhole": quorum_ok,
        "healed_quorum_ok": healed_ok,
        "dropped_frames": soak.dropped_frames,
    }
