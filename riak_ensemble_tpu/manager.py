"""Per-node cluster manager: gossip, reconciliation, peer supervision.

Re-implementation of ``src/riak_ensemble_manager.erl`` (749 LoC) as one
actor per virtual node, folding in the peer-supervisor role
(``riak_ensemble_peer_sup.erl``) since peers here are actors rather
than supervised OS processes.

Responsibilities mirrored from the reference:

- Owns the node's :class:`~riak_ensemble_tpu.state.ClusterState` and a
  read cache of per-ensemble ``(leader, (vsn, views), pending)`` data —
  the ETS table the hot paths read (manager.erl:188-245).  The
  :class:`~riak_ensemble_tpu.directory.Directory` interface peers and
  routers use IS this cache.
- Cluster activation: ``enable`` creates the root ensemble with a
  single local peer and enables the state (activate,
  manager.erl:498-516).
- Join/remove forward to the root ensemble: join pulls the remote
  cluster state first and adopts it if ``join_allowed``
  (manager.erl:311-334,518-532); the consensus write happens via a
  root kmodify on the *remote* cluster (riak_ensemble_root:join).
- Gossip: every 2s tick sends the full cluster state to <=10 random
  other members and requests wanted-but-unknown remote peer addresses
  (tick/send_gossip/request_remote_peers, manager.erl:569-587,643-666);
  inbound gossip merges newest-vsn-wins (merge_gossip, :589-596).
- Reconciliation: any state change diffs wanted-vs-running local peers
  and starts/stops them, gated on the backend's ``ready_to_start``
  (state_changed/check_peers, manager.erl:610-641,697-715).
- Persistence through the coalescing storage manager under the
  ``manager`` key; saves skipped when unchanged (maybe_save_state,
  manager.erl:601-608,534-567).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from riak_ensemble_tpu import state as statelib
from riak_ensemble_tpu.backend import BACKENDS
from riak_ensemble_tpu.config import Config
from riak_ensemble_tpu.directory import Directory
from riak_ensemble_tpu.peer import Peer, peer_name
from riak_ensemble_tpu.router import start_routers
from riak_ensemble_tpu.runtime import Actor, Future, Runtime, Timer
from riak_ensemble_tpu.state import ClusterState
from riak_ensemble_tpu.storage import Storage
from riak_ensemble_tpu.types import EnsembleInfo, PeerId, Views, Vsn

ROOT = "root"

#: manager.erl:569-573 — gossip/reconcile tick period (seconds).
TICK = 2.0
#: manager.erl:581-587 — gossip fan-out bound per tick.
GOSSIP_FANOUT = 10


def manager_name(node: str) -> Tuple:
    return ("manager", node)


class Manager(Actor, Directory):
    def __init__(self, runtime: Runtime, node: str, config: Config,
                 storage: Storage, **peer_kw) -> None:
        super().__init__(runtime, manager_name(node), node)
        self.config = config
        self.storage = storage
        self.peer_kw = peer_kw  # plumbed into started peers (tests)

        #: ens -> (leader, (vsn, views), pending | None) — the ETS cache
        self.ensemble_data: Dict[Any, Tuple] = {}
        #: (ensemble, peer_id) -> actor name, learned via pid exchange
        self.remote_peers: Dict[Tuple[Any, PeerId], Any] = {}
        #: local running peers, the peer_sup registry
        self.local_peers: Dict[Tuple[Any, PeerId], Peer] = {}
        self._pending_calls: Dict[int, Future] = {}
        self._next_ref = 0

        start_routers(runtime, node)
        self.cluster_state = self._reload_state()
        self._tick_timer: Timer = self.send_after(TICK, ("tick",))
        # deferred initial reconciliation (gen_server:cast(self(), init))
        self.runtime.post(self.name, ("init",))

    # ------------------------------------------------------------------
    # Directory interface (the ETS read API, manager.erl:188-245)

    def enabled(self) -> bool:
        return self.cluster_state.enabled

    def get_cluster_state(self) -> ClusterState:
        return self.cluster_state

    def get_leader(self, ensemble) -> Optional[PeerId]:
        data = self.ensemble_data.get(ensemble)
        return data[0] if data else None

    def get_views(self, ensemble) -> Optional[Tuple[Vsn, Views]]:
        data = self.ensemble_data.get(ensemble)
        return data[1] if data else None

    def get_pending(self, ensemble) -> Optional[Tuple[Vsn, Views]]:
        data = self.ensemble_data.get(ensemble)
        return data[2] if data else None

    def get_members(self, ensemble) -> Tuple[PeerId, ...]:
        views = self.get_views(ensemble)
        if not views:
            return ()
        from riak_ensemble_tpu.types import members_of
        return members_of(views[1])

    def cluster(self) -> List[str]:
        return sorted(self.cluster_state.members)

    def get_peer_addr(self, ensemble, peer_id: PeerId):
        if peer_id.node == self.node:
            name = peer_name(ensemble, peer_id)
            return name if self.runtime.whereis(name) is not None else None
        return self.remote_peers.get((ensemble, peer_id))

    def known_ensembles(self) -> Dict[Any, EnsembleInfo]:
        return dict(self.cluster_state.ensembles)

    # -- write-side Directory hooks called by local peers --------------

    def update_ensemble(self, ensemble, peer_id, views, vsn) -> None:
        """Leader pushes committed views (async singleton in the
        reference, peer.erl:1186-1197 → manager.erl:273-279)."""
        self.runtime.post(self.name,
                          ("update_ensemble", ensemble, peer_id,
                           tuple(tuple(v) for v in views), vsn))

    def gossip_pending(self, ensemble, vsn, views) -> None:
        self.runtime.post(self.name,
                          ("gossip_pending", ensemble, vsn,
                           tuple(tuple(v) for v in views)))

    def root_gossip(self, peer, vsn, peer_id, views) -> None:
        """Root leader pushes its views via a kmodify cast to itself
        (riak_ensemble_root:gossip/4 → root_cast {gossip,..})."""
        from riak_ensemble_tpu import root as rootlib
        rootlib.gossip(self, peer, vsn, peer_id, views)

    def stop_peer(self, ensemble, peer_id) -> None:
        self._stop_peer((ensemble, peer_id))

    # ------------------------------------------------------------------
    # public ops (drive from tests/clients; sync ones via mgr_call)

    def enable(self) -> str:
        """Synchronous within the node (manager.erl:296-310)."""
        cs = self.cluster_state
        if cs.enabled:
            return "error"
        root_leader = PeerId(ROOT, self.node)
        info = EnsembleInfo(vsn=(0, 0), leader=root_leader,
                            views=((root_leader,),), seq=(0, 0),
                            mod="basic", args=())
        cs2 = statelib.enable(cs)
        cs2 = statelib.add_member((0, 0), self.node, cs2)
        cs2 = statelib.set_ensemble(ROOT, info, cs2)
        assert cs2 is not None
        self._save_state(cs2)
        self.storage.sync()
        self._state_changed()
        return "ok"

    def join_async(self, other_node: str, timeout: float = 60.0) -> Future:
        """This node joins other_node's cluster (manager.erl:311-334)."""
        fut = Future()
        self.runtime.post(self.name, ("join", other_node, timeout, fut))
        return fut

    def remove_async(self, target_node: str, timeout: float = 60.0
                     ) -> Future:
        fut = Future()
        self.runtime.post(self.name, ("remove", target_node, timeout, fut))
        return fut

    def create_ensemble(self, ensemble, leader: Optional[PeerId],
                        members, mod: str = "basic", args: Tuple = (),
                        timeout: float = 10.0) -> Future:
        """manager.erl:157-166 → root:set_ensemble."""
        from riak_ensemble_tpu import root as rootlib
        info = EnsembleInfo(vsn=(0, 0), leader=leader,
                            views=(tuple(members),), seq=(0, 0),
                            mod=mod, args=tuple(args))
        return rootlib.set_ensemble(self, ensemble, info, timeout)

    def check_quorum(self, ensemble, timeout: float = 5.0) -> Future:
        """manager.erl:252-258 — resolves True iff the leader can
        commit against a quorum right now."""
        from riak_ensemble_tpu import router as routerlib
        out = Future()
        fut = routerlib.sync_send_event_fut(self.runtime, self.node,
                                            ensemble, ("check_quorum",),
                                            timeout)
        fut.add_waiter(lambda r: out.resolve(r == "ok"))
        return out

    def count_quorum(self, ensemble, timeout: float = 5.0) -> Future:
        """manager.erl:260-267 — resolves to the number of reachable
        quorum members (0 on timeout)."""
        from riak_ensemble_tpu import router as routerlib
        out = Future()
        fut = routerlib.sync_send_event_fut(self.runtime, self.node,
                                            ensemble, ("ping_quorum",),
                                            timeout)

        def done(r):
            if isinstance(r, tuple) and len(r) == 3:
                out.resolve(len(r[2]))
            else:
                out.resolve(0)

        fut.add_waiter(done)
        return out

    def get_leader_addr(self, ensemble):
        """get_leader_pid analog (manager.erl:112-119)."""
        leader = self.get_leader(ensemble)
        if leader is None:
            return None
        return self.get_peer_addr(ensemble, leader)

    # ------------------------------------------------------------------
    # actor event loop

    def handle(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "tick":
            self._tick()
        elif kind == "init":
            self._state_changed()
        elif kind == "gossip":
            self._merge_gossip(msg[1])
        elif kind == "gossip_pending":
            _, ensemble, vsn, views = msg
            cs = statelib.set_pending(vsn, ensemble, views,
                                      self.cluster_state)
            if cs is not None:
                self._save_state(cs)
                self._state_changed()
        elif kind == "update_ensemble":
            _, ensemble, leader, views, vsn = msg
            cs = statelib.update_ensemble(vsn, ensemble, leader, views,
                                          self.cluster_state)
            if cs is not None:
                self._save_state(cs)
                self._state_changed()
        elif kind == "join":
            _, other_node, timeout, fut = msg
            self.runtime.spawn_task(self._do_join(other_node, timeout, fut),
                                    name=f"join:{self.node}")
        elif kind == "remove":
            _, target, timeout, fut = msg
            from riak_ensemble_tpu import root as rootlib
            if target not in self.cluster_state.members:
                fut.resolve(("error", "not_member"))
            else:
                rootlib.remove(self, target, timeout).add_waiter(
                    lambda r: self._after_root_op(r, fut))
        elif kind == "mgr_rcall":
            _, from_, request = msg
            self._handle_rcall(from_, request)
        elif kind == "mgr_reply":
            _, ref, result = msg
            fut = self._pending_calls.pop(ref, None)
            if fut is not None:
                fut.resolve(result)
        elif kind == "peer_pid":
            _, key, addr = msg
            self.remote_peers[key] = addr
        elif kind == "request_peer_pid":
            _, from_name, key = msg
            ensemble, peer_id = key
            if peer_id.node == self.node:
                addr = self.get_peer_addr(ensemble, peer_id)
                if addr is not None:
                    self.send(from_name, ("peer_pid", key, addr))

    def _after_root_op(self, result, fut: Future) -> None:
        self._state_changed()
        fut.resolve(result)

    # -- cross-node manager calls --------------------------------------

    def _call_remote(self, node: str, request: Tuple,
                     timeout: float) -> Future:
        self._next_ref += 1
        ref = self._next_ref
        fut = Future()
        self._pending_calls[ref] = fut
        self.send(manager_name(node), ("mgr_rcall", (self.name, ref),
                                       request))
        out = self.runtime.with_timeout(fut, timeout)
        out.add_waiter(lambda _v: self._pending_calls.pop(ref, None))
        return out

    def _handle_rcall(self, from_: Tuple, request: Tuple) -> None:
        owner, ref = from_
        if request[0] == "get_cluster_state":
            self.send(owner, ("mgr_reply", ref, ("ok", self.cluster_state)))
        else:
            self.send(owner, ("mgr_reply", ref, ("error", "bad_call")))

    def _do_join(self, other_node: str, timeout: float, fut: Future):
        """Task: pull remote CS, validate, adopt, then root join
        (manager.erl:311-334)."""
        from riak_ensemble_tpu import root as rootlib
        if other_node == self.node:
            fut.resolve(("error", "same_node"))
            return
        reply = yield self._call_remote(other_node, ("get_cluster_state",),
                                        min(timeout, 60.0))
        if not (isinstance(reply, tuple) and reply[0] == "ok"):
            fut.resolve(("error", "timeout"))
            return
        remote_cs: ClusterState = reply[1]
        allowed = self._join_allowed(self.cluster_state, remote_cs)
        if allowed is not True:
            fut.resolve(("error", allowed))
            return
        self._save_state(remote_cs)
        self._state_changed()
        result = yield rootlib.join(self, other_node, self.node, timeout)
        self._state_changed()
        fut.resolve(result)

    @staticmethod
    def _join_allowed(local_cs: ClusterState, remote_cs: ClusterState):
        """manager.erl:518-532."""
        if not remote_cs.enabled:
            return "remote_not_enabled"
        if local_cs.enabled and local_cs.id != remote_cs.id:
            return "already_enabled"
        return True

    # ------------------------------------------------------------------
    # gossip + reconciliation

    def _tick(self) -> None:
        self._request_remote_peers()
        self._send_gossip()
        self._tick_timer = self.send_after(TICK, ("tick",))

    def _send_gossip(self) -> None:
        cs = self.cluster_state
        others = [n for n in cs.members if n != self.node]
        self.runtime.rng.shuffle(others)
        for node in others[:GOSSIP_FANOUT]:
            self.send(manager_name(node), ("gossip", cs))

    def _merge_gossip(self, other_cs: ClusterState) -> None:
        merged = statelib.merge(self.cluster_state, other_cs)
        self._save_state(merged)
        self._state_changed()

    def _request_remote_peers(self) -> None:
        """manager.erl:643-666: ask peers' home nodes for addresses of
        wanted-but-unknown remote peers (incl. the root leader)."""
        wanted = set(self._wanted_remote_peers())
        rl = self.get_leader(ROOT)
        if rl is not None and rl.node != self.node:
            wanted.add((ROOT, rl))
        for key in wanted:
            if key in self.remote_peers:
                continue
            ensemble, peer_id = key
            self.send(manager_name(peer_id.node),
                      ("request_peer_pid", self.name, key))

    def _wanted_remote_peers(self) -> List[Tuple[Any, PeerId]]:
        """manager.erl:657-666: for each ensemble with a local member,
        every non-local member's address is wanted."""
        cs = self.cluster_state
        out = []
        for ensemble, info in cs.ensembles.items():
            members = self._all_members(ensemble, info.views)
            if not any(p.node == self.node for p in members):
                continue
            out.extend((ensemble, p) for p in members
                       if p.node != self.node)
        return out

    def _all_members(self, ensemble, views) -> Tuple[PeerId, ...]:
        """compute_all_members (manager.erl:605-614): pending views
        count — peers must exist before a membership change lands."""
        pending = self.cluster_state.pending.get(ensemble)
        all_views = list(pending[1]) + list(views) if pending else views
        seen: Dict[PeerId, None] = {}
        for view in all_views:
            for p in view:
                seen[p] = None
        return tuple(seen)

    # -- persistence ----------------------------------------------------

    def _reload_state(self) -> ClusterState:
        saved = self.storage.get("manager")
        if isinstance(saved, ClusterState):
            return saved
        cluster_id = (self.node, self.runtime.rng.random())
        return statelib.new_state(cluster_id)

    def _save_state(self, cs: ClusterState) -> None:
        if cs != self.cluster_state:
            # Intentionally no sync (manager.erl:557-567).
            self.storage.put("manager", cs)
        self.cluster_state = cs

    # -- state_changed (manager.erl:610-641) ----------------------------

    def _state_changed(self) -> None:
        cs = self.cluster_state
        self.ensemble_data = {
            ens: (info.leader, (info.vsn, info.views),
                  cs.pending.get(ens))
            for ens, info in cs.ensembles.items()
        }
        # check_peers: start wanted-but-missing, stop running-but-unwanted
        wanted = self._wanted_peers()
        running = set(self.local_peers)
        for key in running - set(wanted):
            self._stop_peer(key)
        for key, info in wanted.items():
            if key in running:
                continue
            self._start_peer(key, info)
        # prune dead local registrations
        for key in list(self.local_peers):
            name = peer_name(*key)
            if self.runtime.whereis(name) is None:
                del self.local_peers[key]

    def _wanted_peers(self) -> Dict[Tuple[Any, PeerId], EnsembleInfo]:
        """manager.erl:725-737."""
        cs = self.cluster_state
        out = {}
        for ensemble, info in cs.ensembles.items():
            for p in self._all_members(ensemble, info.views):
                if p.node == self.node:
                    out[(ensemble, p)] = info
        return out

    def _start_peer(self, key: Tuple[Any, PeerId],
                    info: EnsembleInfo) -> None:
        ensemble, peer_id = key
        backend_cls = BACKENDS[info.mod]
        args = tuple(info.args)
        if not args and info.mod == "basic" and self.storage.path:
            # The reference's basic backend derives its save file from
            # the app-env data_root (basic_backend.erl:102-111); mirror
            # that off the node's storage root so peer data survives
            # stop/start cycles (membership removal + re-add).
            import os
            args = (os.path.dirname(os.path.dirname(self.storage.path)),)
        info = __import__("dataclasses").replace(info, args=args)
        probe = backend_cls(ensemble, peer_id, args)
        if not probe.ready_to_start():
            return
        if self.runtime.whereis(peer_name(ensemble, peer_id)) is not None:
            return
        peer = Peer(self.runtime, ensemble, peer_id, self.config, self,
                    self.storage, backend=info.mod,
                    backend_args=tuple(info.args), **self.peer_kw)
        self.local_peers[key] = peer

    def _stop_peer(self, key: Tuple[Any, PeerId]) -> None:
        self.local_peers.pop(key, None)
        ensemble, peer_id = key
        name = peer_name(ensemble, peer_id)
        if self.runtime.whereis(name) is not None:
            self.runtime.stop_actor(name)

    def on_stop(self) -> None:
        self._tick_timer.cancel()
