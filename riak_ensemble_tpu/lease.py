"""Leader lease on a monotonic clock.

Mirrors ``src/riak_ensemble_lease.erl``: the lease records an expiry in
monotonic milliseconds; ``check_lease`` compares against the monotonic
clock (never wall-clock, which can jump — rationale lease.erl:26-50).
The leader refreshes each tick (peer tick chain) and releases on
step-down.

The clock source is injected: virtual runtime clock in simulation, the
C++ ``CLOCK_BOOTTIME`` module (:mod:`riak_ensemble_tpu.utils.clock`) in
production.
"""

from __future__ import annotations

from typing import Callable, Optional


class Lease:
    def __init__(self, clock: Callable[[], float]) -> None:
        self._clock = clock
        self._expiry: Optional[float] = None

    def lease(self, duration: float) -> None:
        """Grant/renew for `duration` seconds (lease.erl:63-67)."""
        self._expiry = self._clock() + duration

    def unlease(self) -> None:
        """Release (lease.erl:69-73)."""
        self._expiry = None

    def check_lease(self, margin: float = 0.0) -> bool:
        """lease.erl:76-88.  ``margin`` is the clock-skew guard the
        lease-protected read fast path subtracts before trusting the
        lease (Config.read_margin — the scalar analog of the batched
        service's vectorized ``lease_until`` check): the lease is
        only trusted while ``clock + margin < expiry``."""
        return self._expiry is not None \
            and self._clock() + margin < self._expiry
