"""Versioned cluster state with newest-wins gossip merge.

Re-implementation of ``src/riak_ensemble_state.erl`` (222 LoC): a
pure-functional record ``#cluster_state{id, enabled, members,
ensembles, pending}`` whose every mutator is vsn-guarded (only a
strictly newer vsn may overwrite — ``newer/2``,
``riak_ensemble_state.erl:213-219``) and whose gossip merge is
field-wise newest-vsn-wins (``merge/2``, ``:171-211``).  This is the
eventually-consistent convergence layer that sits UNDER the strongly
consistent root-ensemble data: the authoritative copy of this state
lives as the ``cluster_state`` key in the root ensemble and is mutated
only through root kmodify operations (:mod:`riak_ensemble_tpu.root`);
manager gossip then spreads it epidemically.

All mutators return the new state, or ``None`` on a vsn conflict (the
reference's ``error``).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, FrozenSet, Optional, Tuple

from riak_ensemble_tpu.types import EnsembleInfo, Views, Vsn

#: The reference's vsn0() (riak_ensemble_state.erl:221-222); `undefined`
#: current-vsns compare as this minimum.
VSN0: Vsn = (-1, 0)


def _newer(cur: Optional[Vsn], new: Optional[Vsn]) -> bool:
    """riak_ensemble_state.erl:213-219 (strictly greater wins)."""
    cur = VSN0 if cur is None else cur
    new = VSN0 if new is None else new
    return new > cur


@dataclass(frozen=True)
class ClusterState:
    """``#cluster_state{}`` (riak_ensemble_state.erl:37-42)."""

    id: Any
    enabled: bool = False
    members_vsn: Vsn = VSN0
    members: FrozenSet[str] = frozenset()
    ensembles: Dict[Any, EnsembleInfo] = field(default_factory=dict)
    pending: Dict[Any, Tuple[Vsn, Views]] = field(default_factory=dict)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, ClusterState):
            return NotImplemented
        return (self.id == other.id and self.enabled == other.enabled
                and self.members_vsn == other.members_vsn
                and self.members == other.members
                and self.ensembles == other.ensembles
                and self.pending == other.pending)

    __hash__ = None  # type: ignore[assignment]


def new_state(cluster_id: Any) -> ClusterState:
    """riak_ensemble_state:new/1."""
    return ClusterState(id=cluster_id)


def enable(cs: ClusterState) -> Optional[ClusterState]:
    """riak_ensemble_state:enable/1 — error if already enabled."""
    if cs.enabled:
        return None
    return replace(cs, enabled=True)


def add_member(vsn: Vsn, node: str, cs: ClusterState
               ) -> Optional[ClusterState]:
    """riak_ensemble_state.erl:93-102."""
    if not _newer(cs.members_vsn, vsn):
        return None
    return replace(cs, members_vsn=vsn, members=cs.members | {node})


def del_member(vsn: Vsn, node: str, cs: ClusterState
               ) -> Optional[ClusterState]:
    """riak_ensemble_state.erl:104-113."""
    if not _newer(cs.members_vsn, vsn):
        return None
    return replace(cs, members_vsn=vsn, members=cs.members - {node})


def set_ensemble(ensemble: Any, info: EnsembleInfo, cs: ClusterState
                 ) -> Optional[ClusterState]:
    """riak_ensemble_state.erl:115-132 — insert or overwrite if newer."""
    cur = cs.ensembles.get(ensemble)
    if not _newer(cur.vsn if cur else None, info.vsn):
        return None
    ensembles = dict(cs.ensembles)
    ensembles[ensemble] = info
    return replace(cs, ensembles=ensembles)


def update_ensemble(vsn: Vsn, ensemble: Any, leader, views: Views,
                    cs: ClusterState) -> Optional[ClusterState]:
    """riak_ensemble_state.erl:134-151 — update leader/views of a KNOWN
    ensemble only (unknown → error)."""
    cur = cs.ensembles.get(ensemble)
    if cur is None or not _newer(cur.vsn, vsn):
        return None
    ensembles = dict(cs.ensembles)
    ensembles[ensemble] = replace(cur, vsn=vsn, leader=leader,
                                  views=tuple(tuple(v) for v in views))
    return replace(cs, ensembles=ensembles)


def set_pending(vsn: Vsn, ensemble: Any, views: Views, cs: ClusterState
                ) -> Optional[ClusterState]:
    """riak_ensemble_state.erl:153-169."""
    cur = cs.pending.get(ensemble)
    if not _newer(cur[0] if cur else None, vsn):
        return None
    pending = dict(cs.pending)
    pending[ensemble] = (vsn, tuple(tuple(v) for v in views))
    return replace(cs, pending=pending)


def merge(a: ClusterState, b: ClusterState) -> ClusterState:
    """Gossip merge (riak_ensemble_state.erl:171-211): ignore foreign
    clusters once enabled; otherwise field-wise newest-vsn-wins."""
    if a.enabled and a.id != b.id:
        return a
    if _newer(a.members_vsn, b.members_vsn):
        members_vsn, members = b.members_vsn, b.members
    else:
        members_vsn, members = a.members_vsn, a.members
    ensembles = dict(a.ensembles)
    for ens, info_b in b.ensembles.items():
        info_a = ensembles.get(ens)
        if info_a is None or _newer(info_a.vsn, info_b.vsn):
            ensembles[ens] = info_b
    pending = dict(a.pending)
    for ens, pb in b.pending.items():
        pa = pending.get(ens)
        if pa is None or _newer(pa[0], pb[0]):
            pending[ens] = pb
    return replace(a, members_vsn=members_vsn, members=members,
                   ensembles=ensembles, pending=pending)
