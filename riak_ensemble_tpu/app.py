"""Application facade: per-node bring-up and the top-level API.

The role of ``riak_ensemble_app.erl`` + ``riak_ensemble_sup.erl``: one
call starts a node's full stack in dependency order — routers, storage,
manager (the reference's rest_for_one order router_sup → storage →
peer_sup → manager, riak_ensemble_sup.erl:48-55; peers are started by
the manager's reconciliation, not statically).

``Node`` bundles the per-node handles and the user-facing operations
(enable/join/remove/create_ensemble/client), so application code reads
like the reference's public API surface:

    runtime = Runtime(seed)
    n0 = start(runtime, "node0", config, data_root="/data/n0")
    n0.enable()
    n1 = start(runtime, "node1", config, data_root="/data/n1")
    n1.join("node0")
    n0.create_ensemble("kv", peers)
    n0.client().kover("kv", key, value)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

from riak_ensemble_tpu.client import Client
from riak_ensemble_tpu.config import Config
from riak_ensemble_tpu.manager import Manager
from riak_ensemble_tpu.runtime import Runtime
from riak_ensemble_tpu.storage import Storage
from riak_ensemble_tpu.types import PeerId


@dataclass
class Node:
    runtime: Runtime
    node: str
    manager: Manager
    storage: Storage

    def enable(self, wait: float = 60.0) -> str:
        """Activate the cluster and wait for the root ensemble to
        elect (callers may join/write immediately after — the
        reference leaves that wait to the caller, we fold it in)."""
        result = self.manager.enable()
        if result != "ok":
            return result

        def root_leading() -> bool:
            peer = self.manager.local_peers.get(
                ("root", PeerId("root", self.node)))
            return peer is not None and peer.fsm_state == "leading"
        if not self.runtime.run_until(root_leading, wait, poll=0.05):
            return "timeout"
        return "ok"

    def join(self, other_node: str, timeout: float = 60.0):
        return self.runtime.await_future(
            self.manager.join_async(other_node, timeout), timeout + 5.0)

    def remove(self, target_node: str, timeout: float = 60.0):
        return self.runtime.await_future(
            self.manager.remove_async(target_node, timeout), timeout + 5.0)

    def create_ensemble(self, ensemble: Any, peers: Sequence[PeerId],
                        mod: str = "basic", args=(), timeout: float = 30.0):
        leader = peers[0] if peers else None
        return self.runtime.await_future(
            self.manager.create_ensemble(ensemble, leader, list(peers),
                                         mod, tuple(args), timeout),
            timeout + 5.0)

    def client(self) -> Client:
        return Client(self.runtime, self.node)

    def enabled(self) -> bool:
        return self.manager.enabled()

    def stop(self) -> None:
        """Stop this node's stack (manager stops; its peers follow on
        the next reconciliation of the surviving nodes)."""
        for key in list(self.manager.local_peers):
            self.manager.stop_peer(*key)
        self.runtime.stop_actor(self.manager.name)
        self.runtime.stop_actor(self.storage.name)


def start(runtime: Runtime, node: str, config: Optional[Config] = None,
          data_root: Optional[str] = None, **peer_kw) -> Node:
    """Start one node's stack (the app:start / sup tree analog)."""
    config = config if config is not None else Config()
    storage = Storage(runtime, node, config, data_root)
    manager = Manager(runtime, node, config, storage, **peer_kw)
    return Node(runtime=runtime, node=node, manager=manager,
                storage=storage)
