"""Coalesced fact/cluster-state storage, one per node.

Mirrors ``src/riak_ensemble_storage.erl``: a single table + one
``ensemble_facts`` file; puts stage in the table; ``sync`` resolves
once flushed; flush happens ``storage_delay`` after the first dirty
put/sync and at least every ``storage_tick``; unchanged images skip the
write (storage.erl:86-103, 133-137, 176-193).  Rationale: thousands of
independent synchronous writers overwhelmed I/O (storage.erl:21-39).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional

from riak_ensemble_tpu import save
from riak_ensemble_tpu.config import Config
from riak_ensemble_tpu.runtime import Actor, Future, Runtime, Timer


class Storage(Actor):
    def __init__(self, runtime: Runtime, node: str, config: Config,
                 data_root: Optional[str]) -> None:
        super().__init__(runtime, ("storage", node), node)
        self.config = config
        self.path = (os.path.join(data_root, "ensembles", "ensemble_facts")
                     if data_root else None)
        self.table: Dict[Any, Any] = {}
        self.waiting: List[Future] = []
        self.timer: Optional[Timer] = None
        self.previous: Optional[bytes] = None
        if self.path:
            raw = save.read(self.path)
            if raw is not None:
                self.table = pickle.loads(raw)
        self.send_after(self.config.storage_tick, ("storage_tick",))

    # Direct-call API (the ETS is public in the reference; actors on
    # the same node call these synchronously).

    def put(self, key: Any, value: Any) -> None:
        self.table[key] = value

    def get(self, key: Any) -> Any:
        return self.table.get(key)

    def sync(self) -> Future:
        """Future resolves once staged puts hit disk."""
        fut = Future()
        self.waiting.append(fut)
        self._maybe_schedule_sync()
        return fut

    # -- internals ---------------------------------------------------------

    def _maybe_schedule_sync(self) -> None:
        if self.timer is None:
            self.timer = self.send_after(self.config.storage_delay,
                                         ("do_sync",))

    def handle(self, msg) -> None:
        kind = msg[0]
        if kind == "storage_tick":
            self._maybe_schedule_sync()
            self.send_after(self.config.storage_tick, ("storage_tick",))
        elif kind == "do_sync":
            self.timer = None
            self._do_sync()

    def _do_sync(self) -> None:
        data = pickle.dumps(self.table)
        if data != self.previous and self.path:
            save.write(self.path, data)
        self.previous = data
        waiting, self.waiting = self.waiting, []
        for fut in waiting:
            fut.resolve("ok")
