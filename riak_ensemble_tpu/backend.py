"""Pluggable peer storage behaviour + reference in-memory/file backend.

Mirrors ``src/riak_ensemble_backend.erl`` (15-callback behaviour,
:51-108) and ``src/riak_ensemble_basic_backend.erl``.  The backend owns
the K/V object representation and **replies directly to the original
caller** — the reference's reply-chain optimization
(``riak_ensemble_backend:reply/2``, backend.erl:145-151;
``doc/Readme.md:454-459``): replies skip the peer FSM and resolve the
waiting worker's future straight from the backend.
"""

from __future__ import annotations

import os
import pickle
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

from riak_ensemble_tpu.types import NOTFOUND, Obj
from riak_ensemble_tpu.runtime import Future


#: `from` for backend replies: (sink, peer_id) where sink is a Future,
#: a callable, or None (discard).  Resolving it is the moral equivalent
#: of `To ! {Tag, Reply}` — the reply skips the peer FSM entirely.
From = Tuple[Any, Any]


def reply(from_: From, value: Any) -> None:
    """backend.erl:145-151."""
    sink, _id = from_
    if sink is None:
        return
    if isinstance(sink, Future):
        sink.resolve(value)
    else:
        sink(value)


class Backend:
    """Behaviour contract (riak_ensemble_backend.erl:51-108).

    Subclasses may use any object representation by overriding the
    obj_* accessors; the framework default is
    :class:`riak_ensemble_tpu.types.Obj`.
    """

    # -- lifecycle ---------------------------------------------------------

    def __init__(self, ensemble: Any, peer_id: Any, args: Tuple) -> None:
        self.ensemble = ensemble
        self.peer_id = peer_id
        self.args = args

    # -- object representation --------------------------------------------

    def new_obj(self, epoch: int, seq: int, key: Any, value: Any) -> Obj:
        return Obj(epoch=epoch, seq=seq, key=key, value=value)

    def obj_epoch(self, obj: Obj) -> int:
        return obj.epoch

    def obj_seq(self, obj: Obj) -> int:
        return obj.seq

    def obj_key(self, obj: Obj) -> Any:
        return obj.key

    def obj_value(self, obj: Obj) -> Any:
        return obj.value

    def set_obj_epoch(self, epoch: int, obj: Obj) -> Obj:
        return Obj(epoch=epoch, seq=obj.seq, key=obj.key, value=obj.value)

    def set_obj_seq(self, seq: int, obj: Obj) -> Obj:
        return Obj(epoch=obj.epoch, seq=seq, key=obj.key, value=obj.value)

    def set_obj_value(self, value: Any, obj: Obj) -> Obj:
        return Obj(epoch=obj.epoch, seq=obj.seq, key=obj.key, value=value)

    def latest_obj(self, a: Obj, b: Obj) -> Obj:
        """Newer of two objects by (epoch, seq) (backend.erl:132-143)."""
        va = (self.obj_epoch(a), self.obj_seq(a))
        vb = (self.obj_epoch(b), self.obj_seq(b))
        return b if vb > va else a

    # -- storage ops (must reply via `reply(from_, ...)`) -----------------

    def get(self, key: Any, from_: From) -> None:  # pragma: no cover
        raise NotImplementedError

    def put(self, key: Any, obj: Obj, from_: From) -> None:  # pragma: no cover
        raise NotImplementedError

    # -- housekeeping ------------------------------------------------------

    def tick(self, epoch: int, seq: int, leader: Any, views: Any) -> None:
        """Periodic leader housekeeping (backend.erl:77-79)."""

    def ping(self, peer) -> str:
        """Health check: 'ok' | 'async' | 'failed' (backend.erl:81-83).
        If 'async', backend must eventually call peer.backend_pong()."""
        return "ok"

    def ready_to_start(self) -> bool:
        return True

    def synctree_path(self, ensemble: Any, peer_id: Any):
        """None = default per-peer tree; or (tree_id, path) for shared
        trees (backend.erl:97-108)."""
        return None

    def monitored(self) -> Tuple[Any, ...]:
        """Actor names the owning peer should monitor on the backend's
        behalf at startup (the backend's helper processes; the peer
        passes their DOWN signals to :meth:`handle_down`, matching the
        monitor-then-callback flow of peer.erl:1919-1929).  Backends
        that spawn helpers later use ``peer.monitor_backend(name)``."""
        return ()

    def handle_down(self, ref: Any, pid: Any, reason: Any):
        """React to a DOWN signal for a monitored process
        (backend.erl:84-93): False = not mine / ignore; ('ok',) =
        handled, keep going; ('reset',) = storage lost — the peer must
        step down and re-probe (module_handle_down,
        peer.erl:1937-1948)."""
        return False


class BasicBackend(Backend):
    """In-memory dict + synchronous CRC-checked whole-image file write
    on every put (``riak_ensemble_basic_backend.erl:120-187``).  Used by
    the root ensemble.

    ``data_root=None`` keeps it memory-only (unit tests).
    """

    def __init__(self, ensemble, peer_id, args=()) -> None:
        super().__init__(ensemble, peer_id, args)
        data_root = args[0] if args else None
        self.path: Optional[str] = None
        if data_root is not None:
            fname = f"kv_{abs(hash((repr(ensemble), repr(peer_id)))):x}"
            self.path = os.path.join(data_root, "ensembles", fname)
        self.data: Dict[Any, Obj] = self._load()

    def _load(self) -> Dict[Any, Obj]:
        """CRC-checked reload (basic_backend.erl:151-179)."""
        if self.path is None or not os.path.exists(self.path):
            return {}
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
            crc = int.from_bytes(raw[:4], "big")
            blob = raw[4:]
            if zlib.crc32(blob) & 0xFFFFFFFF != crc:
                return {}
            return pickle.loads(blob)
        except Exception:
            return {}

    def _save(self) -> None:
        """Synchronous whole-image save (basic_backend.erl:181-187)."""
        if self.path is None:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        blob = pickle.dumps(self.data)
        crc = (zlib.crc32(blob) & 0xFFFFFFFF).to_bytes(4, "big")
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(crc + blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def get(self, key, from_) -> None:
        reply(from_, self.data.get(key, NOTFOUND))

    def put(self, key, obj, from_) -> None:
        self.data[key] = obj
        self._save()
        reply(from_, obj)


BACKENDS: Dict[str, Callable] = {"basic": BasicBackend}


def register_backend(name: str, cls: Callable) -> None:
    BACKENDS[name] = cls
